from cocoa_tpu.cli import main

raise SystemExit(main())
