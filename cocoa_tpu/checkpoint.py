"""Resumable training checkpoints.

The reference's checkpointing is Spark lineage truncation only — it cannot
restart a killed job (SURVEY.md §5).  This is the strictly-more-capable TPU
equivalent: a round-stamped device→host save of the full optimizer state
(w, per-shard alpha, round, rng seed), restorable into a fresh process.

Plain ``.npz`` + a JSON sidecar is deliberate: the state is two arrays and
three scalars; orbax would be justified the day state becomes a nested
pytree across hosts.

Failure hardening (docs/DESIGN.md §13): the writer keeps the last
:data:`KEEP_GENERATIONS` round-stamped checkpoints per algorithm (older
generations are pruned — a long run must not grow its directory without
bound, and one healthy predecessor is the torn-file fallback);
:func:`latest` VALIDATES each generation on discovery — npz readable,
meta parses, array shapes match the shapes the meta records — and falls
back to the previous generation when the newest is torn or corrupt,
emitting a typed ``checkpoint_corrupt`` event.  The atomic-rename write
protocol already makes a mid-save kill safe; validation covers what the
protocol cannot: disk-level corruption, a torn copy from remote storage,
or a file damaged after it landed.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Optional

import jax
import numpy as np

# round-stamped generations kept per algorithm; the newest is the resume
# point, the one before it the fallback when the newest fails validation
KEEP_GENERATIONS = 2

_STAMP = r"-r(\d+)\.npz$"


def save(
    directory: str,
    algorithm: str,
    round_t: int,
    w: jax.Array,
    alpha: Optional[jax.Array] = None,
    seed: int = 0,
    sched: Optional[jax.Array] = None,
    hist: Optional[jax.Array] = None,
    gap: Optional[float] = None,
    tenant_gaps=None,
    tenant_cert_ts=None,
) -> str:
    """Write checkpoint for ``round_t``; returns the file path.

    ``gap`` is the last certified duality gap the run observed before
    this save (None outside the gap-target paths).  It rides the meta so
    a DOWNSTREAM consumer — the serving hot-swap watcher
    (cocoa_tpu/serving/) — can report what certificate the model it is
    about to serve carries, and how stale it is: the paper's primal-dual
    certificate doubles as the deployed model's freshness measure
    (docs/DESIGN.md §17 "gap age").

    ``sched`` is the σ′-schedule / watch state of a ``--sigmaSchedule``
    run (solvers/base.py SCHED layout, a tiny float32 vector; ``--accel``
    runs extend it with the momentum/Θ slots — same layout note).  It
    rides the meta JSON rather than the array set: every float32 is
    exactly representable as a JSON double, so the round trip is
    bit-identical — which is what makes a mid-schedule ``--resume``
    reproduce the uninterrupted trajectory — and old checkpoints/readers
    stay valid.

    ``hist`` is the ``--accel`` secant window bank (a (2, K, n_shard)
    dual-history leaf — the two previous eval-boundary α snapshots): it
    joins the ``.npz`` array set so an accelerated run's mid-momentum
    resume is bit-identical too.

    ``tenant_gaps`` / ``tenant_cert_ts`` are the per-tenant
    certification metadata of a stacked ``(T, d)`` catalogue: one
    certified duality gap and one certification wall-clock timestamp
    per tenant row (docs/DESIGN.md §22).  They ride the meta JSON like
    ``sched`` (floats round-trip exactly), so the serving side can
    export a ``tenant=``-labeled gap-age series without touching the
    array set; single-model checkpoints simply omit them.

    Crash-safe: both files are written to temp names and renamed in, the
    ``.npz`` LAST — :func:`latest` discovers checkpoints by the ``.npz``,
    so a process killed mid-save (the exact scenario checkpoints exist
    for) can never leave a discoverable-but-corrupt checkpoint: either
    the rename happened and both files are complete, or the checkpoint
    does not exist."""
    from cocoa_tpu.telemetry import tracing as _tracing

    with _tracing.span("checkpoint_save", algorithm=algorithm,
                       round=int(round_t)):
        return _save(directory, algorithm, round_t, w, alpha=alpha,
                     seed=seed, sched=sched, hist=hist, gap=gap,
                     tenant_gaps=tenant_gaps,
                     tenant_cert_ts=tenant_cert_ts)


def _save(directory, algorithm, round_t, w, alpha=None, seed=0,
          sched=None, hist=None, gap=None, tenant_gaps=None,
          tenant_cert_ts=None) -> str:
    os.makedirs(directory, exist_ok=True)
    algorithm = algorithm.replace(" ", "_")
    path = os.path.join(directory, f"{algorithm}-r{round_t:06d}.npz")
    meta = {"algorithm": algorithm, "round": round_t, "seed": seed}
    if gap is not None:
        meta["gap"] = float(gap)
    if tenant_gaps is not None or tenant_cert_ts is not None:
        # per-tenant certification metadata of a stacked catalogue:
        # both lists or neither, and each must cover every tenant row —
        # a partial list would silently mislabel the gap-age series
        n_rows = int(np.shape(w)[0]) if len(np.shape(w)) == 2 else None
        if n_rows is None:
            raise ValueError(
                "tenant_gaps/tenant_cert_ts only ride a stacked (T, d) "
                f"catalogue checkpoint — w has shape {np.shape(w)}")
        for name, vals in (("tenant_gaps", tenant_gaps),
                           ("tenant_cert_ts", tenant_cert_ts)):
            if vals is None or len(vals) != n_rows:
                raise ValueError(
                    f"{name} must carry one entry per tenant row: got "
                    f"{None if vals is None else len(vals)} entries "
                    f"for a {n_rows}-tenant catalogue")
        meta["tenant_gaps"] = [float(v) for v in tenant_gaps]
        meta["tenant_cert_ts"] = [float(v) for v in tenant_cert_ts]
    # array shapes recorded in the meta give :func:`validate` a
    # self-contained integrity check: a torn or bit-rotted archive whose
    # zip structure still opens is caught by the shape (or the member
    # decompression) disagreeing with what the writer recorded
    shapes = {"w": list(np.shape(w))}
    if sched is not None:
        # float32 -> python float is exact; json.dump emits Infinity for
        # the watch's untouched best-gap slots (python json reads it back)
        meta["sched"] = [float(v) for v in
                         np.asarray(sched, dtype=np.float32)]
    if (isinstance(alpha, jax.Array) and not alpha.is_fully_addressable):
        # multi-host run: each process holds only its dp shards of alpha.
        # Gather the full array on every host so each writes a complete,
        # independently-restorable checkpoint (the elastic supervisor
        # restarts the whole gang from ONE file; per-shard files would
        # couple restore to the old process layout).  Alpha is (K, n_shard)
        # — MBs, not model-scale — so the allgather is cheap.
        from jax.experimental import multihost_utils

        alpha = multihost_utils.process_allgather(alpha, tiled=True)
    if alpha is not None:
        shapes["alpha"] = list(np.shape(alpha))
    if hist is not None:
        shapes["hist"] = list(np.shape(hist))
    meta["shapes"] = shapes
    # meta travels INSIDE the .npz (a unicode array — no pickling), so the
    # archive is self-describing and a stale same-named .npz from an
    # earlier run in a reused directory can never be paired with a fresh
    # sidecar; the sidecar is written too, but only for human inspection
    # and as a fallback for pre-meta checkpoints.
    arrays = {"w": np.asarray(w), "_meta": np.array(json.dumps(meta))}
    if alpha is not None:
        arrays["alpha"] = np.asarray(alpha)
    if hist is not None:
        arrays["hist"] = np.asarray(hist)
    pid = os.getpid()
    tmp = f"{path}.tmp.{pid}"
    with open(tmp, "wb") as f:  # explicit handle: savez must not append .npz
        np.savez(f, **arrays)
    with open(f"{path}.json.tmp.{pid}", "w") as f:
        json.dump(meta, f)
    os.replace(f"{path}.json.tmp.{pid}", path + ".json")
    os.replace(tmp, path)
    # sweep temp litter from earlier interrupted saves of this algorithm
    # (preempted jobs otherwise accumulate *.tmp.<pid> files forever).
    # Current-round temps are left alone: in a multi-host run every process
    # saves the same round concurrently (the per-round collectives keep
    # them in lockstep), and unlinking a peer's in-flight temp would make
    # its os.replace fail.
    for name in os.listdir(directory):
        if (name.startswith(f"{algorithm}-") and ".tmp." in name
                and f"r{round_t:06d}" not in name):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass
    # generation pruning: keep the newest KEEP_GENERATIONS round-stamped
    # checkpoints of this algorithm (+ sidecars), drop the rest — bounded
    # disk for long runs, one predecessor retained as the corruption
    # fallback.  Only rounds <= the round just written are candidates: a
    # reused directory can hold HIGHER-round leftovers from an earlier
    # run, and pruning against those would delete the file this save
    # just produced (stale files stay exactly as benign/visible as they
    # were before pruning existed).  Multi-host peers prune the same set
    # concurrently; a peer winning the unlink race is fine (OSError pass).
    stamp = re.compile(re.escape(algorithm) + _STAMP)
    ours = [p for p in generations(directory, algorithm)
            if int(stamp.search(p).group(1)) <= round_t]
    for old in ours[:-KEEP_GENERATIONS]:
        for victim in (old, old + ".json"):
            try:
                os.unlink(victim)
            except OSError:
                pass
    # every save flows through here (all drive* paths), so this is the one
    # emission point for the checkpoint_write event — what the elastic
    # supervisor's progress watch and external monitors key on
    from cocoa_tpu.telemetry import events as _tele

    _tele.get_bus().emit("checkpoint_write", algorithm=algorithm,
                         round=int(round_t), path=path)
    return path


def generations(directory: str, algorithm: str) -> list:
    """Round-stamped checkpoint paths for ``algorithm``, oldest → newest
    (no validation — :func:`latest` is the validating reader).  The exact
    ``<algorithm>-r<round>.npz`` stamp is matched, so ``CoCoA`` never
    claims ``CoCoA+``'s files (the ADVICE-r5 prefix trap)."""
    if not os.path.isdir(directory):
        return []
    algorithm = algorithm.replace(" ", "_")
    pat = re.compile(re.escape(algorithm) + _STAMP)
    stamped = [(m, f) for f in os.listdir(directory)
               if f.startswith(f"{algorithm}-r") and (m := pat.search(f))]
    # NUMERIC round order: past round 999999 the 06d stamp widens and a
    # lexicographic sort would rank r1000000 before r999999 — with
    # KEEP_GENERATIONS pruning that would delete the newest file on
    # every save thereafter, not just mis-order latest()
    stamped.sort(key=lambda mf: (int(mf[0].group(1)), mf[1]))
    return [os.path.join(directory, f) for _, f in stamped]


# PASSED validations, keyed (path) -> (mtime_ns, size).  The serving
# hot-swap watcher polls :func:`latest` every few hundred ms; without
# the cache every poll re-decompresses every npz member (the CRC check)
# of every retained generation — ~ms of CPU per poll per generation for
# a model that has not changed.  A hit costs one os.stat.  Only PASSES
# are cached: a failed generation may legitimately be replaced in place
# by a healthy rewrite, and the atomic-rename write protocol means a
# path whose (mtime, size) is unchanged cannot have changed content
# out from under a recorded pass — while a REWRITTEN-in-place file
# (same path, new mtime/size) misses the cache and re-validates, which
# tests/test_serving.py pins.
_VALIDATED = {}
_VALIDATED_CAP = 64   # a serving dir holds KEEP_GENERATIONS files per
                      # algorithm; the cap only matters for long-lived
                      # processes sweeping many directories (tests)


def _stat_key(path: str):
    try:
        st = os.stat(path)
    except OSError:
        return None
    # st_ino joins mtime+size: an atomic-rename rewrite always lands a
    # fresh inode, so even a filesystem whose mtime ticks are coarser
    # than the rewrite (1s network FS stamps) cannot alias a cached
    # pass onto bytes the cache never saw
    return (st.st_ino, st.st_mtime_ns, st.st_size)


def validate(path: str) -> Optional[str]:
    """None when the checkpoint at ``path`` is healthy, else a reason
    string.  Healthy = the npz opens, every array member decompresses
    (zip CRC — catches torn/overwritten bytes), the meta parses, and each
    array shape matches the shape the meta recorded at write time
    (pre-``shapes`` checkpoints skip that last comparison).

    Passed validations are cached on (path, mtime, size) so a poll-rate
    reader (the serving swap watcher) pays one stat, not a full
    decompression, for an unchanged generation."""
    from cocoa_tpu.telemetry import tracing as _tracing

    key = _stat_key(path)
    if key is not None and _VALIDATED.get(path) == key:
        return None
    with _tracing.span("checkpoint_validate", path=path):
        reason = _validate(path)
    if reason is None and key is not None and key == _stat_key(path):
        # only record a pass whose file is provably the one we read: an
        # in-place rewrite DURING validation changes the stat key, and
        # caching the pre-read key would bless bytes we never saw
        if len(_VALIDATED) >= _VALIDATED_CAP:
            _VALIDATED.pop(next(iter(_VALIDATED)))
        _VALIDATED[path] = key
    return reason


def _validate(path: str) -> Optional[str]:
    try:
        data = np.load(path)
    except Exception as e:
        return f"unreadable npz ({type(e).__name__}: {e})"
    if not hasattr(data, "files"):
        # np.load happily returns a bare ndarray for .npy bytes — a
        # stray/overwritten file, not a checkpoint archive (and it has
        # no close(), so it must never reach the finally below)
        return "not an npz archive"
    try:
        if "_meta" in data.files:
            meta = json.loads(str(data["_meta"]))
        else:
            with open(path + ".json") as f:
                meta = json.load(f)
        if not isinstance(meta.get("round"), int):
            return "meta carries no integer 'round'"
        arrays = {name: data[name] for name in data.files
                  if name != "_meta"}  # full decompression = CRC check
        if "w" not in arrays:
            return "archive carries no 'w' array"
        for name, shape in (meta.get("shapes") or {}).items():
            if name not in arrays:
                return f"array {name!r} recorded in meta is missing"
            if list(arrays[name].shape) != list(shape):
                return (f"array {name!r} has shape "
                        f"{list(arrays[name].shape)}, meta recorded "
                        f"{list(shape)}")
    except Exception as e:
        return f"corrupt ({type(e).__name__}: {e})"
    finally:
        data.close()
    return None


def latest(directory: str, algorithm: str) -> Optional[str]:
    """Most recent HEALTHY checkpoint path for ``algorithm``, or None.

    Each retained generation is validated newest-first
    (:func:`validate`); a torn or corrupt one is skipped — with a typed
    ``checkpoint_corrupt`` event and a stderr note — and the reader falls
    back to the previous generation.  The cost of that fallback is
    bounded by the checkpoint cadence, exactly like the cost of a crash;
    the alternative (resuming round 1, or crashing on a half-written
    file) is what this guards against."""
    for path in reversed(generations(directory, algorithm)):
        reason = validate(path)
        if reason is None:
            return path
        from cocoa_tpu.telemetry import events as _tele

        _tele.get_bus().emit(
            "checkpoint_corrupt", algorithm=algorithm.replace(" ", "_"),
            path=path, reason=reason)
        print(f"checkpoint: {path} failed validation ({reason}); "
              f"falling back to the previous generation",
              file=sys.stderr, flush=True)
    return None


def load(path: str):
    """Returns (meta dict, w, alpha-or-None) as host numpy arrays.  Meta
    comes from inside the archive (self-describing — see :func:`save`);
    the sidecar is only a fallback for pre-meta checkpoints."""
    meta, arrays = load_full(path)
    return meta, arrays["w"], arrays.get("alpha")


def load_full(path: str):
    """Returns (meta dict, {array name: host ndarray}) — everything the
    checkpoint carries, including the ``--accel`` dual-history leaf
    ``hist`` when present.  :func:`load` keeps the legacy 3-tuple
    shape."""
    data = np.load(path)
    if "_meta" in data.files:
        meta = json.loads(str(data["_meta"]))
    else:
        with open(path + ".json") as f:
            meta = json.load(f)
    return meta, {name: data[name] for name in data.files
                  if name != "_meta"}
