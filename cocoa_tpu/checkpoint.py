"""Resumable training checkpoints.

The reference's checkpointing is Spark lineage truncation only — it cannot
restart a killed job (SURVEY.md §5).  This is the strictly-more-capable TPU
equivalent: a round-stamped device→host save of the full optimizer state
(w, per-shard alpha, round, rng seed), restorable into a fresh process.

Plain ``.npz`` + a JSON sidecar is deliberate: the state is two arrays and
three scalars; orbax would be justified the day state becomes a nested
pytree across hosts.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import numpy as np


def save(
    directory: str,
    algorithm: str,
    round_t: int,
    w: jax.Array,
    alpha: Optional[jax.Array] = None,
    seed: int = 0,
) -> str:
    """Write checkpoint for ``round_t``; returns the file path.

    Crash-safe: both files are written to temp names and renamed in, the
    ``.npz`` LAST — :func:`latest` discovers checkpoints by the ``.npz``,
    so a process killed mid-save (the exact scenario checkpoints exist
    for) can never leave a discoverable-but-corrupt checkpoint: either
    the rename happened and both files are complete, or the checkpoint
    does not exist."""
    os.makedirs(directory, exist_ok=True)
    algorithm = algorithm.replace(" ", "_")
    path = os.path.join(directory, f"{algorithm}-r{round_t:06d}.npz")
    arrays = {"w": np.asarray(w)}
    if alpha is not None:
        arrays["alpha"] = np.asarray(alpha)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:  # explicit handle: savez must not append .npz
        np.savez(f, **arrays)
    meta = {"algorithm": algorithm, "round": round_t, "seed": seed}
    with open(path + ".json.tmp", "w") as f:
        json.dump(meta, f)
    os.replace(path + ".json.tmp", path + ".json")
    os.replace(tmp, path)
    return path


def latest(directory: str, algorithm: str) -> Optional[str]:
    """Most recent checkpoint path for ``algorithm``, or None."""
    if not os.path.isdir(directory):
        return None
    algorithm = algorithm.replace(" ", "_")
    files = sorted(
        f for f in os.listdir(directory)
        if f.startswith(f"{algorithm}-r") and f.endswith(".npz")
    )
    return os.path.join(directory, files[-1]) if files else None


def load(path: str):
    """Returns (meta dict, w, alpha-or-None) as host numpy arrays."""
    with open(path + ".json") as f:
        meta = json.load(f)
    data = np.load(path)
    return meta, data["w"], (data["alpha"] if "alpha" in data.files else None)
