"""Resumable training checkpoints.

The reference's checkpointing is Spark lineage truncation only — it cannot
restart a killed job (SURVEY.md §5).  This is the strictly-more-capable TPU
equivalent: a round-stamped device→host save of the full optimizer state
(w, per-shard alpha, round, rng seed), restorable into a fresh process.

Plain ``.npz`` + a JSON sidecar is deliberate: the state is two arrays and
three scalars; orbax would be justified the day state becomes a nested
pytree across hosts.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import numpy as np


def save(
    directory: str,
    algorithm: str,
    round_t: int,
    w: jax.Array,
    alpha: Optional[jax.Array] = None,
    seed: int = 0,
    sched: Optional[jax.Array] = None,
    hist: Optional[jax.Array] = None,
) -> str:
    """Write checkpoint for ``round_t``; returns the file path.

    ``sched`` is the σ′-schedule / watch state of a ``--sigmaSchedule``
    run (solvers/base.py SCHED layout, a tiny float32 vector; ``--accel``
    runs extend it with the momentum/Θ slots — same layout note).  It
    rides the meta JSON rather than the array set: every float32 is
    exactly representable as a JSON double, so the round trip is
    bit-identical — which is what makes a mid-schedule ``--resume``
    reproduce the uninterrupted trajectory — and old checkpoints/readers
    stay valid.

    ``hist`` is the ``--accel`` secant window bank (a (2, K, n_shard)
    dual-history leaf — the two previous eval-boundary α snapshots): it
    joins the ``.npz`` array set so an accelerated run's mid-momentum
    resume is bit-identical too.

    Crash-safe: both files are written to temp names and renamed in, the
    ``.npz`` LAST — :func:`latest` discovers checkpoints by the ``.npz``,
    so a process killed mid-save (the exact scenario checkpoints exist
    for) can never leave a discoverable-but-corrupt checkpoint: either
    the rename happened and both files are complete, or the checkpoint
    does not exist."""
    os.makedirs(directory, exist_ok=True)
    algorithm = algorithm.replace(" ", "_")
    path = os.path.join(directory, f"{algorithm}-r{round_t:06d}.npz")
    meta = {"algorithm": algorithm, "round": round_t, "seed": seed}
    if sched is not None:
        # float32 -> python float is exact; json.dump emits Infinity for
        # the watch's untouched best-gap slots (python json reads it back)
        meta["sched"] = [float(v) for v in
                         np.asarray(sched, dtype=np.float32)]
    if (isinstance(alpha, jax.Array) and not alpha.is_fully_addressable):
        # multi-host run: each process holds only its dp shards of alpha.
        # Gather the full array on every host so each writes a complete,
        # independently-restorable checkpoint (the elastic supervisor
        # restarts the whole gang from ONE file; per-shard files would
        # couple restore to the old process layout).  Alpha is (K, n_shard)
        # — MBs, not model-scale — so the allgather is cheap.
        from jax.experimental import multihost_utils

        alpha = multihost_utils.process_allgather(alpha, tiled=True)
    # meta travels INSIDE the .npz (a unicode array — no pickling), so the
    # archive is self-describing and a stale same-named .npz from an
    # earlier run in a reused directory can never be paired with a fresh
    # sidecar; the sidecar is written too, but only for human inspection
    # and as a fallback for pre-meta checkpoints.
    arrays = {"w": np.asarray(w), "_meta": np.array(json.dumps(meta))}
    if alpha is not None:
        arrays["alpha"] = np.asarray(alpha)
    if hist is not None:
        arrays["hist"] = np.asarray(hist)
    pid = os.getpid()
    tmp = f"{path}.tmp.{pid}"
    with open(tmp, "wb") as f:  # explicit handle: savez must not append .npz
        np.savez(f, **arrays)
    with open(f"{path}.json.tmp.{pid}", "w") as f:
        json.dump(meta, f)
    os.replace(f"{path}.json.tmp.{pid}", path + ".json")
    os.replace(tmp, path)
    # sweep temp litter from earlier interrupted saves of this algorithm
    # (preempted jobs otherwise accumulate *.tmp.<pid> files forever).
    # Current-round temps are left alone: in a multi-host run every process
    # saves the same round concurrently (the per-round collectives keep
    # them in lockstep), and unlinking a peer's in-flight temp would make
    # its os.replace fail.
    for name in os.listdir(directory):
        if (name.startswith(f"{algorithm}-") and ".tmp." in name
                and f"r{round_t:06d}" not in name):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass
    # every save flows through here (all drive* paths), so this is the one
    # emission point for the checkpoint_write event — what the elastic
    # supervisor's progress watch and external monitors key on
    from cocoa_tpu.telemetry import events as _tele

    _tele.get_bus().emit("checkpoint_write", algorithm=algorithm,
                         round=int(round_t), path=path)
    return path


def latest(directory: str, algorithm: str) -> Optional[str]:
    """Most recent checkpoint path for ``algorithm``, or None."""
    if not os.path.isdir(directory):
        return None
    algorithm = algorithm.replace(" ", "_")
    files = sorted(
        f for f in os.listdir(directory)
        if f.startswith(f"{algorithm}-r") and f.endswith(".npz")
    )
    return os.path.join(directory, files[-1]) if files else None


def load(path: str):
    """Returns (meta dict, w, alpha-or-None) as host numpy arrays.  Meta
    comes from inside the archive (self-describing — see :func:`save`);
    the sidecar is only a fallback for pre-meta checkpoints."""
    meta, arrays = load_full(path)
    return meta, arrays["w"], arrays.get("alpha")


def load_full(path: str):
    """Returns (meta dict, {array name: host ndarray}) — everything the
    checkpoint carries, including the ``--accel`` dual-history leaf
    ``hist`` when present.  :func:`load` keeps the legacy 3-tuple
    shape."""
    data = np.load(path)
    if "_meta" in data.files:
        meta = json.loads(str(data["_meta"]))
    else:
        with open(path + ".json") as f:
            meta = json.load(f)
    return meta, {name: data[name] for name in data.files
                  if name != "_meta"}
