"""LIBSVM text ingestion.

TPU-native replacement for the reference's Spark loader
(OptUtils.scala:11-53).  Semantics kept 1:1:

- label token containing ``+`` or parsing to int 1 → +1, anything else → −1
  (OptUtils.scala:35-37; yes, that means "2" silently becomes −1 — documented
  reference quirk #5 in SURVEY.md).
- feature pairs are 1-based ``idx:val`` → 0-based indices
  (OptUtils.scala:40-43).
- ``num_features`` is taken from the caller (the ``--numFeatures`` flag), not
  inferred, matching ``SparseVector(..., numFeats)``.

Instead of an RDD of per-example sparse vectors, the output is a single
columnar CSR triple (row pointers / column indices / values) — the layout
device sharding wants.  A C++ fast path (``native/libsvm_parser.cpp``, loaded
via ctypes) handles large files; the pure-Python path is the fallback and the
semantic oracle.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

# Tokens are delimited by exactly the whitespace set the native parser's
# is_ws() skips (C-locale isspace minus '\n').  NOT str.split(): that also
# splits on Unicode whitespace (NBSP, \x1c-\x1f, \x85) the native scanner
# treats as ordinary junk bytes, which would silently change which pairs a
# line yields depending on which parser ran.
_WS_SPLIT = re.compile(r"[ \t\r\v\f]+")

# Shared numeric grammar, enforced on BOTH parsers: plain ASCII decimal
# (optionally signed, optional fraction/exponent).  Python's int()/float()
# and C's strtol/strtod each accept extras the other rejects (digit-group
# underscores and Unicode digits vs. hex floats, "nan(...)", "inf"); the
# character class below excludes every such form, and within it the two
# accept exactly the same strings, so token validity cannot depend on
# which parser happened to be built.
_INT_CHARS = frozenset("+-0123456789")
_NUM_CHARS = frozenset("+-.eE0123456789")


@dataclasses.dataclass
class LibsvmData:
    """Columnar CSR holding the whole dataset on host.

    ``labels`` ∈ {−1.0, +1.0}; ``indptr`` has n+1 entries; ``indices`` are
    0-based feature ids; ``num_features`` = d.
    """

    labels: np.ndarray     # (n,) float64
    indptr: np.ndarray     # (n+1,) int64
    indices: np.ndarray    # (nnz,) int32
    values: np.ndarray     # (nnz,) float64
    num_features: int

    @property
    def n(self) -> int:
        return self.labels.shape[0]

    def row(self, i: int) -> tuple[np.ndarray, np.ndarray]:
        lo, hi = self.indptr[i], self.indptr[i + 1]
        return self.indices[lo:hi], self.values[lo:hi]

    # jaxlint: allow=f64 -- host-side densify for tests/oracles; callers
    # pass the compute dtype for device-bound arrays
    def to_dense(self, dtype=np.float64) -> np.ndarray:
        """(n, d) dense matrix — one global scatter, not a per-row Python
        loop (this sits on the oracle path of every dense parity test).
        A duplicate column within a row keeps the LAST occurrence, same
        as the per-row fancy assignment it replaces."""
        out = np.zeros((self.n, self.num_features), dtype=dtype)
        rows = np.repeat(np.arange(self.n), np.diff(self.indptr))
        out[rows, self.indices] = self.values
        return out

    @property
    def max_nnz(self) -> int:
        if self.n == 0:
            return 0
        return int(np.max(np.diff(self.indptr)))


def _parse_label(token: str) -> float:
    """Reference label rule (OptUtils.scala:35-37), restricted to the
    shared decimal grammar (a "0x1" label is −1 on both parsers)."""
    if "+" in token:
        return 1.0
    try:
        if _NUM_CHARS.issuperset(token) and float(token) == 1.0:
            return 1.0
    except ValueError:
        pass
    return -1.0


def _parse_line(line: str):
    """One decoded line → ``(label, idx, val)`` arrays, or None for a
    blank line.  Malformed ``idx:val`` tails (missing ``:``, index or
    value outside the shared decimal grammar, empty value — e.g. a stray
    ``"3: "``) end the pair list for that line; earlier pairs and later
    lines are kept.  The native parser applies the identical rule
    (strtol/strtod longest-prefix parse + whole-token and character-class
    validation), so both paths agree byte-for-byte on such files — pinned
    by the parity cases in
    ``test_native_parser_malformed_whitespace_tails``.  The reference
    simply threw (``"".toDouble``) — crashing on bad input is not
    behavior worth replicating."""
    parts = [t for t in _WS_SPLIT.split(line.rstrip("\n")) if t]
    if not parts:
        return None
    label = _parse_label(parts[0])
    row_idx = np.empty(len(parts) - 1, dtype=np.int32)
    # jaxlint: allow=f64 -- exact text→f64 parse; device arrays cast later
    row_val = np.empty(len(parts) - 1, dtype=np.float64)
    m = 0
    for tok in parts[1:]:
        head, sep, val = tok.partition(":")
        if (not sep or not head or not val
                or not _INT_CHARS.issuperset(head)
                or not _NUM_CHARS.issuperset(val)):
            break
        try:
            i = int(head)
            v = float(val)
        except ValueError:
            break
        # 1-based index must land in int32 after the -1 shift;
        # out-of-range (incl. idx<1) is malformed, same as native —
        # a silent int32 cast there would alias huge indices onto
        # valid features
        if i < 1 or i - 1 > 2**31 - 1:
            break
        row_idx[m] = i - 1  # 1-based → 0-based (OptUtils.scala:42)
        row_val[m] = v
        m += 1
    return label, row_idx[:m], row_val[:m]


def _parse_python_stream(path: str, num_features: int, lo: int, hi):
    """Shared range/whole Python parse: rows whose line START lies in
    [lo, hi) — ``hi=None`` means EOF, and with ``lo == 0`` the file is
    read strictly sequentially (pipes stay supported on the whole-file
    path).  Returns ``(LibsvmData, row_off)`` where ``row_off[i]`` is the
    absolute byte offset of row i's line start.

    Reading is byte-transparent (binary readline + latin-1 decode): every
    byte decodes (a non-UTF-8 byte is junk to reject, not a decode crash
    the native path doesn't have) and a lone ``'\\r'`` stays in-line
    whitespace instead of universal-newlines splitting the row — both
    exactly as the byte-oriented native scanner sees the file.
    """
    labels: list[float] = []
    indptr: list[int] = [0]
    indices: list[np.ndarray] = []
    values: list[np.ndarray] = []
    offsets: list[int] = []
    nnz = 0
    with open(path, "rb") as f:
        pos = 0
        if lo > 0:
            # ownership rule (native resolve_span): a line belongs to the
            # range containing its first byte, so seek to the first line
            # start at or past lo — one past the first '\n' from lo-1
            f.seek(lo - 1)
            pos = None
            probe = lo - 1
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                j = chunk.find(b"\n")
                if j >= 0:
                    pos = probe + j + 1
                    break
                probe += len(chunk)
            if pos is None:
                pos = -1  # no line starts at or past lo
            else:
                f.seek(pos)
        while pos >= 0:
            start = pos
            if hi is not None and start >= hi:
                break
            line = f.readline()
            if not line:
                break
            pos = start + len(line)
            row = _parse_line(line.decode("latin-1"))
            if row is None:
                continue
            label, row_idx, row_val = row
            labels.append(label)
            indices.append(row_idx)
            values.append(row_val)
            nnz += len(row_idx)
            indptr.append(nnz)
            offsets.append(start)
    data = LibsvmData(
        # jaxlint: allow=f64 -- exact parse output; cast at device_put
        labels=np.asarray(labels, dtype=np.float64),
        indptr=np.asarray(indptr, dtype=np.int64),
        indices=(
            np.concatenate(indices) if indices else np.empty(0, dtype=np.int32)
        ),
        values=(
            # jaxlint: allow=f64 -- exact parse output; cast at device_put
            np.concatenate(values) if values else np.empty(0, dtype=np.float64)
        ),
        num_features=num_features,
    )
    return data, np.asarray(offsets, dtype=np.int64)


def load_libsvm_python(path: str, num_features: int) -> LibsvmData:
    """Pure-Python reference parser (semantic oracle for the native one)."""
    return _parse_python_stream(path, num_features, 0, None)[0]


def load_libsvm_python_range(path: str, num_features: int,
                             lo: int, hi: int):
    """Rows owned by the byte range [lo, hi) (ownership rule: a line
    belongs to the range containing its first byte; the last owned line
    parses to ITS end even past ``hi``).  Returns ``(LibsvmData,
    row_off)``.  Ranges that tile the file parse to exactly the
    whole-file result, each row once — pinned byte-for-byte against the
    whole parse by the chunk-boundary parity suite in
    tests/test_libsvm.py."""
    return _parse_python_stream(path, num_features, max(0, lo), hi)


def _validate(data: LibsvmData, path: str) -> LibsvmData:
    if data.indices.size:
        hi = int(data.indices.max())
        if hi >= data.num_features:
            raise ValueError(
                f"{path}: feature index {hi + 1} (1-based) exceeds "
                f"num_features={data.num_features}; pass a larger "
                f"--numFeatures (the reference also requires d up front, "
                f"OptUtils.scala:43)"
            )
        if int(data.indices.min()) < 0:
            raise ValueError(f"{path}: negative feature index after 1→0 shift")
    return data


def load_libsvm(path: str, num_features: int, prefer_native: bool = True) -> LibsvmData:
    """Parse a LIBSVM file; uses the C++ fast path when available."""
    if prefer_native:
        from cocoa_tpu.data import native_loader

        if native_loader.available():
            data = native_loader.parse_file(path, num_features)
            if data is not None:
                return _validate(data, path)
            # None: the path can't be mmap'd (missing or non-regular) —
            # the Python parser owns those cases (clean OSError / pipes)
    return _validate(load_libsvm_python(path, num_features), path)


def load_libsvm_range(path: str, num_features: int, lo: int, hi: int,
                      prefer_native: bool = True):
    """Parse the rows owned by the byte range [lo, hi); C++ fast path when
    available, same fallback contract as :func:`load_libsvm`.  Returns
    ``(LibsvmData, row_off)`` — ``row_off[i]`` the absolute byte offset of
    row i's line start, the per-row index streaming ingest
    (data/ingest.py) uses to map shard row ranges back to byte ranges."""
    if prefer_native:
        from cocoa_tpu.data import native_loader

        if native_loader.available():
            out = native_loader.parse_range(path, lo, hi, num_features)
            if out is not None:
                data, row_off = out
                return _validate(data, path), row_off
    data, row_off = load_libsvm_python_range(path, num_features, lo, hi)
    return _validate(data, path), row_off
