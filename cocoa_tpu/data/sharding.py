"""Device-sharded dataset layouts.

Replaces the reference's ``RDD[LabeledPoint]`` partitioning
(OptUtils.scala:14 ``textFile(...).coalesce(numSplits)``) with K contiguous,
balanced row blocks placed one-per-mesh-position in HBM.  Two layouts:

- **dense** — shard ``X`` is a (n_shard, d) matrix.  Right for dense data
  (epsilon-like) and moderate d: row access is a ``dynamic_slice``, eval is a
  single MXU matmul.
- **sparse** (padded-CSR) — per-row index/value arrays padded to the dataset's
  ``max_nnz``.  Right for high-d sparse data (rcv1-like): a row dot is a
  gather + small reduction instead of an O(d) dot.  TPU has no native sparse
  support, so padding + gather is the idiomatic encoding.

Shards are padded to equal row counts (XLA needs static shapes).  Padded rows
carry ``mask=0``, ``y=0``, ``x=0`` and are never sampled (index draws are
bounded by the shard's true count), never counted in objectives (mask-weighted
reductions).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.parallel import mesh as mesh_lib


def pad_rows(n_rows: int) -> int:
    """Shard length rounded up to a sublane multiple (8 f32 / 16 bf16) so
    Pallas row blocks and XLA tiles stay aligned; padded rows are masked
    everywhere.  This is THE layout contract — every producer of a
    :class:`ShardedDataset` (here and data/synth.py) must use it."""
    return -(-n_rows // 16) * 16


def resolve_layout_stats(n: int, d: int, nnz: int, layout: str,
                         mesh=None) -> str:
    """The one place the ``layout="auto"`` rule lives, from dataset
    STATS alone (streaming ingest resolves before any rows are parsed
    into a full dataset): sparse below 10% density (rcv1-like), dense
    otherwise (epsilon-like); feature-parallel meshes are dense-only."""
    if layout != "auto":
        return layout
    density = nnz / max(1, n * d)
    if mesh_lib.has_fp(mesh):
        return "dense"  # fp sharding is dense-only (see shard_dataset)
    return "sparse" if density < 0.10 else "dense"


def resolve_layout(data, layout: str, mesh=None) -> str:
    """``layout="auto"`` against a parsed dataset — shared by
    :func:`shard_dataset` and the CLI (which must know the resolved
    layout before it can resolve sparse-only knobs like ``--hotCols``)."""
    return resolve_layout_stats(data.n, data.num_features,
                                int(data.indptr[-1]), layout, mesh)


# HBM budget for the OPT-IN dense eval twin (``--evalDense=auto``): the
# twin costs K·n_shard·d·itemsize (~3.8 GB at rcv1 scale) — auto
# materializes it only under this bound and otherwise lets the eval ride
# the hot panel + residual stream (ops/rows.eval_margins).
EVAL_DENSE_HBM_BUDGET = 2 << 30


def eval_dense_fits(n: int, d: int, k: int, dtype,
                    budget: int = EVAL_DENSE_HBM_BUDGET) -> bool:
    """Whether the sparse layout's dense eval twin fits the HBM budget —
    the ``--evalDense=auto`` accounting (twin bytes vs budget)."""
    n_shard = pad_rows(int(split_sizes(n, k).max())) if k > 0 else 0
    return k * n_shard * d * np.dtype(dtype).itemsize <= budget


def segment_sq_norms(values, ptr) -> np.ndarray:
    """Exact per-segment f64 Σv² for CSR/CSC-style ``(values, ptr)``.

    Per-segment accumulation (not a global prefix-sum difference, which can
    absorb a tiny segment's squares below the running sum's ulp — a
    vanished sq_norm freezes that coordinate in the lasso prox rule).
    ``np.add.reduceat`` quirks handled here so callers don't copy them:
    a trailing 0.0 sentinel makes start indices equal to nnz (trailing
    empty segments) valid without clamping — clamping would steal the last
    nonzero from the final non-empty segment — and empty segments, which
    reduceat maps to the element AT their start, are zeroed explicitly."""
    nseg = len(ptr) - 1
    if nseg <= 0:
        return np.zeros(0)
    sq = np.empty(len(values) + 1)
    # jaxlint: allow=f64 -- exact host-side ‖x‖² accounting; the kernels
    # consume the result cast to the compute dtype
    np.square(np.asarray(values, np.float64), out=sq[:-1])
    sq[-1] = 0.0
    out = np.add.reduceat(sq, np.asarray(ptr[:-1], dtype=np.intp))
    out[np.diff(ptr) == 0] = 0.0
    return out


def split_sizes(n: int, k: int) -> np.ndarray:
    """Balanced contiguous split: first n % k shards get one extra row.

    The reference's shard sizes come from HDFS block boundaries via
    ``coalesce`` (OptUtils.scala:14) and are only approximately equal; we
    define them exactly.  Row order is preserved (contiguous blocks).
    """
    base = n // k
    sizes = np.full(k, base, dtype=np.int64)
    sizes[: n % k] += 1
    return sizes


@dataclasses.dataclass
class ShardedDataset:
    """K data shards stacked on a leading device axis.

    All arrays have leading dim K and are placed with ``P('dp', ...)`` when a
    mesh is given.  ``counts[k]`` is the number of real rows in shard k;
    rows ≥ counts[k] are padding.
    """

    layout: str                       # "dense" | "sparse"
    n: int                            # total real examples
    num_features: int                 # d (padded up to an fp multiple on a
                                      #   feature-parallel mesh; w matches)
    counts: np.ndarray                # (K,) int, host-side
    labels: jax.Array                 # (K, n_shard)
    mask: jax.Array                   # (K, n_shard)  1.0 real / 0.0 pad
    sq_norms: jax.Array               # (K, n_shard)  ||x_i||^2 (precomputed;
                                      #   the reference recomputes per step,
                                      #   CoCoA.scala:173 — same values)
    X: Optional[jax.Array] = None     # dense: (K, n_shard, d)
    sp_indices: Optional[jax.Array] = None  # sparse: (K, n_shard, max_nnz) int32
    sp_values: Optional[jax.Array] = None   # sparse: (K, n_shard, max_nnz)
    X_eval: Optional[jax.Array] = None  # optional dense twin of a SPARSE
                                      #   dataset, used ONLY by evaluation
                                      #   (ops/rows.eval_margins): the
                                      #   certificate's full margins pass as
                                      #   one MXU matvec instead of an
                                      #   every-nonzero w-gather (31% of the
                                      #   rcv1 production round); costs
                                      #   K*n_shard*d*itemsize HBM
    X_hot: Optional[jax.Array] = None   # hybrid sparse layout (hot/cold
                                      #   column split, data/hybrid.py):
                                      #   (K, n_shard, n_hot) dense panel
                                      #   over the globally hottest columns;
                                      #   sp_indices/sp_values then hold
                                      #   ONLY the cold residual
    hot_cols: Optional[jax.Array] = None  # (K, n_hot) int32 panel lane ->
                                      #   original column id (identical per
                                      #   shard; K-leading so it rides the
                                      #   fan-out plumbing like every leaf)

    @property
    def k(self) -> int:
        return self.labels.shape[0]

    @property
    def n_hot(self) -> int:
        """Hot-panel width (0 = pure stream layout)."""
        return 0 if self.X_hot is None else self.X_hot.shape[-1]

    @property
    def n_shard(self) -> int:
        return self.labels.shape[1]

    @property
    def dtype(self):
        return self.labels.dtype

    def shard_arrays(self) -> dict:
        """The pytree of per-shard arrays consumed by local solvers."""
        out = {
            "labels": self.labels,
            "mask": self.mask,
            "sq_norms": self.sq_norms,
        }
        if self.layout == "dense":
            out["X"] = self.X
        else:
            out["sp_indices"] = self.sp_indices
            out["sp_values"] = self.sp_values
            if self.X_hot is not None:
                out["X_hot"] = self.X_hot
                out["hot_cols"] = self.hot_cols
            if self.X_eval is not None:
                out["X_eval"] = self.X_eval
        return out

    # --- pytree protocol: array fields are leaves, metadata is static, so a
    # ShardedDataset can be passed straight through jit/shard_map ---
    def tree_flatten(self):
        children = (
            self.labels, self.mask, self.sq_norms,
            self.X, self.sp_indices, self.sp_values, self.X_eval,
            self.X_hot, self.hot_cols,
        )
        aux = (self.layout, self.n, self.num_features, tuple(self.counts))
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        (labels, mask, sq_norms, X, sp_indices, sp_values, X_eval,
         X_hot, hot_cols) = children
        layout, n, num_features, counts = aux
        return cls(
            layout=layout,
            n=n,
            num_features=num_features,
            counts=np.asarray(counts, dtype=np.int64),
            labels=labels,
            mask=mask,
            sq_norms=sq_norms,
            X=X,
            sp_indices=sp_indices,
            sp_values=sp_values,
            X_eval=X_eval,
            X_hot=X_hot,
            hot_cols=hot_cols,
        )


try:
    jax.tree_util.register_pytree_node(
        ShardedDataset, ShardedDataset.tree_flatten, ShardedDataset.tree_unflatten
    )
except ValueError:
    pass  # already registered (module re-imported/reloaded)


def _densify_rows(data, lo, hi, n_shard, d, np_dtype, row_nnz) -> np.ndarray:
    """Rows [lo, hi) of the CSR ``data`` as a zero-padded (n_shard, d)
    dense slab — the one CSR→dense scatter shared by the dense layout,
    the distributed per-shard builder, and the eval twin."""
    a, b = data.indptr[lo], data.indptr[hi]
    rows = np.repeat(np.arange(hi - lo), row_nnz[lo:hi])
    X = np.zeros((n_shard, d), np_dtype)
    X[rows, data.indices[a:b]] = data.values[a:b]
    return X


def _build_shard_slabs(data, lo, hi, n_shard, layout, np_dtype, d, width,
                       row_nnz, row_sq, *, rank=None, n_hot=0,
                       eval_dense=False) -> dict:
    """One shard's COMPLETE padded host arrays (rows [lo, hi) of
    ``data``): labels/mask/sq_norms plus the layout slabs — dense X,
    plain padded-CSR, or (``n_hot > 0``) the hybrid hot panel + cold
    residual — plus the optional dense eval twin.  The ONE slab builder
    shared by the replicated, whole-file-distributed, and streaming
    ingest paths, so every build produces bit-identical shards from the
    same parsed rows.  ``lo``/``hi`` and the ``row_nnz``/``row_sq``
    arrays index into ``data`` — streaming callers pass a range-parsed
    PIECE with piece-relative bounds."""
    m = hi - lo
    labels = np.zeros(n_shard, np_dtype)
    labels[:m] = data.labels[lo:hi]
    mask = np.zeros(n_shard, np_dtype)
    mask[:m] = 1.0
    sq = np.zeros(n_shard, np_dtype)
    sq[:m] = row_sq[lo:hi]
    out = dict(labels=labels, mask=mask, sq_norms=sq)
    if layout == "dense":
        out["X"] = _densify_rows(data, lo, hi, n_shard, d, np_dtype, row_nnz)
    elif n_hot:
        from cocoa_tpu.data import hybrid

        X_hot, spi, spv = hybrid.split_slab(data, lo, hi, n_shard, rank,
                                            n_hot, width, np_dtype)
        out["X_hot"] = X_hot
        out["sp_indices"] = spi
        out["sp_values"] = spv
    else:
        a, b = data.indptr[lo], data.indptr[hi]
        rows = np.repeat(np.arange(m), row_nnz[lo:hi])
        cols = np.arange(a, b) - np.repeat(data.indptr[lo:hi], row_nnz[lo:hi])
        spi = np.zeros((n_shard, width), np.int32)
        spv = np.zeros((n_shard, width), np_dtype)
        spi[rows, cols] = data.indices[a:b]
        spv[rows, cols] = data.values[a:b]
        out["sp_indices"] = spi
        out["sp_values"] = spv
    if eval_dense:
        out["X_eval"] = _densify_rows(data, lo, hi, n_shard, d, np_dtype,
                                      row_nnz)
    return out


def _assemble_distributed(mesh, k, built, locals_, *, layout, n, d,
                          n_shard, width, sizes, n_hot, hot_ids,
                          eval_dense, np_dtype) -> ShardedDataset:
    """Assemble the global (K, ...) sharded arrays from per-device
    (m, ...) slab stacks (``jax.make_array_from_single_device_arrays``):
    ``built`` maps shard id → slab dict for THIS process's shards only,
    ``locals_`` is the :func:`cocoa_tpu.parallel.mesh.dp_local_shards`
    placement.  Shared by the whole-file distributed builder and
    streaming ingest — the same assembly regardless of how the rows were
    parsed."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def assemble(field, trailing, synth=None):
        sh = NamedSharding(mesh,
                           P(mesh_lib.DP_AXIS, *([None] * len(trailing))))
        pieces = [
            jax.device_put(
                np.stack([built[s][field] for s in range(lo, hi)])
                if synth is None else np.tile(synth[None], (hi - lo, 1)),
                dev)
            for dev, lo, hi in locals_
        ]
        return jax.make_array_from_single_device_arrays(
            (k, *trailing), sh, pieces
        )

    kwargs: dict = {}
    if layout == "dense":
        kwargs["X"] = assemble("X", (n_shard, d))
    else:
        kwargs["sp_indices"] = assemble("sp_indices", (n_shard, width))
        kwargs["sp_values"] = assemble("sp_values", (n_shard, width))
        if n_hot:
            # panel lanes past the real hot count carry column id 0 and
            # all-zero values — inert, the standing padding trick
            hc = np.zeros(n_hot, dtype=np.int32)
            hc[:len(hot_ids)] = hot_ids
            kwargs["X_hot"] = assemble("X_hot", (n_shard, n_hot))
            kwargs["hot_cols"] = assemble("hot_cols", (n_hot,), synth=hc)
        if eval_dense:
            kwargs["X_eval"] = assemble("X_eval", (n_shard, d))
    return ShardedDataset(
        layout=layout,
        n=n,
        num_features=d,
        counts=sizes.astype(np.int64),
        labels=assemble("labels", (n_shard,)),
        mask=assemble("mask", (n_shard,)),
        sq_norms=assemble("sq_norms", (n_shard,)),
        **kwargs,
    )


def _slab_view(cache, layout, k, n_shard, width, n_hot, d, np_dtype,
               eval_dense):
    """The fully-resolved layout's slab-cache view, or None when no
    ``--ingestCache`` handle rides the build (data/slab_cache.py)."""
    if cache is None:
        return None
    return cache.view(layout=layout, k=k, n_shard=n_shard, width=width,
                      n_hot=n_hot, d=d, dtype=np_dtype,
                      eval_dense=eval_dense)


def _cached_or_built(view, s, build):
    """One shard through the optional slab-cache view: a valid cached
    artifact wins (zero build), a miss builds and publishes (atomic
    rename, one writer wins) — the whole-path twin of the per-shard
    logic in data/ingest._stream_build."""
    if view is not None:
        slab = view.load(s)
        if slab is not None:
            return slab
    slab = build()
    if view is not None:
        view.store(s, slab)
    return slab


def _shard_dataset_distributed(data, k, layout, np_dtype, mesh, sizes,
                               offsets, n_shard, d, width, row_nnz,
                               row_sq, *, rank=None, n_hot=0,
                               hot_ids=None,
                               eval_dense=False,
                               cache_view=None) -> ShardedDataset:
    """Multi-process assembly from a WHOLE-parsed dataset: each process
    materializes ONLY the shards whose dp mesh position is one of its own
    devices — m = K/D consecutive logical shards per device when the mesh
    is multiplexed (D < K, the Spark ``coalesce`` analogue) — then the
    global (K, ...) arrays are assembled from the per-device (m, ...)
    stacks.  Per-process host memory stays ~1/P of the padded layout
    instead of P full copies (VERDICT r1 item 5; the reference reads only
    local HDFS blocks per executor, OptUtils.scala:14) — though every
    process still parses the whole file here; ``--ingest=stream``
    (data/ingest.py) removes that last full-dataset pass too.  The hybrid
    hot/cold split and the dense eval twin build per shard exactly as on
    the replicated path.  dp-only meshes (the fp extension keeps the
    replicated-assembly path)."""
    locals_ = mesh_lib.dp_local_shards(mesh, k)
    built = {
        s: _cached_or_built(
            cache_view, s,
            lambda s=s: _build_shard_slabs(
                data, offsets[s], offsets[s + 1], n_shard, layout,
                np_dtype, d, width, row_nnz, row_sq, rank=rank,
                n_hot=n_hot, eval_dense=eval_dense))
        for _, lo, hi in locals_ for s in range(lo, hi)
    }
    return _assemble_distributed(mesh, k, built, locals_, layout=layout,
                                 n=data.n, d=d, n_shard=n_shard,
                                 width=width, sizes=sizes, n_hot=n_hot,
                                 hot_ids=hot_ids, eval_dense=eval_dense,
                                 np_dtype=np_dtype)


def shard_dataset(
    data: LibsvmData,
    k: int,
    layout: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    max_nnz: Optional[int] = None,
    eval_dense: bool = False,
    hot_cols: int = 0,
    cache=None,
) -> ShardedDataset:
    """Partition ``data`` into K balanced contiguous shards and device_put them.

    ``layout="auto"`` picks sparse when the density nnz/(n*d) is below 10%
    (rcv1-like) and dense otherwise (epsilon-like).

    ``eval_dense=True`` (sparse layout only) additionally materializes a
    dense (K, n_shard, d) twin consumed ONLY by evaluation
    (ops/rows.eval_margins): the duality-gap certificate's full margins
    pass is then one MXU matvec instead of an every-nonzero w-gather.
    Measured through the production device-loop path at rcv1 scale
    (debugIter=25): 9.42 -> 6.46 ms/round — the gather-based eval was 31%
    of the round time.  Opt-in because the twin costs K·n_shard·d·itemsize of HBM
    (~3.8 GB at rcv1 scale); training paths never touch it.

    ``hot_cols > 0`` (sparse layout only; flag ``--hotCols``) builds the
    HYBRID layout (data/hybrid.py): a dense (K, n_shard, hot_cols) panel
    over the globally hottest columns — chosen once from the column
    histogram — plus the cold-residual padded-CSR.  The split partitions
    each row's nonzeros by column, so every consumer's per-row sum is a
    permutation of the unsplit one (docs/DESIGN.md §3b-vi).

    Multi-process runs (``jax.process_count() > 1`` with a dp mesh)
    materialize only each process's own shards host-side — see
    :func:`_shard_dataset_distributed`.

    ``cache`` (an optional ``slab_cache.FileCacheHandle``,
    ``--ingestCache``) serves each shard from its persistent slab
    artifact when present and publishes every shard built cold — the
    whole-file path's half of the docs/DESIGN.md §18 cache contract
    (the zero-parse warm path lives in data/ingest.load_cached_dataset;
    here the parse is already paid, so a hit saves the slab build and a
    miss populates for the next process).
    """
    n, d = data.n, data.num_features
    layout = resolve_layout(data, layout, mesh)
    if layout == "sparse" and mesh_lib.has_fp(mesh):
        # padded-CSR rows index the full feature space; splitting them over
        # fp would need per-device re-bucketing of each row's nnz (ragged) —
        # use the dense layout for feature-parallel runs
        raise ValueError(
            "feature-axis (fp) sharding requires layout='dense'; the "
            "padded-CSR layout cannot column-partition"
        )

    np_dtype = np.dtype(dtype)
    sizes = split_sizes(n, k)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    n_shard = pad_rows(int(sizes.max())) if k > 0 else 0

    row_nnz = np.diff(data.indptr)
    row_sq = segment_sq_norms(data.values, data.indptr)
    width = 0
    if layout == "sparse":
        width = int(max_nnz if max_nnz is not None
                    else max(1, row_nnz.max(initial=1)))
        if n and int(row_nnz.max(initial=0)) > width:
            raise ValueError(
                f"row nnz {int(row_nnz.max())} exceeds max_nnz {width}"
            )

    hot_ids = None
    rank = None
    n_hot = 0
    if hot_cols:
        from cocoa_tpu.data import hybrid

        if layout != "sparse":
            raise ValueError("hot_cols (the hot/cold column split) only "
                             "applies to the sparse layout")
        if max_nnz is not None:
            raise ValueError("hot_cols and max_nnz cannot combine: the "
                             "residual width is measured from the split")
        n_hot = hybrid.pad_panel(min(int(hot_cols), d))
        # the hot set derives from the same deterministic
        # hottest_columns(column_counts(data), n) that resolve_hot_cols
        # measured, so the manifest's split stats describe THIS layout
        # (lockstep pinned by tests/test_hybrid_sparse.py)
        hot_ids = hybrid.hottest_columns(hybrid.column_counts(data), n_hot)
        rank = hybrid.hot_rank(d, hot_ids)
        # the residual padded-CSR width is the max COLD nnz across rows —
        # the whole point: the stream kernels' padded width drops to the
        # tail's max, not the full row's
        cold_rows = np.repeat(np.arange(n, dtype=np.int64),
                              row_nnz)[rank[data.indices] < 0]
        resid_max = int(np.bincount(cold_rows, minlength=max(1, n))
                        .max(initial=0))
        width = max(1, resid_max)
        if cache is not None:
            # the measured residual width is what keys the hybrid shard
            # artifacts — persist it so a warm run (data/ingest.py
            # load_cached_dataset) resolves the SAME width with no parse
            cache.store_hybrid_meta(n_hot, resid_max)

    if eval_dense and layout != "sparse":
        raise ValueError("eval_dense only applies to the sparse layout "
                         "(the dense layout's eval is already a matvec)")
    if (
        mesh is not None
        and jax.process_count() > 1
        and not mesh_lib.has_fp(mesh)
    ):
        if k % mesh.devices.size != 0:
            # the multiplexed distributed builder stacks m = K/D shards
            # per device; a non-divisor D has no even placement — the same
            # rule fanout.shards_per_device enforces for the solvers, and
            # the divisibility contract the elastic supervisor's
            # shrink-to-survivors path resolves gang sizes against
            # (elastic.shrink_gang_size: a reformed gang is always a
            # divisor, so a post-failure relaunch can never trip this)
            raise ValueError(
                f"multi-process runs need numSplits divisible by the dp "
                f"mesh size: K={k} shards cannot multiplex onto "
                f"{mesh.devices.size} devices"
            )
        d_eff = mesh_lib.pad_features(d, mesh) if layout == "dense" else d
        return _shard_dataset_distributed(
            data, k, layout, np_dtype, mesh, sizes, offsets, n_shard,
            # mirror the replicated path: only the dense layout pads d
            d_eff,
            width, row_nnz, row_sq, rank=rank, n_hot=n_hot,
            hot_ids=hot_ids, eval_dense=eval_dense,
            cache_view=_slab_view(cache, layout, k, n_shard, width,
                                  n_hot, d_eff, np_dtype, eval_dense),
        )

    if layout == "dense":
        d = mesh_lib.pad_features(d, mesh)
    view = _slab_view(cache, layout, k, n_shard, width, n_hot, d,
                      np_dtype, eval_dense)
    arrs: dict = {}
    for s in range(k):
        slab = _cached_or_built(
            view, s,
            lambda s=s: _build_shard_slabs(
                data, offsets[s], offsets[s + 1], n_shard, layout,
                np_dtype, d, width, row_nnz, row_sq, rank=rank,
                n_hot=n_hot, eval_dense=eval_dense))
        for f, v in slab.items():
            arrs.setdefault(f, np.zeros((k, *v.shape), v.dtype))[s] = v
    if n_hot:
        # panel lanes past the real hot count (d < n_hot after lane
        # padding) carry column id 0 and all-zero values — inert in
        # every gather and scatter, the standing padding trick
        hc = np.zeros(n_hot, dtype=np.int32)
        hc[:len(hot_ids)] = hot_ids
        arrs["hot_cols"] = np.tile(hc[None], (k, 1))
    return _finalize_replicated(arrs, layout=layout, n=n, d=d, mesh=mesh,
                                sizes=sizes)


def _finalize_replicated(arrs, *, layout, n, d, mesh, sizes
                         ) -> ShardedDataset:
    """device_put the stacked (K, ...) host arrays and wrap them — the
    tail of every single-process build (replicated whole-file and
    streaming alike)."""
    def put(arr, fp_last=False):
        if arr is None:
            return None
        if mesh is not None:
            if fp_last:
                return jax.device_put(arr, mesh_lib.x_sharding(mesh))
            return jax.device_put(
                arr, mesh_lib.sharded_rows(mesh, extra_dims=arr.ndim - 1)
            )
        return jnp.asarray(arr)

    return ShardedDataset(
        layout=layout,
        n=n,
        num_features=d,
        counts=sizes.astype(np.int64),
        labels=put(arrs["labels"]),
        mask=put(arrs["mask"]),
        sq_norms=put(arrs["sq_norms"]),
        X=put(arrs.get("X"), fp_last=True) if "X" in arrs else None,
        sp_indices=put(arrs.get("sp_indices")),
        sp_values=put(arrs.get("sp_values")),
        X_eval=put(arrs.get("X_eval")),
        X_hot=put(arrs.get("X_hot")),
        hot_cols=put(arrs.get("hot_cols")),
    )
