"""Device-sharded dataset layouts.

Replaces the reference's ``RDD[LabeledPoint]`` partitioning
(OptUtils.scala:14 ``textFile(...).coalesce(numSplits)``) with K contiguous,
balanced row blocks placed one-per-mesh-position in HBM.  Two layouts:

- **dense** — shard ``X`` is a (n_shard, d) matrix.  Right for dense data
  (epsilon-like) and moderate d: row access is a ``dynamic_slice``, eval is a
  single MXU matmul.
- **sparse** (padded-CSR) — per-row index/value arrays padded to the dataset's
  ``max_nnz``.  Right for high-d sparse data (rcv1-like): a row dot is a
  gather + small reduction instead of an O(d) dot.  TPU has no native sparse
  support, so padding + gather is the idiomatic encoding.

Shards are padded to equal row counts (XLA needs static shapes).  Padded rows
carry ``mask=0``, ``y=0``, ``x=0`` and are never sampled (index draws are
bounded by the shard's true count), never counted in objectives (mask-weighted
reductions).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.parallel import mesh as mesh_lib


def pad_rows(n_rows: int) -> int:
    """Shard length rounded up to a sublane multiple (8 f32 / 16 bf16) so
    Pallas row blocks and XLA tiles stay aligned; padded rows are masked
    everywhere.  This is THE layout contract — every producer of a
    :class:`ShardedDataset` (here and data/synth.py) must use it."""
    return -(-n_rows // 16) * 16


def resolve_layout(data, layout: str, mesh=None) -> str:
    """The one place the ``layout="auto"`` rule lives: sparse below 10%
    density (rcv1-like), dense otherwise (epsilon-like); feature-parallel
    meshes are dense-only.  Shared by :func:`shard_dataset` and the CLI
    (which must know the resolved layout before it can resolve
    sparse-only knobs like ``--hotCols``)."""
    if layout != "auto":
        return layout
    nnz = int(data.indptr[-1])
    density = nnz / max(1, data.n * data.num_features)
    if mesh_lib.has_fp(mesh):
        return "dense"  # fp sharding is dense-only (see shard_dataset)
    return "sparse" if density < 0.10 else "dense"


# HBM budget for the OPT-IN dense eval twin (``--evalDense=auto``): the
# twin costs K·n_shard·d·itemsize (~3.8 GB at rcv1 scale) — auto
# materializes it only under this bound and otherwise lets the eval ride
# the hot panel + residual stream (ops/rows.eval_margins).
EVAL_DENSE_HBM_BUDGET = 2 << 30


def eval_dense_fits(n: int, d: int, k: int, dtype,
                    budget: int = EVAL_DENSE_HBM_BUDGET) -> bool:
    """Whether the sparse layout's dense eval twin fits the HBM budget —
    the ``--evalDense=auto`` accounting (twin bytes vs budget)."""
    n_shard = pad_rows(int(split_sizes(n, k).max())) if k > 0 else 0
    return k * n_shard * d * np.dtype(dtype).itemsize <= budget


def segment_sq_norms(values, ptr) -> np.ndarray:
    """Exact per-segment f64 Σv² for CSR/CSC-style ``(values, ptr)``.

    Per-segment accumulation (not a global prefix-sum difference, which can
    absorb a tiny segment's squares below the running sum's ulp — a
    vanished sq_norm freezes that coordinate in the lasso prox rule).
    ``np.add.reduceat`` quirks handled here so callers don't copy them:
    a trailing 0.0 sentinel makes start indices equal to nnz (trailing
    empty segments) valid without clamping — clamping would steal the last
    nonzero from the final non-empty segment — and empty segments, which
    reduceat maps to the element AT their start, are zeroed explicitly."""
    nseg = len(ptr) - 1
    if nseg <= 0:
        return np.zeros(0)
    sq = np.empty(len(values) + 1)
    # jaxlint: allow=f64 -- exact host-side ‖x‖² accounting; the kernels
    # consume the result cast to the compute dtype
    np.square(np.asarray(values, np.float64), out=sq[:-1])
    sq[-1] = 0.0
    out = np.add.reduceat(sq, np.asarray(ptr[:-1], dtype=np.intp))
    out[np.diff(ptr) == 0] = 0.0
    return out


def split_sizes(n: int, k: int) -> np.ndarray:
    """Balanced contiguous split: first n % k shards get one extra row.

    The reference's shard sizes come from HDFS block boundaries via
    ``coalesce`` (OptUtils.scala:14) and are only approximately equal; we
    define them exactly.  Row order is preserved (contiguous blocks).
    """
    base = n // k
    sizes = np.full(k, base, dtype=np.int64)
    sizes[: n % k] += 1
    return sizes


@dataclasses.dataclass
class ShardedDataset:
    """K data shards stacked on a leading device axis.

    All arrays have leading dim K and are placed with ``P('dp', ...)`` when a
    mesh is given.  ``counts[k]`` is the number of real rows in shard k;
    rows ≥ counts[k] are padding.
    """

    layout: str                       # "dense" | "sparse"
    n: int                            # total real examples
    num_features: int                 # d (padded up to an fp multiple on a
                                      #   feature-parallel mesh; w matches)
    counts: np.ndarray                # (K,) int, host-side
    labels: jax.Array                 # (K, n_shard)
    mask: jax.Array                   # (K, n_shard)  1.0 real / 0.0 pad
    sq_norms: jax.Array               # (K, n_shard)  ||x_i||^2 (precomputed;
                                      #   the reference recomputes per step,
                                      #   CoCoA.scala:173 — same values)
    X: Optional[jax.Array] = None     # dense: (K, n_shard, d)
    sp_indices: Optional[jax.Array] = None  # sparse: (K, n_shard, max_nnz) int32
    sp_values: Optional[jax.Array] = None   # sparse: (K, n_shard, max_nnz)
    X_eval: Optional[jax.Array] = None  # optional dense twin of a SPARSE
                                      #   dataset, used ONLY by evaluation
                                      #   (ops/rows.eval_margins): the
                                      #   certificate's full margins pass as
                                      #   one MXU matvec instead of an
                                      #   every-nonzero w-gather (31% of the
                                      #   rcv1 production round); costs
                                      #   K*n_shard*d*itemsize HBM
    X_hot: Optional[jax.Array] = None   # hybrid sparse layout (hot/cold
                                      #   column split, data/hybrid.py):
                                      #   (K, n_shard, n_hot) dense panel
                                      #   over the globally hottest columns;
                                      #   sp_indices/sp_values then hold
                                      #   ONLY the cold residual
    hot_cols: Optional[jax.Array] = None  # (K, n_hot) int32 panel lane ->
                                      #   original column id (identical per
                                      #   shard; K-leading so it rides the
                                      #   fan-out plumbing like every leaf)

    @property
    def k(self) -> int:
        return self.labels.shape[0]

    @property
    def n_hot(self) -> int:
        """Hot-panel width (0 = pure stream layout)."""
        return 0 if self.X_hot is None else self.X_hot.shape[-1]

    @property
    def n_shard(self) -> int:
        return self.labels.shape[1]

    @property
    def dtype(self):
        return self.labels.dtype

    def shard_arrays(self) -> dict:
        """The pytree of per-shard arrays consumed by local solvers."""
        out = {
            "labels": self.labels,
            "mask": self.mask,
            "sq_norms": self.sq_norms,
        }
        if self.layout == "dense":
            out["X"] = self.X
        else:
            out["sp_indices"] = self.sp_indices
            out["sp_values"] = self.sp_values
            if self.X_hot is not None:
                out["X_hot"] = self.X_hot
                out["hot_cols"] = self.hot_cols
            if self.X_eval is not None:
                out["X_eval"] = self.X_eval
        return out

    # --- pytree protocol: array fields are leaves, metadata is static, so a
    # ShardedDataset can be passed straight through jit/shard_map ---
    def tree_flatten(self):
        children = (
            self.labels, self.mask, self.sq_norms,
            self.X, self.sp_indices, self.sp_values, self.X_eval,
            self.X_hot, self.hot_cols,
        )
        aux = (self.layout, self.n, self.num_features, tuple(self.counts))
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        (labels, mask, sq_norms, X, sp_indices, sp_values, X_eval,
         X_hot, hot_cols) = children
        layout, n, num_features, counts = aux
        return cls(
            layout=layout,
            n=n,
            num_features=num_features,
            counts=np.asarray(counts, dtype=np.int64),
            labels=labels,
            mask=mask,
            sq_norms=sq_norms,
            X=X,
            sp_indices=sp_indices,
            sp_values=sp_values,
            X_eval=X_eval,
            X_hot=X_hot,
            hot_cols=hot_cols,
        )


try:
    jax.tree_util.register_pytree_node(
        ShardedDataset, ShardedDataset.tree_flatten, ShardedDataset.tree_unflatten
    )
except ValueError:
    pass  # already registered (module re-imported/reloaded)


def _densify_rows(data, lo, hi, n_shard, d, np_dtype, row_nnz) -> np.ndarray:
    """Rows [lo, hi) of the CSR ``data`` as a zero-padded (n_shard, d)
    dense slab — the one CSR→dense scatter shared by the dense layout,
    the distributed per-shard builder, and the eval twin."""
    a, b = data.indptr[lo], data.indptr[hi]
    rows = np.repeat(np.arange(hi - lo), row_nnz[lo:hi])
    X = np.zeros((n_shard, d), np_dtype)
    X[rows, data.indices[a:b]] = data.values[a:b]
    return X


def _build_shard_slabs(data, lo, hi, n_shard, layout, np_dtype, d, width,
                       row_nnz, row_sq) -> dict:
    """One shard's padded host arrays (rows [lo, hi) of ``data``)."""
    m = hi - lo
    labels = np.zeros(n_shard, np_dtype)
    labels[:m] = data.labels[lo:hi]
    mask = np.zeros(n_shard, np_dtype)
    mask[:m] = 1.0
    sq = np.zeros(n_shard, np_dtype)
    sq[:m] = row_sq[lo:hi]
    out = dict(labels=labels, mask=mask, sq_norms=sq)
    a, b = data.indptr[lo], data.indptr[hi]
    rows = np.repeat(np.arange(m), row_nnz[lo:hi])
    if layout == "dense":
        out["X"] = _densify_rows(data, lo, hi, n_shard, d, np_dtype, row_nnz)
    else:
        cols = np.arange(a, b) - np.repeat(data.indptr[lo:hi], row_nnz[lo:hi])
        spi = np.zeros((n_shard, width), np.int32)
        spv = np.zeros((n_shard, width), np_dtype)
        spi[rows, cols] = data.indices[a:b]
        spv[rows, cols] = data.values[a:b]
        out["sp_indices"] = spi
        out["sp_values"] = spv
    return out


def _shard_dataset_distributed(data, k, layout, np_dtype, mesh, sizes,
                               offsets, n_shard, d, width, row_nnz,
                               row_sq) -> ShardedDataset:
    """Multi-process assembly: each process materializes ONLY the shards
    whose dp mesh position is one of its own devices, then the global
    (K, ...) arrays are assembled from the per-device pieces
    (``jax.make_array_from_single_device_arrays``) — per-process host
    memory stays ~1/P of the dense matrix instead of P full copies
    (VERDICT r1 item 5; the reference reads only local HDFS blocks per
    executor, OptUtils.scala:14).  dp-only meshes (the fp extension keeps
    the replicated-assembly path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    dev_grid = np.asarray(mesh.devices).reshape(k, -1)
    me = jax.process_index()
    local = {s: dev_grid[s, 0] for s in range(k)
             if dev_grid[s, 0].process_index == me}
    built = {
        s: _build_shard_slabs(data, offsets[s], offsets[s + 1], n_shard,
                              layout, np_dtype, d, width, row_nnz, row_sq)
        for s in local
    }

    def assemble(field, trailing):
        sh = NamedSharding(mesh, P(mesh_lib.DP_AXIS, *([None] * len(trailing))))
        pieces = [jax.device_put(built[s][field][None], dev)
                  for s, dev in local.items()]
        return jax.make_array_from_single_device_arrays(
            (k, *trailing), sh, pieces
        )

    kwargs: dict = {}
    if layout == "dense":
        kwargs["X"] = assemble("X", (n_shard, d))
    else:
        kwargs["sp_indices"] = assemble("sp_indices", (n_shard, width))
        kwargs["sp_values"] = assemble("sp_values", (n_shard, width))
    return ShardedDataset(
        layout=layout,
        n=data.n,
        num_features=d,
        counts=sizes.astype(np.int64),
        labels=assemble("labels", (n_shard,)),
        mask=assemble("mask", (n_shard,)),
        sq_norms=assemble("sq_norms", (n_shard,)),
        **kwargs,
    )


def shard_dataset(
    data: LibsvmData,
    k: int,
    layout: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    max_nnz: Optional[int] = None,
    eval_dense: bool = False,
    hot_cols: int = 0,
) -> ShardedDataset:
    """Partition ``data`` into K balanced contiguous shards and device_put them.

    ``layout="auto"`` picks sparse when the density nnz/(n*d) is below 10%
    (rcv1-like) and dense otherwise (epsilon-like).

    ``eval_dense=True`` (sparse layout only) additionally materializes a
    dense (K, n_shard, d) twin consumed ONLY by evaluation
    (ops/rows.eval_margins): the duality-gap certificate's full margins
    pass is then one MXU matvec instead of an every-nonzero w-gather.
    Measured through the production device-loop path at rcv1 scale
    (debugIter=25): 9.42 -> 6.46 ms/round — the gather-based eval was 31%
    of the round time.  Opt-in because the twin costs K·n_shard·d·itemsize of HBM
    (~3.8 GB at rcv1 scale); training paths never touch it.

    ``hot_cols > 0`` (sparse layout only; flag ``--hotCols``) builds the
    HYBRID layout (data/hybrid.py): a dense (K, n_shard, hot_cols) panel
    over the globally hottest columns — chosen once from the column
    histogram — plus the cold-residual padded-CSR.  The split partitions
    each row's nonzeros by column, so every consumer's per-row sum is a
    permutation of the unsplit one (docs/DESIGN.md §3b-vi).

    Multi-process runs (``jax.process_count() > 1`` with a dp mesh)
    materialize only each process's own shards host-side — see
    :func:`_shard_dataset_distributed`.
    """
    n, d = data.n, data.num_features
    layout = resolve_layout(data, layout, mesh)
    if layout == "sparse" and mesh_lib.has_fp(mesh):
        # padded-CSR rows index the full feature space; splitting them over
        # fp would need per-device re-bucketing of each row's nnz (ragged) —
        # use the dense layout for feature-parallel runs
        raise ValueError(
            "feature-axis (fp) sharding requires layout='dense'; the "
            "padded-CSR layout cannot column-partition"
        )

    np_dtype = np.dtype(dtype)
    sizes = split_sizes(n, k)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    n_shard = pad_rows(int(sizes.max())) if k > 0 else 0

    row_nnz = np.diff(data.indptr)
    row_sq = segment_sq_norms(data.values, data.indptr)
    width = 0
    if layout == "sparse":
        width = int(max_nnz if max_nnz is not None
                    else max(1, row_nnz.max(initial=1)))
        if n and int(row_nnz.max(initial=0)) > width:
            raise ValueError(
                f"row nnz {int(row_nnz.max())} exceeds max_nnz {width}"
            )

    hot_ids = None
    rank = None
    n_hot = 0
    if hot_cols:
        from cocoa_tpu.data import hybrid

        if layout != "sparse":
            raise ValueError("hot_cols (the hot/cold column split) only "
                             "applies to the sparse layout")
        if max_nnz is not None:
            raise ValueError("hot_cols and max_nnz cannot combine: the "
                             "residual width is measured from the split")
        n_hot = hybrid.pad_panel(min(int(hot_cols), d))
        # the hot set derives from the same deterministic
        # hottest_columns(column_counts(data), n) that resolve_hot_cols
        # measured, so the manifest's split stats describe THIS layout
        # (lockstep pinned by tests/test_hybrid_sparse.py)
        hot_ids = hybrid.hottest_columns(hybrid.column_counts(data), n_hot)
        rank = hybrid.hot_rank(d, hot_ids)
        # the residual padded-CSR width is the max COLD nnz across rows —
        # the whole point: the stream kernels' padded width drops to the
        # tail's max, not the full row's
        cold_rows = np.repeat(np.arange(n, dtype=np.int64),
                              row_nnz)[rank[data.indices] < 0]
        width = max(1, int(np.bincount(cold_rows, minlength=max(1, n))
                           .max(initial=0)))

    if eval_dense and layout != "sparse":
        raise ValueError("eval_dense only applies to the sparse layout "
                         "(the dense layout's eval is already a matvec)")
    if (
        mesh is not None
        and jax.process_count() > 1
        and not mesh_lib.has_fp(mesh)
        and mesh.devices.size != k
    ):
        # a multiplexed dp mesh (D < K) would otherwise fall through to the
        # single-process replicated builder: every process materializes the
        # full (K, n_shard, d) dataset host-side and device_puts across
        # non-addressable devices — a version-dependent crash or a
        # per-process memory blow-up, never what was asked for (ADVICE r5;
        # mirrors the explicit eval_dense rejection below)
        raise ValueError(
            f"multi-process runs need a dp mesh with exactly "
            f"numSplits={k} positions, got {mesh.devices.size}; shard "
            f"multiplexing (D < K) is single-process only — use "
            f"numSplits == device count, or run single-process"
        )
    if (
        mesh is not None
        and jax.process_count() > 1
        and not mesh_lib.has_fp(mesh)
        and mesh.devices.size == k
    ):
        if eval_dense:
            raise ValueError("eval_dense is not supported on the "
                             "multi-process sharding path yet")
        if n_hot:
            raise ValueError("hot_cols is not supported on the "
                             "multi-process sharding path yet")
        return _shard_dataset_distributed(
            data, k, layout, np_dtype, mesh, sizes, offsets, n_shard,
            # mirror the replicated path: only the dense layout pads d
            mesh_lib.pad_features(d, mesh) if layout == "dense" else d,
            width, row_nnz, row_sq,
        )

    labels = np.zeros((k, n_shard), dtype=np_dtype)
    mask = np.zeros((k, n_shard), dtype=np_dtype)
    sq_norms = np.zeros((k, n_shard), dtype=np_dtype)
    for s in range(k):
        lo, hi = offsets[s], offsets[s + 1]
        m = hi - lo
        labels[s, :m] = data.labels[lo:hi]
        mask[s, :m] = 1.0
        sq_norms[s, :m] = row_sq[lo:hi]

    kwargs: dict = {}
    if layout == "dense":
        d = mesh_lib.pad_features(d, mesh)
        X = np.zeros((k, n_shard, d), dtype=np_dtype)
        for s in range(k):
            lo, hi = offsets[s], offsets[s + 1]
            X[s] = _densify_rows(data, lo, hi, n_shard, d, np_dtype, row_nnz)
        kwargs["X"] = X
    else:
        sp_idx = np.zeros((k, n_shard, width), dtype=np.int32)
        sp_val = np.zeros((k, n_shard, width), dtype=np_dtype)
        X_hot = np.zeros((k, n_shard, n_hot), dtype=np_dtype) if n_hot \
            else None
        for s in range(k):
            lo, hi = offsets[s], offsets[s + 1]
            if n_hot:
                from cocoa_tpu.data import hybrid

                X_hot[s], sp_idx[s], sp_val[s] = hybrid.split_slab(
                    data, lo, hi, n_shard, rank, n_hot, width, np_dtype)
                continue
            a, b = data.indptr[lo], data.indptr[hi]
            rows = np.repeat(np.arange(hi - lo), row_nnz[lo:hi])
            cols = np.arange(a, b) - np.repeat(data.indptr[lo:hi], row_nnz[lo:hi])
            sp_idx[s][rows, cols] = data.indices[a:b]
            sp_val[s][rows, cols] = data.values[a:b]
        kwargs["sp_indices"] = sp_idx
        kwargs["sp_values"] = sp_val
        if n_hot:
            # panel lanes past the real hot count (d < n_hot after lane
            # padding) carry column id 0 and all-zero values — inert in
            # every gather and scatter, the standing padding trick
            hc = np.zeros(n_hot, dtype=np.int32)
            hc[:len(hot_ids)] = hot_ids
            kwargs["X_hot"] = X_hot
            kwargs["hot_cols"] = np.tile(hc[None], (k, 1))
        if eval_dense:
            Xe = np.zeros((k, n_shard, d), dtype=np_dtype)
            for s in range(k):
                lo, hi = offsets[s], offsets[s + 1]
                Xe[s] = _densify_rows(data, lo, hi, n_shard, d, np_dtype,
                                      row_nnz)
            kwargs["X_eval"] = Xe

    def put(arr, fp_last=False):
        if mesh is not None:
            if fp_last:
                return jax.device_put(arr, mesh_lib.x_sharding(mesh))
            return jax.device_put(
                arr, mesh_lib.sharded_rows(mesh, extra_dims=arr.ndim - 1)
            )
        return jnp.asarray(arr)

    return ShardedDataset(
        layout=layout,
        n=n,
        num_features=d,
        counts=sizes.astype(np.int64),
        labels=put(labels),
        mask=put(mask),
        sq_norms=put(sq_norms),
        X=put(kwargs["X"], fp_last=True) if "X" in kwargs else None,
        sp_indices=put(kwargs["sp_indices"]) if "sp_indices" in kwargs else None,
        sp_values=put(kwargs["sp_values"]) if "sp_values" in kwargs else None,
        X_eval=put(kwargs["X_eval"]) if "X_eval" in kwargs else None,
        X_hot=put(kwargs["X_hot"]) if "X_hot" in kwargs else None,
        hot_cols=put(kwargs["hot_cols"]) if "hot_cols" in kwargs else None,
    )
