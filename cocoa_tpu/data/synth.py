"""Synthetic benchmark datasets — epsilon-like dense, rcv1-like sparse.

The north-star baseline configs (BASELINE.md, /root/repo/BASELINE.json) are
LIBSVM's epsilon (400K x 2000, dense, unit-normalized rows) and rcv1.binary
(~20K train x 47236, sparse ~0.16% density, tf-idf values).  Neither file can
be downloaded in this environment, so these generators produce
shape-and-statistics-faithful stand-ins from a fixed seed: a planted
ground-truth separator with label-flip noise, so every solver has a
well-conditioned problem whose duality gap actually closes.

Two paths:

- :func:`synth_dense_sharded` generates the dataset *on device, already
  sharded* — a (K, n_shard, d) normal matrix with unit-normalized rows never
  exists on the host at all.  At epsilon scale that skips a 3.2 GB
  host->device transfer (minutes through a tunneled device) and is the
  TPU-native way to build a benchmark input.
- :func:`synth_dense` / :func:`synth_sparse` build host-side
  :class:`LibsvmData` (tests, small runs, parser round-trips via
  :func:`write_libsvm`).

The reference has no synthetic-data story (its only data is the bundled
``data/small_*.dat``, README.md:19-22); this is net-new capability required
to *generate* the baseline numbers the reference never published
(SURVEY.md #6).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import ShardedDataset, pad_rows, split_sizes
from cocoa_tpu.parallel import mesh as mesh_lib


def _plant_labels(margins: np.ndarray, flip: float, rng) -> np.ndarray:
    """sign(x . w*) labels with probability-``flip`` label noise, in {-1,+1}."""
    y = np.where(margins >= 0, 1.0, -1.0)
    if flip > 0:
        y = np.where(rng.random(y.shape) < flip, -y, y)
    return y


def synth_dense(
    n: int, d: int, *, seed: int = 0, flip: float = 0.02
) -> LibsvmData:
    """Host-side epsilon-like dense data as :class:`LibsvmData` (small n*d
    only — the CSR encoding of a dense matrix is deliberate here: it feeds
    the exact same ingestion path real LIBSVM files do)."""
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d))
    X /= np.linalg.norm(X, axis=1, keepdims=True)
    w_star = rng.standard_normal(d) / np.sqrt(d)
    y = _plant_labels(X @ w_star, flip, rng)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    indices = np.tile(np.arange(d, dtype=np.int32), n)
    return LibsvmData(
        labels=y.astype(np.float64),
        indptr=indptr,
        indices=indices,
        values=X.reshape(-1).astype(np.float64),
        num_features=d,
    )


def synth_sparse(
    n: int,
    d: int,
    *,
    nnz_mean: int = 75,
    seed: int = 0,
    flip: float = 0.02,
    nnz_sigma: float = 0.7,
) -> LibsvmData:
    """rcv1-like sparse data, distribution-faithful where the kernels and
    the gap trajectory are sensitive (VERDICT r3 item 5 — round 3 was
    shape-faithful only):

    - **nnz/row ~ log-normal** with log-sd ``nnz_sigma`` and mean
      ``nnz_mean`` — document lengths are heavy-tailed (RCV1-v2's token
      counts famously so), where round 3's Poisson was nearly constant
      (sd ~sqrt(75) vs the real spread of ~0.7 in the log).  The padded-CSR
      layout pads every row to the MAX row nnz, so this tail is exactly
      what that kernel pays for.
    - **tf-idf values**: tf = the column's repeat count within the row's
      token draws (popular columns repeat — that IS term frequency),
      value = (1 + log tf) * idf(df(col)) with Zipf column popularity
      (df ∝ 1/rank), then L2-normalized rows — RCV1-v2's published ltc
      weighting (Lewis et al. 2004), matching both the value distribution
      and the value↔popularity correlation (common words carry small
      weights) that round 3's iid log-normal values lacked.

    ``nnz_mean`` targets the post-dedup (unique terms per row) mean — the
    token draws are inflated by the empirical dedup factor at rcv1 scale.
    """
    rng = np.random.default_rng(seed)
    # column popularity ~ 1/rank: sample columns by inverse-CDF of a Zipf-ish
    # weight vector so low feature ids are hot, mimicking sorted-by-df tf-idf
    weights = 1.0 / np.arange(1, d + 1)
    probs = weights / weights.sum()
    cdf = np.cumsum(probs)
    # log-normal TOKEN counts whose post-dedup unique mean lands on
    # nnz_mean: mu = ln(mean·inflate) - sigma^2/2, inflate = the measured
    # dedup shrinkage of Zipf draws at rcv1 scale (~0.79 unique/draw)
    mu = np.log(nnz_mean * 1.27) - 0.5 * nnz_sigma ** 2
    row_nnz = np.clip(
        np.round(rng.lognormal(mu, nnz_sigma, size=n)), 1,
        min(d, 12 * nnz_mean),
    ).astype(np.int64)
    indptr = np.concatenate([[0], np.cumsum(row_nnz)])
    total = int(indptr[-1])
    cols = np.searchsorted(cdf, rng.random(total)).astype(np.int32)
    # idf against the sampling distribution itself: df(col) = n * p(col)
    idf = np.log(1.0 / np.maximum(probs, 1.0 / (50.0 * n)))
    # dedupe within each row (duplicate idx:val pairs are legal LIBSVM-wise
    # but the dense/padded layouts would sum them differently than last-wins)
    indices_list = []
    values_list = []
    w_star = rng.standard_normal(d) / np.sqrt(nnz_mean)
    labels = np.empty(n)
    out_ptr = [0]
    for i in range(n):
        c, tf = np.unique(cols[indptr[i]:indptr[i + 1]],
                          return_counts=True)
        v = (1.0 + np.log(tf)) * idf[c]
        nrm = np.linalg.norm(v)
        v = v / (nrm if nrm > 0 else 1.0)
        indices_list.append(c)
        values_list.append(v)
        out_ptr.append(out_ptr[-1] + c.size)
        labels[i] = v @ w_star[c]
    y = _plant_labels(labels, flip, rng)
    return LibsvmData(
        labels=y.astype(np.float64),
        indptr=np.asarray(out_ptr, dtype=np.int64),
        indices=np.concatenate(indices_list).astype(np.int32),
        values=np.concatenate(values_list).astype(np.float64),
        num_features=d,
    )


def write_libsvm(data: LibsvmData, path: str, precision: int = 8) -> None:
    """Serialize to LIBSVM text (1-based indices, ``+1``/``-1`` labels) —
    round-trip fodder for the parsers and for generating big on-disk
    benchmark files."""
    with open(path, "w") as f:
        for i in range(data.n):
            idx, val = data.row(i)
            lab = "+1" if data.labels[i] > 0 else "-1"
            pairs = " ".join(
                f"{j + 1}:{v:.{precision}g}" for j, v in zip(idx, val)
            )
            f.write(f"{lab} {pairs}\n" if pairs else f"{lab}\n")


def synth_dense_sharded(
    n: int,
    d: int,
    k: int,
    *,
    seed: int = 0,
    flip: float = 0.02,
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> ShardedDataset:
    """Generate an epsilon-like dense dataset directly on device, already in
    the (K, n_shard, d) sharded layout of :func:`shard_dataset` — the data
    never exists on the host.  Deterministic in ``(n, d, k, seed, flip)``
    and independent of the mesh (same shard contents on 1 device or K).

    Rows are unit-normalized (as epsilon is), labels are a planted separator
    with ``flip`` label noise, padded rows are zeroed exactly as
    :func:`shard_dataset` does.
    """
    sizes = split_sizes(n, k)
    n_shard = pad_rows(int(sizes.max()))
    d_pad = mesh_lib.pad_features(d, mesh)

    counts_dev = jnp.asarray(sizes, dtype=jnp.int32)
    key = jax.random.key(seed)
    k_w, k_x, k_f = jax.random.split(key, 3)

    def gen_shard(s, count):
        # per-shard fold keeps contents independent of K's device placement
        kx = jax.random.fold_in(k_x, s)
        kf = jax.random.fold_in(k_f, s)
        X = jax.random.normal(kx, (n_shard, d), dtype=jnp.float32)
        X = X / jnp.linalg.norm(X, axis=1, keepdims=True)
        w_star = jax.random.normal(k_w, (d,), dtype=jnp.float32) / np.sqrt(d)
        margins = X @ w_star
        flips = jax.random.bernoulli(kf, flip, (n_shard,))
        y = jnp.where(margins >= 0, 1.0, -1.0)
        y = jnp.where(flips, -y, y)
        m = (jnp.arange(n_shard) < count).astype(dtype)
        X = (X * m[:, None]).astype(dtype)
        if d_pad != d:
            X = jnp.pad(X, ((0, 0), (0, d_pad - d)))
        sq = jnp.sum(X * X, axis=-1)
        return X, (y.astype(dtype) * m), m, sq

    if mesh is not None:
        rows = mesh_lib.sharded_rows(mesh, extra_dims=1)
        out_shardings = (mesh_lib.x_sharding(mesh), rows, rows, rows)
        gen = jax.jit(
            jax.vmap(gen_shard), out_shardings=out_shardings
        )
    else:
        gen = jax.jit(jax.vmap(gen_shard))
    X, labels, mask, sq_norms = gen(jnp.arange(k), counts_dev)
    return ShardedDataset(
        layout="dense",
        n=n,
        num_features=d_pad,
        counts=sizes.astype(np.int64),
        labels=labels,
        mask=mask,
        sq_norms=sq_norms,
        X=X,
    )
