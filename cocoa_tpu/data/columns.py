"""Column (feature) shards for the primal prox solvers (ProxCoCoA+).

The L1 framework partitions the design matrix A (n × d) by **columns**:
worker k owns a coordinate block x_[k] and its columns A_[k], and the
shared n-vector v = A·x is the replicated state (the exact mirror of the
dual solvers, where examples are sharded and the d-vector w is shared).

This builder reuses :class:`~cocoa_tpu.data.sharding.ShardedDataset` with
the roles transposed: the shard's "rows" are columns a_j (shape (n,)),
``labels`` is all-ones (the prox rules have no y factor), ``sq_norms`` are
column norms ‖a_j‖², ``counts`` the per-shard column counts, and
``num_features`` is n (padded) — the length of the replicated residual
vector r = A·x − b.  Every downstream consumer — the fan-out machinery,
the fori_loop inner solvers, both Pallas kernels — works unchanged on
this transposed layout.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import ShardedDataset, split_sizes
from cocoa_tpu.parallel import mesh as mesh_lib


def shard_columns(
    data: LibsvmData,
    k: int,
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> Tuple[ShardedDataset, jax.Array]:
    """Partition A's d columns into K balanced contiguous blocks.

    Returns ``(ds, b)``: ``ds`` is the transposed-role ShardedDataset
    (``ds.X[k, j]`` = column ``offs[k]+j`` of A as a dense (n_pad,)
    vector), ``b`` the (n_pad,) regression target (``data.labels``,
    zero-padded — padding rows of A are zero so they touch nothing).

    Dense layout only (a sparse padded-CSC variant would mirror the CSR
    one); intended for lasso-scale d where columns fit per-device HBM.
    """
    n, d = data.n, data.num_features
    np_dtype = np.dtype(dtype)
    sizes = split_sizes(d, k)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    # pad the column count per shard to a sublane multiple (row axis of the
    # shard) and n to a sublane multiple (the kernels' "feature" axis)
    d_shard = -(-int(sizes.max()) // 16) * 16
    n_pad = mesh_lib.pad_features(n, mesh)

    # dense columns: build A^T once (n×d dense), slice per shard
    AT = np.zeros((d, n_pad), dtype=np_dtype)
    row_nnz = np.diff(data.indptr)
    rows = np.repeat(np.arange(n), row_nnz)
    AT[data.indices, rows] = data.values

    X = np.zeros((k, d_shard, n_pad), dtype=np_dtype)
    labels = np.zeros((k, d_shard), dtype=np_dtype)
    mask = np.zeros((k, d_shard), dtype=np_dtype)
    sq_norms = np.zeros((k, d_shard), dtype=np_dtype)
    # f64 accumulation without a full-matrix f64 temporary (AT can be GBs)
    col_sq = np.einsum("ij,ij->i", AT, AT, dtype=np.float64)
    for s in range(k):
        lo, hi = offsets[s], offsets[s + 1]
        m = hi - lo
        X[s, :m] = AT[lo:hi]
        labels[s, :m] = 1.0   # prox rules have no y factor
        mask[s, :m] = 1.0
        sq_norms[s, :m] = col_sq[lo:hi]

    def put(arr, fp_last=False):
        if mesh is not None:
            if fp_last:
                return jax.device_put(arr, mesh_lib.x_sharding(mesh))
            return jax.device_put(
                arr, mesh_lib.sharded_rows(mesh, extra_dims=arr.ndim - 1)
            )
        return jnp.asarray(arr)

    b = np.zeros(n_pad, dtype=np_dtype)
    b[:n] = data.labels
    ds = ShardedDataset(
        layout="dense",
        n=d,                      # "examples" of this transposed view
        num_features=n_pad,       # the replicated vector length
        counts=sizes.astype(np.int64),
        labels=put(labels),
        mask=put(mask),
        sq_norms=put(sq_norms),
        X=put(X, fp_last=True),
    )
    return ds, jnp.asarray(b)
