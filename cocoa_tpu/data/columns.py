"""Column (feature) shards for the primal prox solvers (ProxCoCoA+).

The L1 framework partitions the design matrix A (n × d) by **columns**:
worker k owns a coordinate block x_[k] and its columns A_[k], and the
shared n-vector v = A·x is the replicated state (the exact mirror of the
dual solvers, where examples are sharded and the d-vector w is shared).

This builder reuses :class:`~cocoa_tpu.data.sharding.ShardedDataset` with
the roles transposed: the shard's "rows" are columns a_j (shape (n,)),
``labels`` is all-ones (the prox rules have no y factor), ``sq_norms`` are
column norms ‖a_j‖², ``counts`` the per-shard column counts, and
``num_features`` is n (padded) — the length of the replicated residual
vector r = A·x − b.  Every downstream consumer — the fan-out machinery,
the fori_loop inner solvers, both Pallas kernels — works unchanged on
this transposed layout.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import (
    ShardedDataset,
    segment_sq_norms,
    split_sizes,
)
from cocoa_tpu.parallel import mesh as mesh_lib


def shard_columns(
    data: LibsvmData,
    k: int,
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    layout: str = "auto",
    max_col_nnz: Optional[int] = None,
) -> Tuple[ShardedDataset, jax.Array]:
    """Partition A's d columns into K balanced contiguous blocks.

    Returns ``(ds, b)``: ``ds`` is the transposed-role ShardedDataset
    (shard "row" j = column ``offs[k]+j`` of A), ``b`` the (n_pad,)
    regression target (``data.labels``, zero-padded — padding rows of A
    are zero so they touch nothing).

    Layouts mirror :func:`~cocoa_tpu.data.sharding.shard_dataset`:

    - ``dense``  — each column a dense (n_pad,) vector.
    - ``sparse`` — padded-CSC: per-column (row-index, value) arrays padded
      to the widest column.  Column nnz is often far more skewed than row
      nnz (hot features touch most examples), so the padded width can
      approach n — ``max_col_nnz`` guards against silent blow-up.
    - ``auto``   — sparse below 10% density (matching shard_dataset), but
      only when the widest column keeps the padded encoding smaller than
      dense.
    """
    if layout not in ("auto", "dense", "sparse"):
        raise ValueError(f"layout must be auto|dense|sparse, got {layout!r}")
    n, d = data.n, data.num_features
    np_dtype = np.dtype(dtype)
    sizes = split_sizes(d, k)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    # pad the column count per shard to a sublane multiple (row axis of the
    # shard) and n to a sublane multiple (the kernels' "feature" axis)
    d_shard = -(-int(sizes.max()) // 16) * 16
    n_pad = mesh_lib.pad_features(n, mesh)

    # CSR -> CSC once (also yields per-column nnz for the layout choice)
    row_ids = np.repeat(np.arange(n, dtype=np.int32), np.diff(data.indptr))
    order = np.argsort(data.indices, kind="stable")
    csc_rows = row_ids[order]
    csc_vals = np.asarray(data.values)[order]
    col_nnz = np.bincount(data.indices, minlength=d)
    col_ptr = np.concatenate([[0], np.cumsum(col_nnz)])
    widest = int(col_nnz.max(initial=1))

    if layout == "auto":
        nnz = int(data.indptr[-1])
        density = nnz / max(1, n * d)
        layout = ("sparse" if density < 0.10 and widest * 2 < n_pad
                  and (max_col_nnz is None or widest <= max_col_nnz)
                  else "dense")   # auto's job is to pick a VIABLE layout
        if mesh_lib.has_fp(mesh):
            layout = "dense"
    if layout == "sparse":
        if mesh_lib.has_fp(mesh):
            raise ValueError(
                "sparse column shards cannot combine with an fp mesh"
            )
        if max_col_nnz is not None and widest > max_col_nnz:
            raise ValueError(
                f"widest column has {widest} nonzeros > max_col_nnz="
                f"{max_col_nnz}; hot features make padded-CSC degenerate — "
                f"use layout='dense'"
            )

    labels = np.zeros((k, d_shard), dtype=np_dtype)
    mask = np.zeros((k, d_shard), dtype=np_dtype)
    sq_norms = np.zeros((k, d_shard), dtype=np_dtype)
    col_sq = segment_sq_norms(csc_vals, col_ptr)
    for s in range(k):
        lo, hi = offsets[s], offsets[s + 1]
        m = hi - lo
        labels[s, :m] = 1.0   # prox rules have no y factor
        mask[s, :m] = 1.0
        sq_norms[s, :m] = col_sq[lo:hi]

    kwargs: dict = {}
    if layout == "dense":
        X = np.zeros((k, d_shard, n_pad), dtype=np_dtype)
        for s in range(k):
            lo, hi = offsets[s], offsets[s + 1]
            a, bnd = col_ptr[lo], col_ptr[hi]
            cols = np.repeat(np.arange(hi - lo),
                             col_nnz[lo:hi].astype(np.int64))
            X[s][cols, csc_rows[a:bnd]] = csc_vals[a:bnd]
        kwargs["X"] = X
    else:
        sp_idx = np.zeros((k, d_shard, widest), dtype=np.int32)
        sp_val = np.zeros((k, d_shard, widest), dtype=np_dtype)
        for s in range(k):
            lo, hi = offsets[s], offsets[s + 1]
            a, bnd = col_ptr[lo], col_ptr[hi]
            cols = np.repeat(np.arange(hi - lo),
                             col_nnz[lo:hi].astype(np.int64))
            slots = (np.arange(a, bnd)
                     - np.repeat(col_ptr[lo:hi], col_nnz[lo:hi].astype(np.int64)))
            sp_idx[s][cols, slots] = csc_rows[a:bnd]
            sp_val[s][cols, slots] = csc_vals[a:bnd]
        kwargs["sp_indices"] = sp_idx
        kwargs["sp_values"] = sp_val

    def put(arr, fp_last=False):
        if mesh is not None:
            if fp_last:
                return jax.device_put(arr, mesh_lib.x_sharding(mesh))
            return jax.device_put(
                arr, mesh_lib.sharded_rows(mesh, extra_dims=arr.ndim - 1)
            )
        return jnp.asarray(arr)

    b = np.zeros(n_pad, dtype=np_dtype)
    b[:n] = data.labels
    ds = ShardedDataset(
        layout=layout,
        n=d,                      # "examples" of this transposed view
        num_features=n_pad,       # the replicated vector length
        counts=sizes.astype(np.int64),
        labels=put(labels),
        mask=put(mask),
        sq_norms=put(sq_norms),
        X=put(kwargs["X"], fp_last=True) if "X" in kwargs else None,
        sp_indices=put(kwargs["sp_indices"]) if "sp_indices" in kwargs else None,
        sp_values=put(kwargs["sp_values"]) if "sp_values" in kwargs else None,
    )
    return ds, jnp.asarray(b)
