"""ctypes bridge to the C++ LIBSVM parser (native/libsvm_parser.cpp).

The reference's only native component is JNI-wrapped BLAS (build.sbt:27);
here the native obligation lands on the runtime around the XLA compute path —
starting with ingestion, whose line parsing is the one CPU-bound O(file-size)
step.  The shared library is built by ``make -C native`` (see native/Makefile);
when it is absent, ``available()`` is False and callers fall back to the
pure-Python parser, which is the semantic oracle.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libsvm_parser.so")

_lib = None
_build_tried = False


def _try_build() -> None:
    """One-shot best-effort ``make -C native`` so fresh checkouts get the
    native parser without a manual build step (~1 s; silently falls back to
    the Python parser when no toolchain or the build fails).  Builds to a
    pid-suffixed temp name and atomically renames it in, so concurrent
    processes (a multi-host launch on a shared checkout) never dlopen a
    half-written .so."""
    global _build_tried
    if _build_tried:
        return
    _build_tried = True
    if not os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
        return
    import subprocess

    tmp = f"{_SO_PATH}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, f"OUT={os.path.basename(tmp)}"],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO_PATH)
    except Exception as e:
        # fall back to the Python parser, but say so — a silent fallback
        # reads as "parsing is mysteriously slow" at multi-GB scale
        import warnings

        detail = ""
        if isinstance(e, subprocess.CalledProcessError) and e.stderr:
            detail = f": {e.stderr.decode(errors='replace').strip()[-200:]}"
        warnings.warn(
            f"native LIBSVM parser build failed ({type(e).__name__}{detail}); "
            f"falling back to the pure-Python parser",
            RuntimeWarning,
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO_PATH):
        _try_build()
    if not os.path.exists(_SO_PATH):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        lib.cocoa_parse_libsvm.restype = ctypes.c_void_p
        lib.cocoa_parse_libsvm.argtypes = [ctypes.c_char_p]
        lib.cocoa_parsed_n.restype = ctypes.c_int64
        lib.cocoa_parsed_n.argtypes = [ctypes.c_void_p]
        lib.cocoa_parsed_nnz.restype = ctypes.c_int64
        lib.cocoa_parsed_nnz.argtypes = [ctypes.c_void_p]
        lib.cocoa_parsed_fill.restype = None
        lib.cocoa_parsed_fill.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),  # labels (n)
            ctypes.POINTER(ctypes.c_int64),   # indptr (n+1)
            ctypes.POINTER(ctypes.c_int32),   # indices (nnz)
            ctypes.POINTER(ctypes.c_double),  # values (nnz)
        ]
        lib.cocoa_parsed_free.restype = None
        lib.cocoa_parsed_free.argtypes = [ctypes.c_void_p]
    except (OSError, AttributeError):
        # corrupt/incompatible .so (e.g. an interrupted foreign build):
        # honor the fallback contract — the Python parser takes over
        return None
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def parse_file(path: str, num_features: int) -> Optional[LibsvmData]:
    """Parse via the C++ library; None when the library is not built."""
    lib = _load()
    if lib is None:
        return None
    handle = lib.cocoa_parse_libsvm(path.encode())
    if not handle:
        raise IOError(f"native parser failed to open {path}")
    try:
        n = lib.cocoa_parsed_n(handle)
        nnz = lib.cocoa_parsed_nnz(handle)
        labels = np.empty(n, dtype=np.float64)
        indptr = np.empty(n + 1, dtype=np.int64)
        indices = np.empty(max(nnz, 1), dtype=np.int32)
        values = np.empty(max(nnz, 1), dtype=np.float64)
        lib.cocoa_parsed_fill(
            handle,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
    finally:
        lib.cocoa_parsed_free(handle)
    return LibsvmData(
        labels=labels,
        indptr=indptr,
        indices=indices[:nnz],
        values=values[:nnz],
        num_features=num_features,
    )
