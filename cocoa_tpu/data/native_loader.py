"""ctypes bridge to the C++ LIBSVM parser (native/libsvm_parser.cpp).

The reference's only native component is JNI-wrapped BLAS (build.sbt:27);
here the native obligation lands on the runtime around the XLA compute path —
starting with ingestion, whose line parsing is the one CPU-bound O(file-size)
step.  The shared library is built by ``make -C native`` (see native/Makefile);
when it is absent, ``available()`` is False and callers fall back to the
pure-Python parser, which is the semantic oracle.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libsvm_parser.so")

_lib = None
_build_tried = False


def _try_build() -> None:
    """One-shot best-effort ``make -C native`` so fresh checkouts get the
    native parser without a manual build step (~1 s; silently falls back to
    the Python parser when no toolchain or the build fails).  Builds to a
    pid-suffixed temp name and atomically renames it in, so concurrent
    processes (a multi-host launch on a shared checkout) never dlopen a
    half-written .so."""
    global _build_tried
    if _build_tried:
        return
    _build_tried = True
    if not os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
        return
    import subprocess

    tmp = f"{_SO_PATH}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, f"OUT={os.path.basename(tmp)}"],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _SO_PATH)
    except Exception as e:
        # fall back to the Python parser, but say so — a silent fallback
        # reads as "parsing is mysteriously slow" at multi-GB scale
        import warnings

        detail = ""
        if isinstance(e, subprocess.CalledProcessError) and e.stderr:
            detail = f": {e.stderr.decode(errors='replace').strip()[-200:]}"
        warnings.warn(
            f"native LIBSVM parser build failed ({type(e).__name__}{detail}); "
            f"falling back to the pure-Python parser",
            RuntimeWarning,
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO_PATH):
        _try_build()
    if not os.path.exists(_SO_PATH):
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.cocoa_libsvm_count.restype = ctypes.c_int
        lib.cocoa_libsvm_count.argtypes = [ctypes.c_char_p, i64p, i64p]
        lib.cocoa_libsvm_parse.restype = ctypes.c_int
        lib.cocoa_libsvm_parse.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),  # labels (cap_rows)
            i64p,                             # indptr (cap_rows + 1)
            ctypes.POINTER(ctypes.c_int32),   # indices (cap_pairs)
            ctypes.POINTER(ctypes.c_double),  # values (cap_pairs)
            ctypes.c_int64,                   # cap_rows
            ctypes.c_int64,                   # cap_pairs
            i64p,                             # actual rows out
            i64p,                             # actual pairs out
        ]
        lib.cocoa_libsvm_count_range.restype = ctypes.c_int
        lib.cocoa_libsvm_count_range.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, i64p, i64p,
        ]
        lib.cocoa_libsvm_parse_range.restype = ctypes.c_int
        lib.cocoa_libsvm_parse_range.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,                   # byte range lo
            ctypes.c_int64,                   # byte range hi
            ctypes.POINTER(ctypes.c_double),  # labels (cap_rows)
            i64p,                             # indptr (cap_rows + 1)
            ctypes.POINTER(ctypes.c_int32),   # indices (cap_pairs)
            ctypes.POINTER(ctypes.c_double),  # values (cap_pairs)
            i64p,                             # row_off (cap_rows)
            ctypes.c_int64,                   # cap_rows
            ctypes.c_int64,                   # cap_pairs
            i64p,                             # actual rows out
            i64p,                             # actual pairs out
        ]
    except (OSError, AttributeError):
        # corrupt/incompatible .so (e.g. an interrupted foreign build, or
        # one with the pre-two-pass ABI): honor the fallback contract —
        # the Python parser takes over
        return None
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def parse_file(path: str, num_features: int) -> Optional[LibsvmData]:
    """Parse via the C++ library; None when the library is not built or the
    path cannot be mmap'd (missing / non-regular file — the Python parser
    owns those cases).

    Two passes (see native/libsvm_parser.cpp): a memchr count pass bounds
    the row/pair counts, numpy buffers are allocated ONCE at those bounds,
    and the parse writes directly into them — no intermediate growable
    buffers, no copy-out, so peak RSS is ~the parsed arrays alone even at
    multi-GB input sizes (np.empty pages materialize only as the parser
    writes them)."""
    lib = _load()
    if lib is None:
        return None
    rows_b, pairs_b = ctypes.c_int64(), ctypes.c_int64()
    if lib.cocoa_libsvm_count(path.encode(), ctypes.byref(rows_b),
                              ctypes.byref(pairs_b)) != 0:
        return None
    nb, zb = rows_b.value, pairs_b.value
    labels = np.empty(max(nb, 1), dtype=np.float64)
    indptr = np.empty(nb + 2, dtype=np.int64)
    indices = np.empty(max(zb, 1), dtype=np.int32)
    values = np.empty(max(zb, 1), dtype=np.float64)
    rows, pairs = ctypes.c_int64(), ctypes.c_int64()
    rc = lib.cocoa_libsvm_parse(
        path.encode(),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(max(nb, 1)), ctypes.c_int64(max(zb, 1)),
        ctypes.byref(rows), ctypes.byref(pairs),
    )
    if rc != 0:
        # -1: file vanished between the passes; 1: it GREW past the counted
        # capacities (truncated output) — either way the Python parser owns
        # the racing-writer case
        return None
    n, nnz = rows.value, pairs.value
    return LibsvmData(
        labels=labels[:n],
        indptr=indptr[:n + 1],
        indices=indices[:nnz],
        values=values[:nnz],
        num_features=num_features,
    )


def parse_range(path: str, lo: int, hi: int,
                num_features: int) -> "Optional[tuple]":
    """Rows owned by the byte range [lo, hi) via the C++ library (the
    ownership rule lives in native/libsvm_parser.cpp resolve_span: a line
    belongs to the range containing its first byte; the last owned line
    parses to its own end even past ``hi``).  Returns ``(LibsvmData,
    row_off)`` — ``row_off[i]`` the absolute byte offset of row i's line
    start — or None when the library is not built, the path cannot be
    mmap'd, or the file changed between the count and parse passes (the
    Python range parser owns those cases)."""
    lib = _load()
    if lib is None:
        return None
    rows_b, pairs_b = ctypes.c_int64(), ctypes.c_int64()
    if lib.cocoa_libsvm_count_range(path.encode(), lo, hi,
                                    ctypes.byref(rows_b),
                                    ctypes.byref(pairs_b)) != 0:
        return None
    nb, zb = rows_b.value, pairs_b.value
    # jaxlint: allow=f64 -- exact text→f64 parse buffers (host-side);
    # device arrays are cast to the compute dtype downstream
    labels = np.empty(max(nb, 1), dtype=np.float64)
    indptr = np.empty(nb + 2, dtype=np.int64)
    indices = np.empty(max(zb, 1), dtype=np.int32)
    # jaxlint: allow=f64 -- same exact-parse buffer as labels above
    values = np.empty(max(zb, 1), dtype=np.float64)
    row_off = np.empty(max(nb, 1), dtype=np.int64)
    rows, pairs = ctypes.c_int64(), ctypes.c_int64()
    rc = lib.cocoa_libsvm_parse_range(
        path.encode(), lo, hi,
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        row_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(max(nb, 1)), ctypes.c_int64(max(zb, 1)),
        ctypes.byref(rows), ctypes.byref(pairs),
    )
    if rc != 0:
        return None
    n, nnz = rows.value, pairs.value
    data = LibsvmData(
        labels=labels[:n],
        indptr=indptr[:n + 1],
        indices=indices[:nnz],
        values=values[:nnz],
        num_features=num_features,
    )
    return data, row_off[:n]
