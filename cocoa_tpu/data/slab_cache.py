"""Shard-granular persistent slab cache: ingest goes free after first touch.

The compile cache (utils/compile_cache.py) made the SECOND run's XLA
compiles free; ingest stayed the dominant fixed cost — every process
re-parsed the LIBSVM text on every start (benchmarks/RESULTS.md
"Fixed-cost breakdown").  The CoCoA premise (arXiv:1409.1458) is that
local data is touched ONCE and then reused across many cheap rounds;
elastic restarts (PR 9), serve-while-train trainer relaunches (PR 13),
fleet manifests sharing a dataset ref (PR 12), bench sweeps, and CI all
violated that premise at the process level.

This module closes it (docs/DESIGN.md §18).  After a cold parse, each
built shard's DEVICE-READY host slabs — the exact ``_build_shard_slabs``
output: labels/mask/sq_norms plus padded-CSR index/value arrays, the
hybrid hot-panel + cold-residual pair, the dense ``--evalDense`` twin —
are written as memmap-able ``.npy`` artifacts under ``--ingestCache=DIR``,
alongside the pass-1 index (global column histogram + row offsets/nnz)
and the hybrid layout meta (the exchanged residual width).  Warm runs
``np.load(mmap_mode="r")`` the slabs straight into ``device_put``: zero
parse, zero slab build, RSS shared through the page cache across
concurrent processes mapping the same artifact.

**Key derivation** (the invalidation contract):

- the *file tag* hashes ``(st_dev, st_ino, st_size, st_mtime_ns,
  num_features, PARSER_VERSION)``.  ``st_ino`` is load-bearing: an
  atomic-rename rewrite on a coarse-mtime filesystem changes the inode
  even when mtime_ns aliases (the checkpoint-validate lesson from
  PR 13); any content change flips size or mtime_ns or inode.
- the *shard tag* adds the full layout resolution — layout kind, K,
  n_shard, padded width, hot-panel width, eval-twin flag, padded d,
  dtype, LAYOUT_VERSION — plus the shard id ``s``.  Because the key is
  the SHARD (0..K-1), not the process geometry, an elastic shrink's
  survivors re-map their inherited shards warm, and a T-tenant fleet
  maps one build T times.

**Single-writer protocol**: an artifact is a directory written to a
writer-unique (pid + uuid — pids collide across hosts sharing a cache
dir) temp name and atomically ``os.rename``\\ d into place — one writer
wins, the loser reads the winner's (bit-identical) artifact.  A rename
onto an existing artifact fails and the temp is discarded; a reader
never sees a half-written directory.  Publish failures (ENOSPC, lost
permission) degrade to uncached operation with one warning — the cache
is an accelerator, never a dependency.

**Corruption**: every load re-validates shapes/dtypes/field sets against
the artifact's own manifest; a torn, truncated, or short file (the
``tests/_faults.truncate_newest_cache_artifact`` fault) fails the load,
fires ``on_corrupt`` (the typed ``ingest_cache_corrupt`` event), evicts
the bad artifact best-effort, and the caller falls back to a cold parse.

**What is never cached**: device arrays (placement is per-run), the
lasso column shards (the transpose re-buckets every row per run), fleet
``(T, K, …)`` stacks (tenant-geometry-keyed; fleet dedupe is the
in-process ref memo in data/fleet.py), and anything keyed to a mesh —
shard slabs are geometry-free by construction.

Deliberately numpy-only (no jax import): the ingest benchmarks measure
warm loads in clean subprocesses whose RSS must reflect the mapped
artifacts, not a backend baseline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import uuid
from typing import Callable, Optional

import numpy as np

# bump when the PARSE semantics change (what rows/pairs a byte range
# yields): invalidates every artifact derived from parsed text
PARSER_VERSION = 1
# bump when the SLAB layout changes (the _build_shard_slabs output
# contract: field set, padding, dtypes): invalidates shard artifacts
LAYOUT_VERSION = 1


def _digest(parts: dict) -> str:
    blob = json.dumps(parts, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:20]


def _tmp_name(final: str) -> str:
    """A writer-unique temp name.  pid alone is NOT unique across hosts
    sharing one cache directory (the multi-host elastic gang over NFS —
    two workers with the same pid would interleave writes into one temp
    dir and publish a torn artifact); the uuid component makes every
    writer's staging area its own."""
    return f"{final}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"


def _atomic_publish(tmp_dir: str, final_dir: str) -> bool:
    """Atomically rename a fully-written temp artifact into place.
    Returns True when THIS writer won; False when another writer already
    published (the temp is discarded — the artifacts are bit-identical
    by construction, so the loser simply reads the winner's)."""
    try:
        os.rename(tmp_dir, final_dir)
        return True
    except OSError:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        return False


def _write_json_atomic(path: str, obj: dict) -> None:
    tmp = _tmp_name(path)
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


@dataclasses.dataclass
class CachedStats:
    """The cached pass-1 facts of one source file: enough to resolve
    ``--layout=auto`` / ``--hotCols=auto`` / ``--evalDense=auto`` and to
    key every shard artifact WITHOUT parsing a byte.  ``row_off`` /
    ``row_nnz`` are present only on index artifacts stored by a pass-1
    scan (``has_rows``) — the whole-file populate path has no byte
    offsets to record, and a warm full-hit load never needs them."""

    n: int
    file_bytes: int
    total_nnz: int
    max_row_nnz: int
    hist: np.ndarray                 # (d,) int64 global column histogram
    has_rows: bool
    row_off: Optional[np.ndarray] = None   # (n+1,) int64 when has_rows
    row_nnz: Optional[np.ndarray] = None   # (n,) int64 when has_rows


class SlabCache:
    """One ``--ingestCache=DIR`` root.  Thread-compatible; process-safe
    through the atomic-rename protocol.  Counters accumulate across every
    handle/view created from this instance (the telemetry the CLI's
    ``ingest_cache`` event reports)."""

    def __init__(self, root: str,
                 on_corrupt: Optional[Callable] = None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.on_corrupt = on_corrupt
        self.shard_hits = 0
        self.shard_misses = 0
        self.corrupt_total = 0
        self.bytes_mapped = 0
        self.store_failures = 0

    def _store_failed(self, what: str, err: Exception) -> None:
        """Publish failures (ENOSPC, lost permission, a yanked volume)
        degrade to UNCACHED operation — the data is already parsed in
        memory and the run must proceed; a cache is an accelerator, not
        a dependency.  Warn once so a dead cache volume is visible."""
        self.store_failures += 1
        if self.store_failures == 1:
            import warnings

            warnings.warn(
                f"--ingestCache could not publish {what} "
                f"({type(err).__name__}: {err}); continuing uncached — "
                f"check the cache volume", RuntimeWarning)

    def for_file(self, path: str, num_features: int) -> "FileCacheHandle":
        """Bind the cache to one source file's CURRENT identity (stat).
        Raises OSError when the file cannot be stat'd — the cold parse
        would fail on the same file, so callers share one error path."""
        st = os.stat(path)
        return FileCacheHandle(self, path, num_features, st)

    def _corrupt(self, path: str, artifact: str, reason: str) -> None:
        self.corrupt_total += 1
        if self.on_corrupt is not None:
            try:
                self.on_corrupt(path=path, artifact=artifact,
                                reason=reason)
            except Exception:
                pass  # telemetry must never turn a recoverable cache
                # miss into a crash


class FileCacheHandle:
    """The per-source-file face of the cache: the index/stats artifact,
    the hybrid layout meta, the cold-cost sidecar, and the
    :class:`ShardCacheView` factory."""

    def __init__(self, cache: SlabCache, path: str, num_features: int,
                 st: os.stat_result):
        self.cache = cache
        self.path = path
        self.num_features = int(num_features)
        self.file_tag = _digest({
            "kind": "file",
            "dev": int(st.st_dev),
            "ino": int(st.st_ino),
            "size": int(st.st_size),
            "mtime_ns": int(st.st_mtime_ns),
            "num_features": self.num_features,
            "parser": PARSER_VERSION,
        })
        self.file_bytes = int(st.st_size)

    # --- the pass-1 index artifact ---------------------------------------

    def _index_dir(self, full: bool) -> str:
        # two artifact kinds, never overwritten in place: "-full" carries
        # the row offset/nnz arrays a streaming pass-2 needs, "-stats"
        # is the whole-path populate (histogram + scalars only).  The
        # loader prefers full; a later scan upgrades stats->full by
        # publishing the OTHER name (no replace-in-place race).
        return os.path.join(self.cache.root,
                            f"index-{self.file_tag}-"
                            f"{'full' if full else 'stats'}")

    def store_index(self, *, hist, n: int, total_nnz: int,
                    max_row_nnz: int, row_off=None, row_nnz=None) -> None:
        full = row_off is not None
        final = self._index_dir(full)
        if os.path.isdir(final):
            return
        tmp = _tmp_name(final)
        try:
            os.makedirs(tmp, exist_ok=True)
            np.save(os.path.join(tmp, "hist.npy"),
                    np.asarray(hist, np.int64))
            if full:
                np.save(os.path.join(tmp, "row_off.npy"),
                        np.asarray(row_off, np.int64))
                np.save(os.path.join(tmp, "row_nnz.npy"),
                        np.asarray(row_nnz, np.int64))
            _write_json_atomic(os.path.join(tmp, "meta.json"), {
                "n": int(n), "file_bytes": self.file_bytes,
                "total_nnz": int(total_nnz),
                "max_row_nnz": int(max_row_nnz), "has_rows": bool(full),
            })
        except OSError as e:
            shutil.rmtree(tmp, ignore_errors=True)
            self.cache._store_failed(os.path.basename(final), e)
            return
        _atomic_publish(tmp, final)

    def load_index(self) -> Optional[CachedStats]:
        """The cached stats (preferring the full index), or None."""
        for full in (True, False):
            d = self._index_dir(full)
            if not os.path.isdir(d):
                continue
            try:
                with open(os.path.join(d, "meta.json")) as f:
                    meta = json.load(f)
                hist = np.load(os.path.join(d, "hist.npy"),
                               mmap_mode="r")
                if hist.shape != (self.num_features,):
                    raise ValueError(
                        f"hist shape {hist.shape} != "
                        f"({self.num_features},)")
                out = CachedStats(
                    n=int(meta["n"]),
                    file_bytes=int(meta["file_bytes"]),
                    total_nnz=int(meta["total_nnz"]),
                    max_row_nnz=int(meta["max_row_nnz"]),
                    hist=np.asarray(hist), has_rows=bool(full))
                if full:
                    row_off = np.load(os.path.join(d, "row_off.npy"),
                                      mmap_mode="r")
                    row_nnz = np.load(os.path.join(d, "row_nnz.npy"),
                                      mmap_mode="r")
                    if (row_off.shape != (out.n + 1,)
                            or row_nnz.shape != (out.n,)):
                        raise ValueError("row index shape mismatch")
                    out.row_off = np.asarray(row_off)
                    out.row_nnz = np.asarray(row_nnz)
                return out
            except (OSError, ValueError, KeyError) as e:
                self.cache._corrupt(self.path, os.path.basename(d),
                                    f"{type(e).__name__}: {e}")
                shutil.rmtree(d, ignore_errors=True)
        return None

    # --- the hybrid layout meta (the exchanged residual width) -----------

    def _hybrid_meta_path(self, n_hot: int) -> str:
        tag = _digest({"kind": "hybridmeta", "file": self.file_tag,
                       "n_hot": int(n_hot), "layout": LAYOUT_VERSION})
        return os.path.join(self.cache.root, f"hybrid-{tag}.json")

    def store_hybrid_meta(self, n_hot: int, resid_max: int) -> None:
        try:
            _write_json_atomic(self._hybrid_meta_path(n_hot),
                               {"resid_max": int(resid_max),
                                "n_hot": int(n_hot)})
        except OSError as e:
            self.cache._store_failed("hybrid meta", e)

    def load_hybrid_meta(self, n_hot: int) -> Optional[int]:
        path = self._hybrid_meta_path(n_hot)
        try:
            with open(path) as f:
                meta = json.load(f)
            return int(meta["resid_max"])
        except FileNotFoundError:
            return None
        except (OSError, ValueError, KeyError) as e:
            self.cache._corrupt(self.path, os.path.basename(path),
                                f"{type(e).__name__}: {e}")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None

    # --- the cold-cost sidecar (the seconds_saved estimate) --------------

    def _cost_path(self) -> str:
        return os.path.join(self.cache.root, f"cost-{self.file_tag}.json")

    def store_cost(self, seconds: float) -> None:
        try:
            _write_json_atomic(self._cost_path(),
                               {"cold_seconds": float(seconds)})
        except OSError as e:
            self.cache._store_failed("cost sidecar", e)

    def load_cost(self) -> float:
        try:
            with open(self._cost_path()) as f:
                return float(json.load(f)["cold_seconds"])
        except (OSError, ValueError, KeyError):
            return 0.0

    # --- the per-shard slab view -----------------------------------------

    def view(self, *, layout: str, k: int, n_shard: int, width: int,
             n_hot: int, d: int, dtype, eval_dense: bool
             ) -> "ShardCacheView":
        return ShardCacheView(self, layout=layout, k=k, n_shard=n_shard,
                              width=width, n_hot=n_hot, d=d, dtype=dtype,
                              eval_dense=eval_dense)


class ShardCacheView:
    """One fully-resolved layout's shard artifacts: ``load(s)`` /
    ``store(s, slab)`` over the ``_build_shard_slabs`` field dicts."""

    def __init__(self, handle: FileCacheHandle, *, layout: str, k: int,
                 n_shard: int, width: int, n_hot: int, d: int, dtype,
                 eval_dense: bool):
        self.handle = handle
        self.cache = handle.cache
        np_dtype = np.dtype(dtype)
        self.fields = ["labels", "mask", "sq_norms"]
        if layout == "dense":
            self.fields.append("X")
        else:
            if n_hot:
                self.fields.append("X_hot")
            self.fields += ["sp_indices", "sp_values"]
            if eval_dense:
                self.fields.append("X_eval")
        self.layout_tag = _digest({
            "kind": "slab", "file": handle.file_tag, "layout": layout,
            "k": int(k), "n_shard": int(n_shard), "width": int(width),
            "n_hot": int(n_hot), "d": int(d), "dtype": np_dtype.name,
            "eval_dense": bool(eval_dense), "version": LAYOUT_VERSION,
        })

    def _shard_dir(self, s: int) -> str:
        return os.path.join(self.cache.root,
                            f"slab-{self.layout_tag}-s{int(s):05d}")

    def load(self, s: int, *, mmap: bool = True) -> Optional[dict]:
        """Shard ``s``'s slab dict (memmap'd by default), or None on a
        miss.  Any validation failure — torn file, shape/dtype/field
        drift — counts as CORRUPT: the event fires, the artifact is
        evicted, and None sends the caller to the cold parse."""
        d = self._shard_dir(s)
        if not os.path.isdir(d):
            self.cache.shard_misses += 1
            return None
        try:
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
            if sorted(meta.get("fields", {})) != sorted(self.fields):
                raise ValueError(
                    f"field set {sorted(meta.get('fields', {}))} != "
                    f"expected {sorted(self.fields)}")
            out = {}
            nbytes = 0
            for name in self.fields:
                spec = meta["fields"][name]
                arr = np.load(os.path.join(d, f"{name}.npy"),
                              mmap_mode="r" if mmap else None)
                if (list(arr.shape) != list(spec["shape"])
                        or arr.dtype.name != spec["dtype"]):
                    raise ValueError(
                        f"{name}: {arr.shape}/{arr.dtype.name} != "
                        f"manifest {spec['shape']}/{spec['dtype']}")
                # touch the first element: a truncated data segment that
                # survived the header check must fail HERE, not later
                # inside device_put
                if arr.size:
                    arr[(0,) * arr.ndim]
                out[name] = arr
                nbytes += arr.nbytes
            self.cache.shard_hits += 1
            self.cache.bytes_mapped += nbytes
            return out
        except (OSError, ValueError, KeyError) as e:
            self.cache.shard_misses += 1
            self.cache._corrupt(self.handle.path, os.path.basename(d),
                                f"{type(e).__name__}: {e}")
            shutil.rmtree(d, ignore_errors=True)
            return None

    def store(self, s: int, slab: dict) -> None:
        """Publish shard ``s``'s slab dict (atomic rename, one writer
        wins).  Field order/set is validated against the view so a
        builder drift cannot poison the cache silently."""
        if sorted(slab) != sorted(self.fields):
            raise ValueError(
                f"slab fields {sorted(slab)} != view fields "
                f"{sorted(self.fields)} — the cache key no longer "
                f"matches the builder output (bump LAYOUT_VERSION)")
        final = self._shard_dir(s)
        if os.path.isdir(final):
            return
        tmp = _tmp_name(final)
        try:
            os.makedirs(tmp, exist_ok=True)
            meta = {"fields": {}, "shard": int(s)}
            for name in self.fields:
                arr = np.ascontiguousarray(slab[name])
                np.save(os.path.join(tmp, f"{name}.npy"), arr)
                meta["fields"][name] = {"shape": list(arr.shape),
                                        "dtype": arr.dtype.name}
            _write_json_atomic(os.path.join(tmp, "meta.json"), meta)
        except OSError as e:
            # a publish failure (ENOSPC, lost permission) must degrade
            # to uncached operation, not kill a run whose data is
            # already parsed — the read-side contract's write twin
            shutil.rmtree(tmp, ignore_errors=True)
            self.cache._store_failed(os.path.basename(final), e)
            return
        _atomic_publish(tmp, final)
