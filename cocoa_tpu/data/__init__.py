from cocoa_tpu.data.libsvm import load_libsvm, LibsvmData  # noqa: F401
from cocoa_tpu.data.sharding import ShardedDataset, shard_dataset  # noqa: F401
