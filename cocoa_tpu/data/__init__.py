from cocoa_tpu.data.libsvm import (  # noqa: F401
    load_libsvm,
    load_libsvm_range,
    LibsvmData,
)
from cocoa_tpu.data.sharding import (  # noqa: F401
    ShardedDataset,
    resolve_layout,
    resolve_layout_stats,
    shard_dataset,
)
from cocoa_tpu.data.hybrid import resolve_hot_cols  # noqa: F401
from cocoa_tpu.data.ingest import (  # noqa: F401
    IngestIndex,
    IngestReport,
    build_index,
    resolve_ingest_mode,
    stream_shard_dataset,
)
from cocoa_tpu.data.slab_cache import SlabCache  # noqa: F401
from cocoa_tpu.data.columns import shard_columns  # noqa: F401
from cocoa_tpu.data.fleet import (  # noqa: F401
    FleetDataset,
    TenantSpec,
    build_fleet,
    load_fleet_manifest,
    synth_fleet_specs,
    write_fleet_manifest,
)
from cocoa_tpu.data.synth import (  # noqa: F401
    synth_dense,
    synth_dense_sharded,
    synth_sparse,
    write_libsvm,
)
