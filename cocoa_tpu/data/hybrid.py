"""Hot/cold column split for the sparse (padded-CSR) layout.

Sparse text workloads (rcv1-like) have Zipf column popularity: a small
set of globally hot columns carries the majority of all nonzeros (the
hottest ~2-4k of rcv1's 47k columns cover ~3/4 of the entries).  The
sparse kernels pay a *scalar-issue-bound* merge loop per nonzero
(~6 scalar ops each — docs/DESIGN.md §3d), so every nonzero moved out of
the streams and into a dense panel is paid for at MXU/VPU rates instead.

The split (docs/DESIGN.md §3b-vi):

- a **hot panel** ``X_hot`` (K, n_shard, n_hot): each row's values at the
  globally hottest ``n_hot`` columns, dense (zero where the row lacks the
  column), lane-aligned (n_hot a multiple of 128).  ``hot_cols``
  (n_hot,) maps panel lanes back to original column ids.
- a **cold residual** padded-CSR holding only the surviving tail
  nonzeros — the scalar merge loops shrink proportionally to
  1 − coverage, and the padded width drops with the tail's max.

The panel is **global and static** — chosen ONCE from the whole
dataset's column-frequency histogram, identical for every shard and
every sampled block.  This is what survives the §3b-iv refutation of
per-block compact supports: a 128-row block still touches ~4.4k distinct
columns, but under Zipf most of those *occurrences* land in the same few
thousand globally-hot columns, so one fixed panel serves every block.

The split is a partition of each row's nonzeros by column — a
permutation of every per-nonzero sum the solvers compute — so the math
is unchanged (identical in real arithmetic; floating point reassociates,
so trajectories are pinned at f64 against the sequential chain exactly
like the round-6 kernel was, tests/test_hybrid_sparse.py).

``--hotCols=auto|off|<n>`` resolves through :func:`resolve_hot_cols`
under explicit HBM accounting: the panel costs
``K · n_shard · n_hot · itemsize`` bytes (~166 MB at rcv1 scale with
n_hot=2048), reported up front and rejected when it exceeds the budget.
"""

from __future__ import annotations

import numpy as np

from cocoa_tpu.data.libsvm import LibsvmData

PANEL_LANES = 128            # panel width granularity (TPU lane width)
HOT_COVERAGE_TARGET = 0.75   # --hotCols=auto aims at this nonzero coverage
HOT_PANEL_HBM_BUDGET = 2 << 30   # 2 GiB — the panel is capacity buying
                                 # scalar-port relief, same trade as the
                                 # eval twin (docs/DESIGN.md §3d-ii)


def pad_panel(n: int) -> int:
    """Panel width rounded up to whole 128-lane blocks (padded columns
    carry value 0 everywhere and column id 0 — inert in every dot,
    scatter, and gather, the standing inertness trick)."""
    return -(-n // PANEL_LANES) * PANEL_LANES


def column_counts(data: LibsvmData) -> np.ndarray:
    """(d,) global column-frequency histogram — how many rows carry each
    column.  The measured basis every resolution decision reads."""
    return np.bincount(data.indices, minlength=data.num_features)


def hottest_columns(counts: np.ndarray, n_hot: int) -> np.ndarray:
    """The ``n_hot`` most frequent column ids, returned SORTED ASCENDING
    (deterministic: count descending, id ascending tiebreak, then sorted
    by id so the panel's lane order is reproducible and gathers walk w
    monotonically)."""
    n_hot = min(int(n_hot), len(counts))
    if n_hot <= 0:
        return np.zeros(0, dtype=np.int32)
    order = np.lexsort((np.arange(len(counts)), -counts))
    return np.sort(order[:n_hot]).astype(np.int32)


def hot_rank(num_features: int, hot_ids: np.ndarray) -> np.ndarray:
    """(d,) lookup: column id -> panel lane, or -1 for cold columns."""
    rank = np.full(num_features, -1, dtype=np.int64)
    rank[hot_ids] = np.arange(len(hot_ids))
    return rank


def split_stats(data: LibsvmData, hot_ids: np.ndarray) -> dict:
    """Measured facts of one candidate split: nonzero coverage and the
    residual's per-row nnz distribution (mean and max — the max IS the
    residual padded-CSR width the streams will pay)."""
    rank = hot_rank(data.num_features, hot_ids)
    is_hot = rank[data.indices] >= 0
    row_nnz = np.diff(data.indptr)
    rows = np.repeat(np.arange(data.n, dtype=np.int64), row_nnz)
    cold_per_row = np.bincount(rows[~is_hot], minlength=data.n)
    total = max(1, int(data.indptr[-1]))
    return {
        "coverage": float(is_hot.sum() / total),
        "residual_mean_nnz": float(cold_per_row.mean()) if data.n else 0.0,
        "residual_max_nnz": int(cold_per_row.max(initial=0)),
        "total_nnz": int(data.indptr[-1]),
    }


def panel_bytes(n_hot: int, k: int, n_shard: int, itemsize: int) -> int:
    """HBM cost of the (K, n_shard, n_hot) hot panel."""
    return k * n_shard * n_hot * itemsize


def normalize_spec(spec) -> str:
    """One normalization of the ``--hotCols`` flag value, shared by the
    whole-file and streaming resolution paths."""
    return ("off" if spec is None else str(spec)).strip().lower()


def resolve_hot_width(
    spec,
    counts: np.ndarray,
    n: int,
    k: int,
    dtype,
    *,
    coverage_target: float = HOT_COVERAGE_TARGET,
    budget: "int | None" = None,
) -> int:
    """``--hotCols=auto|off|<n>`` → lane-padded panel width (0 = off),
    from the column histogram alone — no parsed dataset required, so
    streaming ingest resolves the SAME width from its assembled partial
    histograms bit-identically to the whole-file build.  Raises for an
    explicit width over the HBM budget (loud, with the accounting)."""
    from cocoa_tpu.data.sharding import pad_rows, split_sizes

    if budget is None:
        budget = HOT_PANEL_HBM_BUDGET
    spec_s = normalize_spec(spec)
    if spec_s in ("off", "false", "0", "none", ""):
        return 0
    d = len(counts)
    itemsize = np.dtype(dtype).itemsize
    n_shard = pad_rows(int(split_sizes(n, k).max())) if k > 0 else 0
    per_lane_block = panel_bytes(PANEL_LANES, k, n_shard, itemsize)

    if spec_s == "auto":
        desc = np.sort(counts)[::-1]
        cums = np.cumsum(desc)
        total = max(1, int(cums[-1]) if len(cums) else 1)
        need = int(np.searchsorted(cums, coverage_target * total)) + 1
        real = min(need, d)
        width = pad_panel(real)
        max_width = (budget // per_lane_block) * PANEL_LANES \
            if per_lane_block > 0 else width
        width = min(width, max_width)
        if width < PANEL_LANES:
            # not even one lane block fits the budget — keep the streams
            return 0
        return int(width)

    try:
        want = int(spec_s)
    except ValueError:
        raise ValueError(f"--hotCols must be auto|off|<n>, "
                         f"got {spec!r}") from None
    if want <= 0:
        raise ValueError(f"--hotCols must be auto|off|<positive n>, "
                         f"got {spec!r}")
    width = pad_panel(min(want, d))
    pb = panel_bytes(width, k, n_shard, itemsize)
    if pb > budget:
        raise ValueError(
            f"--hotCols={want}: the hot panel needs {pb / 2**20:.1f} MiB "
            f"of HBM (K={k} x n_shard={n_shard} x {width} lanes x "
            f"{itemsize} B) against the {budget / 2**20:.0f} MiB "
            f"budget; lower --hotCols or use --hotCols=auto"
        )
    return int(width)


def stats_from_counts(
    spec,
    counts: np.ndarray,
    width: int,
    residual_max_nnz: int,
    n: int,
    k: int,
    dtype,
) -> dict:
    """The layout-split manifest record from the column histogram plus
    the (exchanged) residual per-row max — the streaming twin of
    :func:`split_stats`: coverage and residual mean derive from exact
    integer totals, so the record is bit-identical to the whole-file one
    for the same dataset."""
    from cocoa_tpu.data.sharding import pad_rows, split_sizes

    total = max(1, int(counts.sum()))
    if width:
        hot_total = int(counts[hottest_columns(counts, width)].sum())
    else:
        hot_total = 0
    n_shard = pad_rows(int(split_sizes(n, k).max())) if k > 0 else 0
    itemsize = np.dtype(dtype).itemsize
    spec_s = normalize_spec(spec)
    if width == 0 and spec_s != "auto":
        spec_s = "off"  # the whole off-family records as "off"
    return {
        "coverage": float(hot_total / total) if width else 0.0,
        "residual_mean_nnz": (
            float((total - hot_total) / n) if n else 0.0),
        "residual_max_nnz": int(residual_max_nnz),
        "total_nnz": int(counts.sum()),
        "spec": spec_s,
        "hot_cols": int(width),
        "panel_bytes": panel_bytes(width, k, n_shard, itemsize),
    }


def resolve_hot_cols(
    spec,
    data: LibsvmData,
    k: int,
    dtype,
    *,
    coverage_target: float = HOT_COVERAGE_TARGET,
    budget: "int | None" = None,   # None -> HOT_PANEL_HBM_BUDGET (read at
                                   # call time so tests can patch it)
):
    """Resolve ``--hotCols=auto|off|<n>`` to a panel width, with explicit
    HBM accounting.  Returns ``(n_hot, stats)``: ``n_hot`` the lane-padded
    panel width (0 = keep the pure stream layout), ``stats`` the
    machine-readable split record the run manifest carries (hot_cols,
    coverage, residual_mean_nnz, residual_max_nnz, panel_bytes).

    - ``auto``: the smallest 128-multiple panel whose hottest columns
      cover ``coverage_target`` of all nonzeros (measured from the column
      histogram), clamped DOWN to the largest width the HBM ``budget``
      admits; resolves to 0 (off) when even one 128-lane block does not
      fit.
    - ``<n>``: explicit width (padded up to 128 lanes), REJECTED with the
      accounting when the panel exceeds the budget — an explicit ask that
      cannot be honored must fail loudly, not silently degrade.
    - ``off``/``0``: the unchanged stream layout (the A/B control).

    The width itself comes from :func:`resolve_hot_width` (histogram
    only); streaming ingest calls that directly with its assembled
    histogram and fills the stats via :func:`stats_from_counts`.
    """
    from cocoa_tpu.data.sharding import pad_rows, split_sizes

    spec_s = normalize_spec(spec)
    counts = column_counts(data)
    width = resolve_hot_width(spec, counts, data.n, k, dtype,
                              coverage_target=coverage_target,
                              budget=budget)
    if width == 0:
        off_spec = spec_s if spec_s == "auto" else "off"
        return 0, {"spec": off_spec, "hot_cols": 0, "coverage": 0.0,
                   "residual_mean_nnz": (float(np.diff(data.indptr).mean())
                                         if data.n else 0.0),
                   "residual_max_nnz": int(np.diff(data.indptr).max(initial=0)),
                   "panel_bytes": 0,
                   "total_nnz": int(data.indptr[-1])}

    hot_ids = hottest_columns(counts, width)
    n_shard = pad_rows(int(split_sizes(data.n, k).max())) if k > 0 else 0
    stats = split_stats(data, hot_ids)
    stats.update(spec=spec_s, hot_cols=int(width),
                 panel_bytes=panel_bytes(width, k, n_shard,
                                         np.dtype(dtype).itemsize))
    return int(width), stats


def split_slab(
    data: LibsvmData,
    lo: int,
    hi: int,
    n_shard: int,
    rank: np.ndarray,      # hot_rank(d, hot_ids)
    n_hot: int,            # lane-padded panel width
    width_res: int,        # residual padded-CSR width (global max cold nnz)
    np_dtype,
):
    """One shard's hybrid slabs for rows [lo, hi): the dense hot panel
    plus the cold-residual padded-CSR.  The residual preserves the
    original within-row slot order of the surviving nonzeros, so the
    stream kernels' per-slot summation order over the tail is exactly the
    pre-split order with the hot entries deleted."""
    m = hi - lo
    a, b = data.indptr[lo], data.indptr[hi]
    row_nnz = np.diff(data.indptr[lo:hi + 1])
    rows = np.repeat(np.arange(m, dtype=np.int64), row_nnz)
    cols = np.asarray(data.indices[a:b], dtype=np.int64)
    vals = np.asarray(data.values[a:b])
    lanes = rank[cols]
    hot = lanes >= 0

    X_hot = np.zeros((n_shard, n_hot), np_dtype)
    X_hot[rows[hot], lanes[hot]] = vals[hot]

    crows = rows[~hot]
    cold_per_row = np.bincount(crows, minlength=m)
    cptr = np.concatenate([[0], np.cumsum(cold_per_row)])
    slots = np.arange(len(crows), dtype=np.int64) - cptr[crows]
    spi = np.zeros((n_shard, width_res), np.int32)
    spv = np.zeros((n_shard, width_res), np_dtype)
    spi[crows, slots] = cols[~hot]
    spv[crows, slots] = vals[~hot]
    return X_hot, spi, spv
