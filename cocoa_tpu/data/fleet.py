"""Fleet manifests and stacked multi-tenant datasets.

"Millions of users" also means millions of *models*: per-tenant SVMs,
one-vs-rest heads, regularization-path sweeps — thousands of independent,
statically-shaped problems that each paid a full compile + round loop
through the solo path.  The fleet path batches them: a ``--fleet``
manifest (one tenant per JSONL line, validated by telemetry/schema.py as
its own dialect) is loaded into a :class:`FleetDataset` whose arrays
carry a leading tenant axis — ``(T, K, n_shard, …)`` slabs built by the
SAME :func:`cocoa_tpu.data.sharding._build_shard_slabs` every other
ingest path uses, so a tenant's slab is bit-identical to the shards a
solo run of that tenant would build.

Static-shape contract: XLA needs ONE shape for the whole fleet, so every
tenant pads to the common ``n_shard`` (the fleet max, rows masked — exact
by the standing padding convention: masked rows are never sampled and
contribute exactly 0 to every masked reduction) and must agree on d,
layout, and H (the per-round local-step count is the index-table width).
Tenants that cannot pad to a common static shape are REJECTED with the
numbers, not silently truncated.

What may vary per tenant: the dataset itself, λ (the regularization-path
axis), and the duality-gap target.  What must be uniform: d, layout
(dense in v1 — the padded-CSR stream kernels own their shard axis and
cannot ride the tenant vmap), H, loss/smoothing (a per-tenant loss would
need per-lane branch selection, which a vmapped ``lax.switch`` pays for
by executing every branch on every lane — docs/DESIGN.md §16).

Dataset refs: ``synth:dense:n=<rows>,d=<features>[,seed=S][,flip=F]``
generates a planted-separator tenant (data/synth.py), or a LIBSVM file
path (the manifest line then needs ``num_features``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np

from cocoa_tpu.data.sharding import (
    ShardedDataset, _build_shard_slabs, pad_rows, segment_sq_norms,
    split_sizes,
)


@dataclasses.dataclass
class TenantSpec:
    """One manifest line: a tenant's problem definition."""

    tenant: str                       # unique tenant id
    dataset: str                      # synth:... spec or a LIBSVM path
    lam: float                        # λ — the per-tenant regularization
    gap_target: Optional[float] = None  # duality-gap certificate target
    num_features: int = 0             # required for file-backed datasets
    loss: str = "hinge"               # must be uniform across the fleet
    smoothing: float = 1.0            # must be uniform across the fleet


def parse_dataset_ref(ref: str, num_features: int = 0):
    """A manifest ``dataset`` ref -> :class:`LibsvmData`.

    ``synth:dense:n=128,d=64[,seed=S][,flip=F]`` generates a planted-
    separator dense tenant; anything else is a LIBSVM path (loaded with
    the line's ``num_features``, which is then required)."""
    if ref.startswith("synth:"):
        parts = ref.split(":")
        if len(parts) != 3 or parts[1] != "dense":
            raise ValueError(
                f"fleet dataset ref {ref!r}: synth refs are "
                f"'synth:dense:n=<rows>,d=<features>[,seed=S][,flip=F]' "
                f"(sparse tenants are not in the fleet v1 surface — "
                f"docs/DESIGN.md §16)")
        kv = {}
        for item in parts[2].split(","):
            if "=" not in item:
                raise ValueError(
                    f"fleet dataset ref {ref!r}: bad key=value {item!r}")
            key, val = item.split("=", 1)
            kv[key] = val
        try:
            n = int(kv.pop("n"))
            d = int(kv.pop("d"))
            seed = int(kv.pop("seed", 0))
            flip = float(kv.pop("flip", 0.02))
        except (KeyError, ValueError) as e:
            raise ValueError(
                f"fleet dataset ref {ref!r}: needs integer n= and d= "
                f"(optional seed=, flip=): {e}") from None
        if kv:
            raise ValueError(
                f"fleet dataset ref {ref!r}: unknown keys {sorted(kv)}")
        from cocoa_tpu.data.synth import synth_dense

        return synth_dense(n, d, seed=seed, flip=flip)
    if num_features <= 0:
        raise ValueError(
            f"fleet dataset ref {ref!r} is a LIBSVM path; the manifest "
            f"line must carry a positive num_features")
    from cocoa_tpu.data.libsvm import load_libsvm

    return load_libsvm(ref, num_features)


def load_fleet_manifest(path: str) -> list:
    """Parse + validate a ``--fleet`` manifest into TenantSpecs.

    The file is first schema-validated as the ``fleet`` JSONL dialect
    (telemetry/schema.py — a ``fleet_manifest`` header line, then one
    tenant object per line); any violation — including a duplicate
    tenant id, which the checker owns — is raised with the checker's
    line-accurate messages."""
    from cocoa_tpu.telemetry import schema as tele_schema

    errs = tele_schema.check_file(path, kind="fleet")
    if errs:
        raise ValueError(
            f"fleet manifest {path} failed schema validation "
            f"({len(errs)} violation(s)): " + "; ".join(errs[:5]))
    specs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "fleet_manifest" in obj:
                continue
            specs.append(TenantSpec(
                tenant=str(obj["tenant"]),
                dataset=str(obj["dataset"]),
                lam=float(obj["lam"]),
                gap_target=(None if obj.get("gap_target") is None
                            else float(obj["gap_target"])),
                num_features=int(obj.get("num_features", 0)),
                loss=str(obj.get("loss", "hinge")),
                smoothing=float(obj.get("smoothing", 1.0)),
            ))
    if not specs:
        raise ValueError(f"fleet manifest {path} names no tenants")
    return specs


def write_fleet_manifest(path: str, specs: list) -> None:
    """Write TenantSpecs as a schema-valid fleet manifest (the header +
    one tenant line each) — the producer the synth benchmark, the CLI
    quickstart, and the tests share."""
    with open(path, "w") as f:
        f.write(json.dumps(
            {"fleet_manifest": {"version": 1, "tenants": len(specs)}})
            + "\n")
        for s in specs:
            row = {"tenant": s.tenant, "dataset": s.dataset, "lam": s.lam,
                   "gap_target": s.gap_target}
            if s.num_features:
                row["num_features"] = s.num_features
            if s.loss != "hinge":
                row["loss"] = s.loss
                row["smoothing"] = s.smoothing
            f.write(json.dumps(row) + "\n")


def synth_fleet_specs(tenants: int, *, n: int = 128, d: int = 64,
                      lam_lo: float = 1e-3, lam_hi: float = 1e-1,
                      gap_target: float = 1e-3, seed0: int = 100) -> list:
    """T synthetic tenants spanning a log-spaced λ regularization path —
    the canonical fleet workload (each tenant a distinct problem AND a
    distinct λ, so the solo control pays a fresh compile per tenant)."""
    lams = np.logspace(np.log10(lam_lo), np.log10(lam_hi), max(tenants, 1))
    return [
        TenantSpec(
            tenant=f"tenant-{i:04d}",
            dataset=f"synth:dense:n={n},d={d},seed={seed0 + i}",
            lam=float(lams[i]),
            gap_target=float(gap_target),
        )
        for i in range(tenants)
    ]


@dataclasses.dataclass
class FleetDataset:
    """T tenants' shards stacked on a leading tenant axis.

    Every array leaf is the solo :class:`ShardedDataset` layout with a
    leading T dim; ``counts[t, k]`` is tenant t's real rows in shard k
    (rows ≥ counts are padding, masked everywhere).  ``lams`` /
    ``gap_targets`` (NaN = no target) are the per-tenant problem scalars
    the vmapped drive ladder consumes as traced inputs."""

    tenants: list                     # T tenant id strings
    n: np.ndarray                     # (T,) real example counts
    num_features: int                 # d, common
    counts: np.ndarray                # (T, K) int64, host-side
    lams: np.ndarray                  # (T,) float64 (host-exact λ)
    gap_targets: np.ndarray           # (T,) float64, NaN = none
    local_iters: int                  # H, common (the index-table width)
    loss: str
    smoothing: float
    labels: "jax.Array"               # (T, K, n_shard)
    mask: "jax.Array"                 # (T, K, n_shard)
    sq_norms: "jax.Array"             # (T, K, n_shard)
    X: "jax.Array"                    # (T, K, n_shard, d)
    layout: str = "dense"

    @property
    def t(self) -> int:
        return self.labels.shape[0]

    @property
    def k(self) -> int:
        return self.labels.shape[1]

    @property
    def n_shard(self) -> int:
        return self.labels.shape[2]

    @property
    def dtype(self):
        return self.labels.dtype

    def shard_arrays(self) -> dict:
        """The (T, K, …) pytree the vmapped kernels consume."""
        return {"labels": self.labels, "mask": self.mask,
                "sq_norms": self.sq_norms, "X": self.X}

    def tenant_ds(self, t: int) -> ShardedDataset:
        """Tenant t's slab as a solo :class:`ShardedDataset` — the SAME
        arrays (one slice, no rebuild), so the solo control path of the
        fleet benchmark and the bit-identity tests train on bitwise the
        data the fleet lane holds."""
        return ShardedDataset(
            layout="dense", n=int(self.n[t]),
            num_features=self.num_features,
            counts=np.asarray(self.counts[t], dtype=np.int64),
            labels=self.labels[t], mask=self.mask[t],
            sq_norms=self.sq_norms[t], X=self.X[t],
        )


def fleet_from_datasets(datasets: list, lams, gap_targets=None,
                        tenants=None, local_iters: int = 1,
                        loss: str = "hinge",
                        smoothing: float = 1.0) -> FleetDataset:
    """Stack already-built solo :class:`ShardedDataset`\\ s into a fleet —
    the programmatic entry (one-vs-rest heads over a shared dataset, test
    harnesses, λ-path sweeps over one corpus).  All datasets must share
    the dense layout and one (K, n_shard, d) static shape; ``lams`` is
    the per-tenant λ, ``gap_targets`` per-tenant or None, ``local_iters``
    the common H the caller's Params will run."""
    import jax.numpy as jnp

    if not datasets:
        raise ValueError("fleet_from_datasets needs at least one dataset")
    shapes = sorted({(d.layout, d.k, d.n_shard, d.num_features)
                     for d in datasets})
    if len(shapes) > 1 or shapes[0][0] != "dense":
        raise ValueError(
            f"fleet datasets must share one dense (K, n_shard, d) static "
            f"shape; got {shapes} — pad to a common shape or split the "
            f"fleet (sparse tenants are not in the fleet v1 surface)")
    t_count = len(datasets)
    # jaxlint: allow=f64 -- host-exact per-tenant λ staging: the traced
    # f32 λ·n is derived from this (solvers/fleet.py bit-parity contract)
    lams = np.asarray(lams, dtype=np.float64)
    if lams.shape != (t_count,):
        raise ValueError(f"lams must be one λ per tenant "
                         f"({t_count}), got shape {lams.shape}")
    gaps = (np.full(t_count, np.nan) if gap_targets is None
            else np.asarray([np.nan if g is None else float(g)
                             # jaxlint: allow=f64 -- host-side target list
                             for g in gap_targets], dtype=np.float64))
    return FleetDataset(
        tenants=(list(tenants) if tenants is not None
                 else [f"tenant-{i:04d}" for i in range(t_count)]),
        n=np.array([d.n for d in datasets], dtype=np.int64),
        num_features=datasets[0].num_features,
        counts=np.stack([np.asarray(d.counts) for d in datasets]
                        ).astype(np.int64),
        lams=lams, gap_targets=gaps, local_iters=int(local_iters),
        loss=loss, smoothing=float(smoothing),
        labels=jnp.stack([d.labels for d in datasets]),
        mask=jnp.stack([d.mask for d in datasets]),
        sq_norms=jnp.stack([d.sq_norms for d in datasets]),
        X=jnp.stack([d.X for d in datasets]),
    )


def build_fleet(specs: list, k: int, *, dtype=None,
                local_iter_frac: float = 1.0,
                default_gap_target: Optional[float] = None) -> FleetDataset:
    """Stack the tenants of ``specs`` into one :class:`FleetDataset`.

    Enforces the fleet's static-shape contract LOUDLY (with the numbers):
    every tenant must resolve to the dense layout at a common d and a
    common H = max(1, localIterFrac·n/K); differing loss phases are
    rejected (uniformity — see the module docstring).  n may vary: shards
    pad to the fleet-max ``n_shard`` (masked rows, exact)."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    if not specs:
        raise ValueError("build_fleet needs at least one tenant")
    np_dtype = np.dtype(dtype)

    losses_seen = sorted({(s.loss, float(s.smoothing)) for s in specs})
    if len(losses_seen) > 1:
        raise ValueError(
            f"fleet tenants must share one loss phase (a per-tenant loss "
            f"would make every vmapped lane pay every branch); manifest "
            f"mixes {losses_seen} — split the fleet by loss")

    # dataset refs resolve through an in-process memo: tenants sharing a
    # ref (one-vs-rest heads, a λ path over one corpus) parse it ONCE
    # per run — T tenants map one build T times, never T parses (the
    # parse-count pin in tests/test_fleet.py).  Ref resolution is pure
    # (synth refs are seed-keyed, file refs re-read the same bytes), so
    # sharing the parsed CSR is exact; the slab build below only READS
    # it per tenant.
    ref_memo: dict = {}
    parsed = []
    for s in specs:
        key = (s.dataset, int(s.num_features))
        if key not in ref_memo:
            ref_memo[key] = parse_dataset_ref(s.dataset, s.num_features)
        parsed.append(ref_memo[key])
    ds_d = sorted({p.num_features for p in parsed})
    if len(ds_d) > 1:
        raise ValueError(
            f"fleet tenants must share one feature dimension d (the "
            f"stacked (T, K, n_shard, d) slab is one static shape); "
            f"manifest mixes d={ds_d}")
    d = ds_d[0]

    hs = {}
    for s, p in zip(specs, parsed):
        hs.setdefault(max(1, int(local_iter_frac * p.n / k)),
                      []).append(s.tenant)
    if len(hs) > 1:
        raise ValueError(
            f"fleet tenants must share one H = max(1, localIterFrac·n/K) "
            f"(the index-table width is one static shape); manifest "
            f"yields H={ {h: v[:3] for h, v in sorted(hs.items())} } — "
            f"pad tenant datasets to a common n or split the fleet")
    h = next(iter(hs))

    t_count = len(specs)
    sizes = [split_sizes(p.n, k) for p in parsed]
    for s, p, sz in zip(specs, parsed, sizes):
        if np.any(sz <= 0):
            raise ValueError(
                f"fleet tenant {s.tenant!r}: every shard needs at least "
                f"one example; n={p.n} over K={k} shards gives sizes "
                f"{sz.tolist()} — lower numSplits")
    n_shard = pad_rows(int(max(int(sz.max()) for sz in sizes)))

    labels = np.zeros((t_count, k, n_shard), np_dtype)
    mask = np.zeros((t_count, k, n_shard), np_dtype)
    sq = np.zeros((t_count, k, n_shard), np_dtype)
    x = np.zeros((t_count, k, n_shard, d), np_dtype)
    for ti, p in enumerate(parsed):
        offsets = np.concatenate([[0], np.cumsum(sizes[ti])])
        row_nnz = np.diff(p.indptr)
        row_sq = segment_sq_norms(p.values, p.indptr)
        for s in range(k):
            slab = _build_shard_slabs(
                p, int(offsets[s]), int(offsets[s + 1]), n_shard, "dense",
                np_dtype, d, 0, row_nnz, row_sq)
            labels[ti, s] = slab["labels"]
            mask[ti, s] = slab["mask"]
            sq[ti, s] = slab["sq_norms"]
            x[ti, s] = slab["X"]

    gaps = np.array(
        [np.nan if (s.gap_target is None and default_gap_target is None)
         else (s.gap_target if s.gap_target is not None
               else default_gap_target)
         # jaxlint: allow=f64 -- host-side target staging (cast at use)
         for s in specs], dtype=np.float64)
    return FleetDataset(
        tenants=[s.tenant for s in specs],
        n=np.array([p.n for p in parsed], dtype=np.int64),
        num_features=d,
        counts=np.stack(sizes).astype(np.int64),
        # jaxlint: allow=f64 -- host-exact λ staging (see fleet_from_datasets)
        lams=np.array([s.lam for s in specs], dtype=np.float64),
        gap_targets=gaps,
        local_iters=h,
        loss=specs[0].loss,
        smoothing=float(specs[0].smoothing),
        labels=jnp.asarray(labels),
        mask=jnp.asarray(mask),
        sq_norms=jnp.asarray(sq),
        X=jnp.asarray(x),
    )
