"""Streaming sharded ingest: parse only your shards, build in place.

The reference's Spark loader reads only the HDFS blocks local to each
executor (OptUtils.scala:11-53).  The whole-file path here
(``load_libsvm`` → ``shard_dataset``) instead parses the ENTIRE LIBSVM
text in every process and only then slices out the local shards — P
redundant full parses, and a full-dataset host-side CSR per process.
This module is the data-local ingest CoCoA+'s design assumes (Ma et al.,
arXiv:1502.03508: each worker only ever touches its own partition), as a
two-pass byte-range pipeline (docs/DESIGN.md §12):

- **pass 1 — index scan.**  Each process scans its 1/P byte range of the
  file in bounded windows (range-parse, keep the stats, drop the rows):
  per-row byte offsets + nnz, and a partial column histogram.  The
  partials are all-gathered over the jax.distributed KV store
  (parallel/distributed.host_allgather_bytes — host data, no device
  round-trip) and summed: integer totals, so the assembled histogram is
  bit-identical to a whole-file ``np.bincount`` and ``--hotCols=auto``
  resolves to exactly the single-process width
  (hybrid.resolve_hot_width).
- **pass 2 — shard parse.**  The global row-offset index maps each local
  device's m = K/D consecutive shards to an EXACT byte range; each
  process parses only those ranges (native or Python range parser,
  data/libsvm.load_libsvm_range) and builds the padded slabs straight
  into the target layout — dense, padded-CSR, or the hybrid hot/cold
  split with the dense eval twin — through the same
  ``sharding._build_shard_slabs`` the whole-file paths use, so the
  shards are bit-identical by construction.  The full dataset CSR is
  never materialized host-side: peak host RSS is ~1/P of the dataset
  plus the index.

The hybrid residual width (global max COLD nnz per row) needs the hot
set, which needs the global histogram — so it is measured on the held
pass-2 pieces and max-reduced across processes (exact integer max, equal
to the whole-file ``bincount(...).max()``).

The single-process replicated builder (``shard_dataset``) stays bit-exact
as the A/B control; ``stream_shard_dataset`` with one process produces
the identical ``ShardedDataset`` (pinned by tests/test_ingest.py).

This pipeline is also the elastic supervisor's RESHARDING entry
(cocoa_tpu/elastic.py shrink-to-survivors, docs/DESIGN.md §13): after a
gang reforms at P′ < P, each survivor's relaunch lands here with the new
process count and materializes exactly the byte ranges of its newly
inherited m = K/D′ shards — shard assignment is re-solved by the same
``mesh_lib.dp_local_shards`` placement map every multi-process run uses,
so no shrink-specific build code exists to drift.  Every cross-process
exchange below rides the bounded, retrying KV ops
(distributed.blocking_kv_get): a peer that died between the supervisor's
relaunch and this exchange fails the build in bounded time with the
peer named, which the supervisor observes as a worker death and handles.
"""

from __future__ import annotations

import dataclasses
import io
import itertools
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data import hybrid as hybrid_lib
from cocoa_tpu.data import sharding as sharding_lib
from cocoa_tpu.data.libsvm import load_libsvm_range
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.parallel import distributed
from cocoa_tpu.parallel import mesh as mesh_lib
from cocoa_tpu.telemetry import tracing as _tracing

# pass-1 window: bounds the transient CSR a scan holds (rows are parsed
# and dropped per window; only offsets/nnz/histogram survive)
PASS1_WINDOW = 64 << 20

# SPMD-deterministic exchange tags: every process runs the same ingest
# calls in the same order, so a per-process counter yields matching tags
_EXCHANGE_SEQ = itertools.count()


def peak_rss_bytes() -> int:
    """This process's lifetime peak resident set (ru_maxrss is kB on
    Linux) — the ingest telemetry's memory fact.  ``resource`` is
    Unix-only; report 0 where it is absent rather than breaking the
    package import (this module loads with ``cocoa_tpu.data``)."""
    try:
        import resource
    except ImportError:
        return 0
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


@dataclasses.dataclass
class IngestIndex:
    """The pass-1 artifact: the global row index + column histogram.

    ``row_off`` has n+1 entries — ``row_off[i]`` is the byte offset of
    row i's line start, ``row_off[n]`` the file size — so rows [a, b)
    occupy exactly bytes [row_off[a], row_off[b]).
    """

    path: str
    file_bytes: int
    num_features: int
    row_off: np.ndarray      # (n+1,) int64
    row_nnz: np.ndarray      # (n,) int64
    hist: np.ndarray         # (d,) int64 global column histogram
    scan_bytes: int          # bytes THIS process scanned in pass 1
    scan_seconds: float

    @property
    def n(self) -> int:
        return len(self.row_nnz)

    @property
    def total_nnz(self) -> int:
        return int(self.row_nnz.sum())


def _pack_arrays(**arrays) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _unpack_arrays(payload: bytes) -> dict:
    with np.load(io.BytesIO(payload)) as z:
        return {k: z[k] for k in z.files}


def _exchange_max(value: int) -> int:
    """Exact integer max across processes (identity single-process)."""
    tag = f"ingest{next(_EXCHANGE_SEQ)}"
    payloads = distributed.host_allgather_bytes(
        tag, _pack_arrays(v=np.asarray([value], np.int64)))
    return int(max(int(_unpack_arrays(p)["v"][0]) for p in payloads))


def build_index(path: str, num_features: int, *,
                window: int = PASS1_WINDOW) -> IngestIndex:
    """Pass 1: scan this process's 1/P byte range, exchange, assemble.

    Every process returns the same global index (offsets concatenated in
    process order — ranges tile the file, so the concatenation IS the
    whole-file row order; histogram summed as int64, bit-identical to the
    whole-file ``np.bincount``).
    """
    with _tracing.span("ingest_pass1", path=path):
        return _build_index(path, num_features, window=window)


def _build_index(path: str, num_features: int, *,
                 window: int = PASS1_WINDOW) -> IngestIndex:
    size = os.path.getsize(path)
    nproc = jax.process_count()
    me = jax.process_index()
    lo = me * size // nproc
    hi = (me + 1) * size // nproc
    t0 = time.perf_counter()
    offs: list = []
    nnzs: list = []
    hist = np.zeros(num_features, np.int64)
    w = lo
    while w < hi:
        wl, wh = w, min(w + window, hi)
        piece, off = load_libsvm_range(path, num_features, wl, wh)
        hist += np.bincount(piece.indices, minlength=num_features)
        nnzs.append(np.diff(piece.indptr))
        offs.append(off)
        w = wh
    my_off = (np.concatenate(offs) if offs
              else np.empty(0, np.int64)).astype(np.int64)
    my_nnz = (np.concatenate(nnzs) if nnzs
              else np.empty(0, np.int64)).astype(np.int64)
    scan_seconds = time.perf_counter() - t0

    if nproc > 1:
        tag = f"ingest{next(_EXCHANGE_SEQ)}"
        payloads = distributed.host_allgather_bytes(
            tag, _pack_arrays(off=my_off, nnz=my_nnz, hist=hist))
        parts = [_unpack_arrays(p) for p in payloads]
        row_off = np.concatenate([p["off"] for p in parts])
        row_nnz = np.concatenate([p["nnz"] for p in parts])
        hist = np.sum([p["hist"] for p in parts], axis=0,
                      dtype=np.int64)
        scan_seconds = time.perf_counter() - t0
    else:
        row_off, row_nnz = my_off, my_nnz

    return IngestIndex(
        path=path,
        file_bytes=size,
        num_features=num_features,
        row_off=np.append(row_off, np.int64(size)),
        row_nnz=row_nnz,
        hist=hist,
        scan_bytes=hi - lo,
        scan_seconds=scan_seconds,
    )


@dataclasses.dataclass
class StreamBuildInfo:
    """Pass-2 facts of one streamed build (this process's share)."""

    rows: int                # rows parsed by THIS process in pass 2
    nnz: int
    bytes_read: int          # pass-2 bytes parsed by this process
    parse_seconds: float     # pass-2 wall time (parse + slab build)
    residual_max_nnz: int    # global max cold nnz (0 unless hybrid)


def stream_shard_dataset(
    path: str,
    num_features: int,
    k: int,
    *,
    layout: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    max_nnz: Optional[int] = None,
    eval_dense: bool = False,
    hot_cols: int = 0,
    index: Optional[IngestIndex] = None,
):
    """Streamed twin of :func:`cocoa_tpu.data.sharding.shard_dataset`
    (see :func:`_stream_build` for the mechanics; this wrapper only
    resolves the pass-1 index first so the ``ingest_pass2`` span times
    exactly the shard parse + slab build)."""
    if index is None:
        index = build_index(path, num_features)
    with _tracing.span("ingest_pass2", path=path):
        return _stream_build(
            path, num_features, k, layout=layout, dtype=dtype, mesh=mesh,
            max_nnz=max_nnz, eval_dense=eval_dense, hot_cols=hot_cols,
            index=index)


def _stream_build(
    path: str,
    num_features: int,
    k: int,
    *,
    layout: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    max_nnz: Optional[int] = None,
    eval_dense: bool = False,
    hot_cols: int = 0,
    index: Optional[IngestIndex] = None,
):
    """Streamed twin of :func:`cocoa_tpu.data.sharding.shard_dataset`:
    same arguments plus the file path instead of parsed data, returning
    ``(ShardedDataset, StreamBuildInfo)``.  The dataset is bit-identical
    to the whole-file build of the same file/config — same slab builders
    over the same parsed values, only the parse granularity changes.

    Multi-process with a dp mesh: each process parses and materializes
    ONLY the byte ranges of its local devices' shards (m = K/D shards
    per device — multiplexed meshes are first-class).  Single-process:
    shards build one at a time from their byte ranges (the full CSR is
    still never materialized), then place exactly like the replicated
    builder.  fp meshes keep whole-file ingest — the feature-axis column
    split re-buckets every row and has no data-local byte range per
    device; that combination is rejected loudly upstream.
    """
    if index is None:
        index = build_index(path, num_features)
    n, d = index.n, num_features
    layout = sharding_lib.resolve_layout_stats(n, d, index.total_nnz,
                                               layout, mesh)
    if mesh_lib.has_fp(mesh):
        # sparse+fp is impossible anywhere; dense+fp is whole-ingest only
        raise ValueError(
            "streamed ingest does not support feature-parallel (fp) "
            "meshes: the fp column split has no per-device byte range "
            "to stream; use --ingest=whole"
        )
    if eval_dense and layout != "sparse":
        raise ValueError("eval_dense only applies to the sparse layout "
                         "(the dense layout's eval is already a matvec)")

    np_dtype = np.dtype(dtype)
    sizes = sharding_lib.split_sizes(n, k)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    n_shard = sharding_lib.pad_rows(int(sizes.max())) if k > 0 else 0

    width = 0
    if layout == "sparse":
        width = int(max_nnz if max_nnz is not None
                    else max(1, index.row_nnz.max(initial=1)))
        if n and int(index.row_nnz.max(initial=0)) > width:
            raise ValueError(
                f"row nnz {int(index.row_nnz.max())} exceeds max_nnz "
                f"{width}"
            )

    rank = None
    hot_ids = None
    n_hot = 0
    if hot_cols:
        if layout != "sparse":
            raise ValueError("hot_cols (the hot/cold column split) only "
                             "applies to the sparse layout")
        if max_nnz is not None:
            raise ValueError("hot_cols and max_nnz cannot combine: the "
                             "residual width is measured from the split")
        n_hot = hybrid_lib.pad_panel(min(int(hot_cols), d))
        # the hot set derives from the ASSEMBLED histogram — identical to
        # the whole-file hottest_columns(column_counts(data), n_hot)
        hot_ids = hybrid_lib.hottest_columns(index.hist, n_hot)
        rank = hybrid_lib.hot_rank(d, hot_ids)

    distributed_build = (mesh is not None and jax.process_count() > 1)
    if distributed_build:
        if k % mesh.devices.size != 0:
            # same divisibility contract as sharding.shard_dataset; the
            # elastic shrink path only relaunches divisor-sized gangs
            # (elastic.shrink_gang_size), so a reformed survivor gang can
            # never trip this — only a hand-built mismatched launch does
            raise ValueError(
                f"multi-process runs need numSplits divisible by the dp "
                f"mesh size: K={k} shards cannot multiplex onto "
                f"{mesh.devices.size} devices"
            )
        locals_ = mesh_lib.dp_local_shards(mesh, k)
    else:
        locals_ = [(None, 0, k)]

    t0 = time.perf_counter()
    bytes_read = 0
    rows_parsed = 0
    nnz_parsed = 0

    def parse_piece(shard_lo, shard_hi):
        """The CSR piece holding shards [shard_lo, shard_hi)'s rows."""
        nonlocal bytes_read, rows_parsed, nnz_parsed
        r0, r1 = int(offsets[shard_lo]), int(offsets[shard_hi])
        blo = int(index.row_off[r0])
        bhi = int(index.row_off[r1])
        piece, _ = load_libsvm_range(path, d, blo, bhi)
        if piece.n != r1 - r0:
            raise ValueError(
                f"{path}: changed during ingest (index says rows "
                f"[{r0}, {r1}) occupy bytes [{blo}, {bhi}), parsed "
                f"{piece.n} rows); re-run"
            )
        bytes_read += bhi - blo
        rows_parsed += piece.n
        nnz_parsed += len(piece.values)
        return piece, r0

    # hybrid residual width: measured on the held pass-2 pieces, then
    # max-reduced across processes — exact integer, equal to the
    # whole-file bincount(cold_rows).max()
    pieces = None
    resid_max = 0
    if n_hot:
        pieces = {(slo, shi): parse_piece(slo, shi)
                  for _, slo, shi in locals_}
        local_max = 0
        for piece, _ in pieces.values():
            if piece.n == 0:
                continue
            pr_nnz = np.diff(piece.indptr)
            rows = np.repeat(np.arange(piece.n, dtype=np.int64), pr_nnz)
            cold = rows[rank[piece.indices] < 0]
            local_max = max(local_max, int(
                np.bincount(cold, minlength=piece.n).max(initial=0)))
        resid_max = (_exchange_max(local_max) if jax.process_count() > 1
                     else local_max)
        width = max(1, resid_max)

    d_eff = mesh_lib.pad_features(d, mesh) if layout == "dense" else d

    def build_shards(shard_lo, shard_hi):
        """Slab dicts for shards [shard_lo, shard_hi) from one piece."""
        if pieces is not None:
            piece, base = pieces.pop((shard_lo, shard_hi))
        else:
            piece, base = parse_piece(shard_lo, shard_hi)
        pr_nnz = np.diff(piece.indptr)
        pr_sq = sharding_lib.segment_sq_norms(piece.values, piece.indptr)
        out = {}
        for s in range(shard_lo, shard_hi):
            lo, hi = int(offsets[s]) - base, int(offsets[s + 1]) - base
            out[s] = sharding_lib._build_shard_slabs(
                piece, lo, hi, n_shard, layout, np_dtype, d_eff, width,
                pr_nnz, pr_sq, rank=rank, n_hot=n_hot,
                eval_dense=eval_dense)
        return out

    if distributed_build:
        built = {}
        for _, slo, shi in locals_:
            built.update(build_shards(slo, shi))
        ds = sharding_lib._assemble_distributed(
            mesh, k, built, locals_, layout=layout, n=n, d=d_eff,
            n_shard=n_shard, width=width, sizes=sizes, n_hot=n_hot,
            hot_ids=hot_ids, eval_dense=eval_dense, np_dtype=np_dtype)
    else:
        # single process: one shard's piece at a time — the full CSR is
        # never held; peak = the stacked (K, ...) arrays + one piece.
        # (Hybrid is the exception: the residual-width measurement above
        # already parsed the whole range as one held piece, so build from
        # it rather than parse everything twice.)
        ranges = ([(0, k)] if pieces is not None
                  else [(s, s + 1) for s in range(k)])
        arrs: dict = {}
        for slo, shi in ranges:
            for s, slab in build_shards(slo, shi).items():
                for f, v in slab.items():
                    arrs.setdefault(f,
                                    np.zeros((k, *v.shape), v.dtype))[s] = v
        if n_hot:
            hc = np.zeros(n_hot, dtype=np.int32)
            hc[:len(hot_ids)] = hot_ids
            arrs["hot_cols"] = np.tile(hc[None], (k, 1))
        ds = sharding_lib._finalize_replicated(
            arrs, layout=layout, n=n, d=d_eff, mesh=mesh, sizes=sizes)

    info = StreamBuildInfo(
        rows=rows_parsed,
        nnz=nnz_parsed,
        bytes_read=bytes_read,
        parse_seconds=time.perf_counter() - t0,
        residual_max_nnz=resid_max,
    )
    return ds, info


def resolve_ingest_mode(spec, mesh, *, objective: str = "svm") -> str:
    """``--ingest=stream|whole|auto`` → the mode a run uses.

    ``auto`` picks ``stream`` exactly where it wins: multi-process svm
    runs on a dp mesh (every process would otherwise parse the whole
    file).  Single-process, fp meshes, and the lasso column shards keep
    ``whole`` — the replicated builder is the bit-exact A/B control.
    Explicit asks that cannot be honored raise (loudly, with the remedy).
    """
    spec_s = ("auto" if spec is None else str(spec)).strip().lower()
    if spec_s not in ("auto", "stream", "whole"):
        raise ValueError(f"--ingest must be stream|whole|auto, "
                         f"got {spec!r}")
    if spec_s == "stream":
        if objective == "lasso":
            raise ValueError(
                "--ingest=stream does not apply to --objective=lasso "
                "(column shards re-bucket every row; use --ingest=whole)")
        if mesh_lib.has_fp(mesh):
            raise ValueError(
                "--ingest=stream does not support feature-parallel (fp) "
                "meshes (no per-device byte range to stream); use "
                "--ingest=whole")
        return "stream"
    if spec_s == "whole":
        return "whole"
    if (objective == "svm" and mesh is not None
            and not mesh_lib.has_fp(mesh) and jax.process_count() > 1):
        return "stream"
    return "whole"


@dataclasses.dataclass
class IngestReport:
    """The typed ``ingest`` telemetry payload (one per loaded file)."""

    mode: str                # "stream" | "whole"
    path: str
    file_bytes: int
    processes: int
    parse_seconds: float     # this process: scan + shard parse
    bytes_read: int          # this process: scanned + parsed bytes
    rows: int                # rows this process materialized
    nnz: int
    n: int                   # global dataset facts
    total_nnz: int
    peak_rss_bytes: int

    def as_fields(self) -> dict:
        return dataclasses.asdict(self)
