"""Streaming sharded ingest: parse only your shards, build in place.

The reference's Spark loader reads only the HDFS blocks local to each
executor (OptUtils.scala:11-53).  The whole-file path here
(``load_libsvm`` → ``shard_dataset``) instead parses the ENTIRE LIBSVM
text in every process and only then slices out the local shards — P
redundant full parses, and a full-dataset host-side CSR per process.
This module is the data-local ingest CoCoA+'s design assumes (Ma et al.,
arXiv:1502.03508: each worker only ever touches its own partition), as a
two-pass byte-range pipeline (docs/DESIGN.md §12):

- **pass 1 — index scan.**  Each process scans its 1/P byte range of the
  file in bounded windows (range-parse, keep the stats, drop the rows):
  per-row byte offsets + nnz, and a partial column histogram.  The
  partials are all-gathered over the jax.distributed KV store
  (parallel/distributed.host_allgather_bytes — host data, no device
  round-trip) and summed: integer totals, so the assembled histogram is
  bit-identical to a whole-file ``np.bincount`` and ``--hotCols=auto``
  resolves to exactly the single-process width
  (hybrid.resolve_hot_width).
- **pass 2 — shard parse.**  The global row-offset index maps each
  shard's rows to an EXACT byte range; each process parses only its own
  local shards' ranges (native or Python range parser,
  data/libsvm.load_libsvm_range) — SHARD-GRANULAR, fanned out over an
  intra-process thread pool when the native parser is available (its
  ctypes entry points release the GIL; the pure-Python parser keeps the
  sequential loop) — and builds the padded slabs straight into the
  target layout through the same ``sharding._build_shard_slabs`` the
  whole-file paths use, so the shards are bit-identical by construction.
  The full dataset CSR is never materialized host-side: peak host RSS
  is ~1/P of the dataset plus the index.

**The persistent slab cache** (``--ingestCache=DIR``,
data/slab_cache.py, docs/DESIGN.md §18) makes the SECOND touch free:
pass 1 warm-loads the cached index (zero scan), pass 2 ``np.load``\\ s
each shard's device-ready slabs from memmap-able artifacts (zero parse,
zero slab build) and parses only cache misses; cold builds populate the
cache shard by shard (atomic rename, one writer wins).  Because the
artifacts are keyed by SHARD (0..K−1), not process geometry, an elastic
shrink's survivors re-map their inherited shards warm.  Every
conditional cache shortcut is VOTED across the gang first
(:func:`_all_agree`) — per-host cache state may differ, and a process
skipping an exchange its peers entered would wedge the gang.

The hybrid residual width (global max COLD nnz per row) needs the hot
set, which needs the global histogram — so it is measured on the held
pass-2 pieces and max-reduced across processes (exact integer max, equal
to the whole-file ``bincount(...).max()``), then cached as the hybrid
layout meta so warm runs skip the measurement parse entirely.

The single-process replicated builder (``shard_dataset``) stays bit-exact
as the A/B control; ``stream_shard_dataset`` with one process produces
the identical ``ShardedDataset`` (pinned by tests/test_ingest.py).

This pipeline is also the elastic supervisor's RESHARDING entry
(cocoa_tpu/elastic.py shrink-to-survivors, docs/DESIGN.md §13): after a
gang reforms at P′ < P, each survivor's relaunch lands here with the new
process count and materializes exactly its newly inherited m = K/D′
shards — warm from the cache when ``--ingestCache`` rode the worker
line, since the shard keys ignore the gang geometry.  Every
cross-process exchange below rides the bounded, retrying KV ops
(distributed.blocking_kv_get): a peer that died between the supervisor's
relaunch and this exchange fails the build in bounded time with the
peer named, which the supervisor observes as a worker death and handles.
"""

from __future__ import annotations

import dataclasses
import io
import itertools
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from cocoa_tpu.data import hybrid as hybrid_lib
from cocoa_tpu.data import sharding as sharding_lib
from cocoa_tpu.data.libsvm import load_libsvm_range
from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.parallel import distributed
from cocoa_tpu.parallel import mesh as mesh_lib
from cocoa_tpu.telemetry import tracing as _tracing

# pass-1 window: bounds the transient CSR a scan holds (rows are parsed
# and dropped per window; only offsets/nnz/histogram survive)
PASS1_WINDOW = 64 << 20

# SPMD-deterministic exchange tags: every process runs the same ingest
# calls in the same order, so a per-process counter yields matching tags
_EXCHANGE_SEQ = itertools.count()


def peak_rss_bytes() -> int:
    """This process's lifetime peak resident set (ru_maxrss is kB on
    Linux) — the ingest telemetry's memory fact.  ``resource`` is
    Unix-only; report 0 where it is absent rather than breaking the
    package import (this module loads with ``cocoa_tpu.data``)."""
    try:
        import resource
    except ImportError:
        return 0
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


@dataclasses.dataclass
class IngestIndex:
    """The pass-1 artifact: the global row index + column histogram.

    ``row_off`` has n+1 entries — ``row_off[i]`` is the byte offset of
    row i's line start, ``row_off[n]`` the file size — so rows [a, b)
    occupy exactly bytes [row_off[a], row_off[b]).
    """

    path: str
    file_bytes: int
    num_features: int
    row_off: np.ndarray      # (n+1,) int64
    row_nnz: np.ndarray      # (n,) int64
    hist: np.ndarray         # (d,) int64 global column histogram
    scan_bytes: int          # bytes THIS process scanned in pass 1
    scan_seconds: float

    @property
    def n(self) -> int:
        return len(self.row_nnz)

    @property
    def total_nnz(self) -> int:
        return int(self.row_nnz.sum())


def _pack_arrays(**arrays) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _unpack_arrays(payload: bytes) -> dict:
    with np.load(io.BytesIO(payload)) as z:
        return {k: z[k] for k in z.files}


def _exchange_max(value: int) -> int:
    """Exact integer max across processes (identity single-process)."""
    tag = f"ingest{next(_EXCHANGE_SEQ)}"
    payloads = distributed.host_allgather_bytes(
        tag, _pack_arrays(v=np.asarray([value], np.int64)))
    return int(max(int(_unpack_arrays(p)["v"][0]) for p in payloads))


def _all_agree(flag: bool) -> bool:
    """Exact all-processes AND (identity single-process).  Cache state
    is per-host: one worker may hold a warm artifact its peers lack, and
    a process that skipped an exchange its peers entered would wedge the
    gang — so every conditional cache shortcut votes first with one tiny
    allgather, and the gang takes the shortcut only unanimously."""
    if jax.process_count() <= 1:
        return flag
    tag = f"ingest{next(_EXCHANGE_SEQ)}"
    payloads = distributed.host_allgather_bytes(
        tag, _pack_arrays(v=np.asarray([1 if flag else 0], np.int64)))
    return all(int(_unpack_arrays(p)["v"][0]) for p in payloads)


def _cache_handle(cache, path: str, num_features: int):
    """Bind the slab cache to the file, or None (a vanished file fails
    the subsequent parse with its own clean error)."""
    if cache is None:
        return None
    try:
        return cache.for_file(path, num_features)
    except OSError:
        return None


def build_index(path: str, num_features: int, *,
                window: int = PASS1_WINDOW, cache=None) -> IngestIndex:
    """Pass 1: scan this process's 1/P byte range, exchange, assemble.

    Every process returns the same global index (offsets concatenated in
    process order — ranges tile the file, so the concatenation IS the
    whole-file row order; histogram summed as int64, bit-identical to the
    whole-file ``np.bincount``).

    With ``cache`` (a :class:`cocoa_tpu.data.slab_cache.SlabCache`), a
    previously stored FULL index for this exact file identity returns
    without reading a byte (``scan_bytes=0``) — unanimously voted across
    the gang — and a cold scan stores its index for the next process.
    """
    with _tracing.span("ingest_pass1", path=path):
        handle = _cache_handle(cache, path, num_features)
        if cache is not None:
            stats = handle.load_index() if handle is not None else None
            have = stats is not None and stats.has_rows
            if not _all_agree(have):
                stats = None
            if stats is not None and stats.has_rows:
                return IngestIndex(
                    path=path, file_bytes=stats.file_bytes,
                    num_features=num_features,
                    row_off=np.asarray(stats.row_off, np.int64),
                    row_nnz=np.asarray(stats.row_nnz, np.int64),
                    hist=np.asarray(stats.hist, np.int64),
                    scan_bytes=0, scan_seconds=0.0,
                )
        index = _build_index(path, num_features, window=window)
        if handle is not None:
            handle.store_index(
                hist=index.hist, n=index.n, total_nnz=index.total_nnz,
                max_row_nnz=int(index.row_nnz.max(initial=0)),
                row_off=index.row_off, row_nnz=index.row_nnz)
        return index


def _build_index(path: str, num_features: int, *,
                 window: int = PASS1_WINDOW) -> IngestIndex:
    size = os.path.getsize(path)
    nproc = jax.process_count()
    me = jax.process_index()
    lo = me * size // nproc
    hi = (me + 1) * size // nproc
    t0 = time.perf_counter()
    offs: list = []
    nnzs: list = []
    hist = np.zeros(num_features, np.int64)
    w = lo
    while w < hi:
        wl, wh = w, min(w + window, hi)
        piece, off = load_libsvm_range(path, num_features, wl, wh)
        hist += np.bincount(piece.indices, minlength=num_features)
        nnzs.append(np.diff(piece.indptr))
        offs.append(off)
        w = wh
    my_off = (np.concatenate(offs) if offs
              else np.empty(0, np.int64)).astype(np.int64)
    my_nnz = (np.concatenate(nnzs) if nnzs
              else np.empty(0, np.int64)).astype(np.int64)
    scan_seconds = time.perf_counter() - t0

    if nproc > 1:
        tag = f"ingest{next(_EXCHANGE_SEQ)}"
        payloads = distributed.host_allgather_bytes(
            tag, _pack_arrays(off=my_off, nnz=my_nnz, hist=hist))
        parts = [_unpack_arrays(p) for p in payloads]
        row_off = np.concatenate([p["off"] for p in parts])
        row_nnz = np.concatenate([p["nnz"] for p in parts])
        hist = np.sum([p["hist"] for p in parts], axis=0,
                      dtype=np.int64)
        scan_seconds = time.perf_counter() - t0
    else:
        row_off, row_nnz = my_off, my_nnz

    return IngestIndex(
        path=path,
        file_bytes=size,
        num_features=num_features,
        row_off=np.append(row_off, np.int64(size)),
        row_nnz=row_nnz,
        hist=hist,
        scan_bytes=hi - lo,
        scan_seconds=scan_seconds,
    )


def _pass2_workers(n_tasks: int) -> int:
    """Thread-pool width for the pass-2 shard parses: the native
    parser's byte-range entry points run per shard and release the GIL
    inside the ctypes call, so they are embarrassingly parallel; the
    pure-Python parser holds the GIL and keeps the sequential loop."""
    if n_tasks <= 1:
        return 1
    from cocoa_tpu.data import native_loader

    if not native_loader.available():
        return 1
    return max(1, min(n_tasks, os.cpu_count() or 1))


def _parse_waves(shards, parse_fn):
    """Yield ``(s, parse_fn(s))`` for every shard id, parsing in
    bounded parallel waves: at most one thread-pool width of pieces is
    in flight, so the peak transient CSR stays ~workers/K of the
    dataset instead of all local pieces at once.  Results are yielded
    in shard order — assembly is keyed by shard id, so the parallelism
    cannot perturb a single output byte."""
    shards = list(shards)
    workers = _pass2_workers(len(shards))
    if workers <= 1:
        for s in shards:
            yield s, parse_fn(s)
        return
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as ex:
        for i in range(0, len(shards), workers):
            chunk = shards[i:i + workers]
            for s, res in zip(chunk, ex.map(parse_fn, chunk)):
                yield s, res


@dataclasses.dataclass
class StreamBuildInfo:
    """Pass-2 facts of one streamed build (this process's share)."""

    rows: int                # rows parsed by THIS process in pass 2
    nnz: int
    bytes_read: int          # pass-2 bytes parsed by this process
    parse_seconds: float     # pass-2 wall time (parse + slab build)
    residual_max_nnz: int    # global max cold nnz (0 unless hybrid)
    shards_cached: int = 0   # local shards served from --ingestCache
    shards_total: int = 0    # local shards this process materialized
    cache_bytes_mapped: int = 0
    cache_status: str = "off"   # off | hit | partial | miss
    seconds_saved: float = 0.0  # the cached cold cost, on a full hit


def stream_shard_dataset(
    path: str,
    num_features: int,
    k: int,
    *,
    layout: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    max_nnz: Optional[int] = None,
    eval_dense: bool = False,
    hot_cols: int = 0,
    index: Optional[IngestIndex] = None,
    cache=None,
):
    """Streamed twin of :func:`cocoa_tpu.data.sharding.shard_dataset`
    (see :func:`_stream_build` for the mechanics; this wrapper only
    resolves the pass-1 index first so the ``ingest_pass2`` span times
    exactly the shard parse + slab build)."""
    if index is None:
        index = build_index(path, num_features, cache=cache)
    with _tracing.span("ingest_pass2", path=path):
        return _stream_build(
            path, num_features, k, layout=layout, dtype=dtype, mesh=mesh,
            max_nnz=max_nnz, eval_dense=eval_dense, hot_cols=hot_cols,
            index=index, cache=cache)


def _stream_build(
    path: str,
    num_features: int,
    k: int,
    *,
    layout: str = "auto",
    dtype=jnp.float32,
    mesh: Optional[jax.sharding.Mesh] = None,
    max_nnz: Optional[int] = None,
    eval_dense: bool = False,
    hot_cols: int = 0,
    index: Optional[IngestIndex] = None,
    cache=None,
):
    """Streamed twin of :func:`cocoa_tpu.data.sharding.shard_dataset`:
    same arguments plus the file path instead of parsed data, returning
    ``(ShardedDataset, StreamBuildInfo)``.  The dataset is bit-identical
    to the whole-file build of the same file/config — same slab builders
    over the same parsed values, only the parse granularity changes.

    Multi-process with a dp mesh: each process parses and materializes
    ONLY its local devices' shards (m = K/D shards per device —
    multiplexed meshes are first-class).  Single-process: shards build
    one wave at a time from their byte ranges (the full CSR is still
    never materialized), then place exactly like the replicated builder.
    fp meshes keep whole-file ingest — the feature-axis column split
    re-buckets every row and has no data-local byte range per device;
    that combination is rejected loudly upstream.

    With ``cache`` (--ingestCache), each shard is served from its cached
    slab artifact when present — zero parse, mmap'd straight toward
    ``device_put`` — and every shard parsed cold is stored back
    (slab_cache.ShardCacheView, atomic rename).  A full-hit build parses
    zero bytes.
    """
    if index is None:
        index = build_index(path, num_features, cache=cache)
    n, d = index.n, num_features
    layout = sharding_lib.resolve_layout_stats(n, d, index.total_nnz,
                                               layout, mesh)
    if mesh_lib.has_fp(mesh):
        # sparse+fp is impossible anywhere; dense+fp is whole-ingest only
        raise ValueError(
            "streamed ingest does not support feature-parallel (fp) "
            "meshes: the fp column split has no per-device byte range "
            "to stream; use --ingest=whole"
        )
    if eval_dense and layout != "sparse":
        raise ValueError("eval_dense only applies to the sparse layout "
                         "(the dense layout's eval is already a matvec)")

    np_dtype = np.dtype(dtype)
    sizes = sharding_lib.split_sizes(n, k)
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    n_shard = sharding_lib.pad_rows(int(sizes.max())) if k > 0 else 0

    width = 0
    if layout == "sparse":
        width = int(max_nnz if max_nnz is not None
                    else max(1, index.row_nnz.max(initial=1)))
        if n and int(index.row_nnz.max(initial=0)) > width:
            raise ValueError(
                f"row nnz {int(index.row_nnz.max())} exceeds max_nnz "
                f"{width}"
            )

    rank = None
    hot_ids = None
    n_hot = 0
    if hot_cols:
        if layout != "sparse":
            raise ValueError("hot_cols (the hot/cold column split) only "
                             "applies to the sparse layout")
        if max_nnz is not None:
            raise ValueError("hot_cols and max_nnz cannot combine: the "
                             "residual width is measured from the split")
        n_hot = hybrid_lib.pad_panel(min(int(hot_cols), d))
        # the hot set derives from the ASSEMBLED histogram — identical to
        # the whole-file hottest_columns(column_counts(data), n_hot)
        hot_ids = hybrid_lib.hottest_columns(index.hist, n_hot)
        rank = hybrid_lib.hot_rank(d, hot_ids)

    distributed_build = (mesh is not None and jax.process_count() > 1)
    if distributed_build:
        if k % mesh.devices.size != 0:
            # same divisibility contract as sharding.shard_dataset; the
            # elastic shrink path only relaunches divisor-sized gangs
            # (elastic.shrink_gang_size), so a reformed survivor gang can
            # never trip this — only a hand-built mismatched launch does
            raise ValueError(
                f"multi-process runs need numSplits divisible by the dp "
                f"mesh size: K={k} shards cannot multiplex onto "
                f"{mesh.devices.size} devices"
            )
        locals_ = mesh_lib.dp_local_shards(mesh, k)
    else:
        locals_ = [(None, 0, k)]
    local_shards = [s for _, lo, hi in locals_ for s in range(lo, hi)]

    handle = _cache_handle(cache, path, num_features)
    mapped_before = cache.bytes_mapped if cache is not None else 0

    t0 = time.perf_counter()
    bytes_read = 0
    rows_parsed = 0
    nnz_parsed = 0

    def parse_shard(s):
        """The CSR piece holding exactly shard ``s``'s rows (thread-safe:
        pure function of the index; accounting happens at the consumer)."""
        r0, r1 = int(offsets[s]), int(offsets[s + 1])
        blo = int(index.row_off[r0])
        bhi = int(index.row_off[r1])
        piece, _ = load_libsvm_range(path, d, blo, bhi)
        if piece.n != r1 - r0:
            raise ValueError(
                f"{path}: changed during ingest (index says rows "
                f"[{r0}, {r1}) occupy bytes [{blo}, {bhi}), parsed "
                f"{piece.n} rows); re-run"
            )
        return piece, bhi - blo

    def account(piece, nbytes):
        nonlocal bytes_read, rows_parsed, nnz_parsed
        bytes_read += nbytes
        rows_parsed += piece.n
        nnz_parsed += len(piece.values)

    # hybrid residual width: the cached layout meta when EVERY process
    # holds it (voted — see _all_agree); else measured on the held
    # pass-2 pieces and max-reduced across processes — exact integer,
    # equal to the whole-file bincount(cold_rows).max() — then cached
    pieces: dict = {}
    resid_max = 0
    if n_hot:
        cached_resid = (handle.load_hybrid_meta(n_hot)
                        if handle is not None else None)
        have_meta = cache is not None and _all_agree(
            cached_resid is not None)
        if have_meta:
            resid_max = int(cached_resid)
        else:
            for s, (piece, nbytes) in _parse_waves(local_shards,
                                                   parse_shard):
                account(piece, nbytes)
                pieces[s] = piece
            local_max = 0
            for piece in pieces.values():
                if piece.n == 0:
                    continue
                pr_nnz = np.diff(piece.indptr)
                rows = np.repeat(np.arange(piece.n, dtype=np.int64),
                                 pr_nnz)
                cold = rows[rank[piece.indices] < 0]
                local_max = max(local_max, int(
                    np.bincount(cold, minlength=piece.n).max(initial=0)))
            resid_max = (_exchange_max(local_max)
                         if jax.process_count() > 1 else local_max)
            if handle is not None:
                handle.store_hybrid_meta(n_hot, resid_max)
        width = max(1, resid_max)

    d_eff = mesh_lib.pad_features(d, mesh) if layout == "dense" else d
    view = (handle.view(layout=layout, k=k, n_shard=n_shard, width=width,
                        n_hot=n_hot, d=d_eff, dtype=np_dtype,
                        eval_dense=eval_dense)
            if handle is not None else None)

    cached_count = 0

    def build_from_piece(s, piece):
        """Shard ``s``'s slab dict from its own parsed piece; a cold
        build also publishes the slab to the cache."""
        pr_nnz = np.diff(piece.indptr)
        pr_sq = sharding_lib.segment_sq_norms(piece.values, piece.indptr)
        slab = sharding_lib._build_shard_slabs(
            piece, 0, piece.n, n_shard, layout, np_dtype, d_eff, width,
            pr_nnz, pr_sq, rank=rank, n_hot=n_hot, eval_dense=eval_dense)
        if view is not None:
            view.store(s, slab)
        return slab

    def iter_slabs():
        """Yield ``(s, slab)`` for every local shard: cache hits first
        (zero parse), then the held hybrid-measurement pieces (no
        re-parse), then the remaining misses parsed in bounded parallel
        waves — one slab at a time, so the single-process peak stays the
        stacked arrays plus one wave of pieces."""
        nonlocal cached_count
        to_parse = []
        for s in local_shards:
            if s in pieces:
                continue
            slab = view.load(s) if view is not None else None
            if slab is not None:
                cached_count += 1
                yield s, slab
            else:
                to_parse.append(s)
        for s in sorted(pieces):
            yield s, build_from_piece(s, pieces.pop(s))
        for s, (piece, nbytes) in _parse_waves(to_parse, parse_shard):
            account(piece, nbytes)
            yield s, build_from_piece(s, piece)

    if distributed_build:
        built = dict(iter_slabs())
        ds = sharding_lib._assemble_distributed(
            mesh, k, built, locals_, layout=layout, n=n, d=d_eff,
            n_shard=n_shard, width=width, sizes=sizes, n_hot=n_hot,
            hot_ids=hot_ids, eval_dense=eval_dense, np_dtype=np_dtype)
    else:
        arrs: dict = {}
        for s, slab in iter_slabs():
            for f, v in slab.items():
                arrs.setdefault(f,
                                np.zeros((k, *v.shape), v.dtype))[s] = v
        if n_hot:
            hc = np.zeros(n_hot, dtype=np.int32)
            hc[:len(hot_ids)] = hot_ids
            arrs["hot_cols"] = np.tile(hc[None], (k, 1))
        ds = sharding_lib._finalize_replicated(
            arrs, layout=layout, n=n, d=d_eff, mesh=mesh, sizes=sizes)

    parse_seconds = time.perf_counter() - t0
    status = "off"
    seconds_saved = 0.0
    if cache is not None:
        if cached_count == len(local_shards):
            status = "hit"
            seconds_saved = (handle.load_cost()
                             if handle is not None else 0.0)
        else:
            status = "partial" if cached_count else "miss"
            if handle is not None and cached_count == 0:
                # record the FULL-miss cold cost so warm runs can report
                # what the cache bought (the seconds_saved estimate);
                # a partial run only re-paid its missed shards — writing
                # that sliver would corrupt the estimate for the cache's
                # lifetime
                handle.store_cost(index.scan_seconds + parse_seconds)
    info = StreamBuildInfo(
        rows=rows_parsed,
        nnz=nnz_parsed,
        bytes_read=bytes_read,
        parse_seconds=parse_seconds,
        residual_max_nnz=resid_max,
        shards_cached=cached_count,
        shards_total=len(local_shards),
        cache_bytes_mapped=(cache.bytes_mapped - mapped_before
                            if cache is not None else 0),
        cache_status=status,
        seconds_saved=seconds_saved,
    )
    return ds, info


def load_cached_dataset(handle, stats, k, *, layout: str, dtype,
                        mesh=None, eval_dense: bool = False,
                        hot_cols: int = 0):
    """Zero-parse :class:`ShardedDataset` entirely from ``--ingestCache``
    artifacts — the warm half of the WHOLE-file path (the streaming path
    warms per shard inside :func:`_stream_build`).  ``layout`` must be
    RESOLVED (the caller resolved it from the cached stats); ``hot_cols``
    is the resolved lane-padded panel width.  Returns
    ``(ShardedDataset, StreamBuildInfo)`` or None when any artifact is
    missing or corrupt — the caller cold-parses, which re-populates."""
    t0 = time.perf_counter()
    n, d = stats.n, handle.num_features
    np_dtype = np.dtype(dtype)
    sizes = sharding_lib.split_sizes(n, k)
    n_shard = sharding_lib.pad_rows(int(sizes.max())) if k > 0 else 0
    width = 0
    resid_max = 0
    hot_ids = None
    if layout == "sparse":
        if hot_cols:
            resid = handle.load_hybrid_meta(hot_cols)
            if resid is None:
                return None
            resid_max = int(resid)
            width = max(1, resid_max)
            hot_ids = hybrid_lib.hottest_columns(stats.hist, hot_cols)
        else:
            width = max(1, int(stats.max_row_nnz))
    d_eff = mesh_lib.pad_features(d, mesh) if layout == "dense" else d
    view = handle.view(layout=layout, k=k, n_shard=n_shard, width=width,
                       n_hot=hot_cols, d=d_eff, dtype=np_dtype,
                       eval_dense=eval_dense)
    distributed_build = (mesh is not None and jax.process_count() > 1
                         and not mesh_lib.has_fp(mesh))
    if distributed_build:
        if k % mesh.devices.size != 0:
            return None  # the cold path raises its own loud error
        locals_ = mesh_lib.dp_local_shards(mesh, k)
        needed = [s for _, lo, hi in locals_ for s in range(lo, hi)]
    else:
        locals_ = None
        needed = list(range(k))
    before = handle.cache.bytes_mapped
    built = {}
    for s in needed:
        slab = view.load(s)
        if slab is None:
            return None
        built[s] = slab
    bytes_mapped = handle.cache.bytes_mapped - before
    if distributed_build:
        ds = sharding_lib._assemble_distributed(
            mesh, k, built, locals_, layout=layout, n=n, d=d_eff,
            n_shard=n_shard, width=width, sizes=sizes, n_hot=hot_cols,
            hot_ids=hot_ids, eval_dense=eval_dense, np_dtype=np_dtype)
    else:
        arrs: dict = {}
        for s in needed:
            for f, v in built[s].items():
                arrs.setdefault(f,
                                np.zeros((k, *v.shape), v.dtype))[s] = v
        if hot_cols:
            hc = np.zeros(hot_cols, dtype=np.int32)
            hc[:len(hot_ids)] = hot_ids
            arrs["hot_cols"] = np.tile(hc[None], (k, 1))
        ds = sharding_lib._finalize_replicated(
            arrs, layout=layout, n=n, d=d_eff, mesh=mesh, sizes=sizes)
    info = StreamBuildInfo(
        rows=0, nnz=0, bytes_read=0,
        parse_seconds=time.perf_counter() - t0,
        residual_max_nnz=resid_max,
        shards_cached=len(needed), shards_total=len(needed),
        cache_bytes_mapped=bytes_mapped, cache_status="hit",
        seconds_saved=handle.load_cost(),
    )
    return ds, info


def resolve_ingest_mode(spec, mesh, *, objective: str = "svm",
                        cached: bool = False) -> str:
    """``--ingest=stream|whole|auto`` → the mode a run uses.

    ``auto`` picks ``stream`` exactly where it wins: multi-process svm
    runs on a dp mesh (every process would otherwise parse the whole
    file) — and, with ``cached`` (--ingestCache armed), EVERY svm run on
    a dp-or-no mesh, since the shard-granular pipeline is what consults
    and populates the cache at shard granularity and its shards are
    bit-identical to the whole-file build (pinned).  Single-process
    uncached, fp meshes, and the lasso column shards keep ``whole`` —
    the replicated builder is the bit-exact A/B control.  Explicit asks
    that cannot be honored raise (loudly, with the remedy).
    """
    spec_s = ("auto" if spec is None else str(spec)).strip().lower()
    if spec_s not in ("auto", "stream", "whole"):
        raise ValueError(f"--ingest must be stream|whole|auto, "
                         f"got {spec!r}")
    if spec_s == "stream":
        if objective == "lasso":
            raise ValueError(
                "--ingest=stream does not apply to --objective=lasso "
                "(column shards re-bucket every row; use --ingest=whole)")
        if mesh_lib.has_fp(mesh):
            raise ValueError(
                "--ingest=stream does not support feature-parallel (fp) "
                "meshes (no per-device byte range to stream); use "
                "--ingest=whole")
        return "stream"
    if spec_s == "whole":
        return "whole"
    if (objective == "svm" and mesh is not None
            and not mesh_lib.has_fp(mesh) and jax.process_count() > 1):
        return "stream"
    if cached and objective == "svm" and not mesh_lib.has_fp(mesh):
        return "stream"
    return "whole"


@dataclasses.dataclass
class IngestReport:
    """The typed ``ingest`` telemetry payload (one per loaded file)."""

    mode: str                # "stream" | "whole"
    path: str
    file_bytes: int
    processes: int
    parse_seconds: float     # this process: scan + shard parse
    bytes_read: int          # this process: scanned + parsed bytes
    rows: int                # rows this process materialized
    nnz: int
    n: int                   # global dataset facts
    total_nnz: int
    peak_rss_bytes: int
    cache: str = "off"       # --ingestCache outcome: off|hit|partial|miss

    def as_fields(self) -> dict:
        return dataclasses.asdict(self)
