"""Algorithm and run configuration.

Mirrors the reference's core datatypes (OptClasses.scala:21-29 ``Params``,
OptClasses.scala:38-42 ``DebugParams``) and the full CLI flag inventory
(hingeDriver.scala:22-38), as plain dataclasses.  The loss is selected by name
rather than by function pointer so configs stay serializable and jit-friendly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Params:
    """Algorithmic parameters (reference: OptClasses.scala:21-29).

    Notation follows the CoCoA papers: K = number of shards/workers,
    H = ``local_iters`` local steps per round, T = ``num_rounds``.
    """

    n: int                      # global number of training examples
    num_rounds: int = 200       # T, outer iterations (hingeDriver.scala:33)
    local_iters: int = 1        # H, local steps per round (hingeDriver.scala:70-71)
    lam: float = 0.01           # lambda, L2 regularization (hingeDriver.scala:32)
    beta: float = 1.0           # update scaling; 1 = averaging (hingeDriver.scala:35)
    gamma: float = 1.0          # CoCoA+ aggregation; 1 = adding (hingeDriver.scala:36)
    loss: str = "hinge"         # "hinge" | "smooth_hinge" | "logistic" (extension)
    smoothing: float = 1.0      # smooth_hinge smoothing parameter s (unused
                                # by the other losses)
    sigma: Optional[float] = None  # σ′ subproblem-coupling override (extension;
                                # None = the reference's safe bound K·γ,
                                # CoCoA.scala:45; the string "auto" =
                                # start at the aggressive K·γ/2 and back
                                # off toward K·γ when the stall watch
                                # fires — in place on the device by
                                # default (--sigmaSchedule=anneal), or
                                # via the trial-then-rerun A/B control
                                # (--sigmaSchedule=trial) —
                                # solvers/cocoa.run_cocoa).  K·γ assumes worst-case
                                # cross-shard coherence; random shards
                                # tolerate less — measured on the rcv1
                                # config, σ′=K/2 HALVES the certified
                                # comm-rounds to the 1e-4 gap while
                                # anything below K/2 diverges (σ′=3.5 at
                                # K=8 already does — which the exact
                                # duality-gap certificate reports rather
                                # than hides)


@dataclasses.dataclass
class DebugParams:
    """Systems/debugging parameters (reference: OptClasses.scala:38-42)."""

    debug_iter: int = 10        # evaluate every this many rounds; <=0 disables
    seed: int = 0
    chkpt_iter: int = 201       # checkpoint every this many rounds (num_rounds+1 disables)
    chkpt_dir: str = ""         # empty disables checkpointing (hingeDriver.scala:55-59)


@dataclasses.dataclass
class RunConfig:
    """Full run configuration = the reference CLI flag set (hingeDriver.scala:22-38)
    plus TPU-specific knobs that have no Spark analogue."""

    # --- reference flags (names kept 1:1 so the CLI is drop-in) ---
    train_file: str = ""
    test_file: str = ""
    num_features: int = 0
    num_splits: int = 1          # K, number of data shards (= mesh size by default)
    chkpt_dir: str = ""
    chkpt_iter: int = 100
    just_cocoa: bool = True
    lam: float = 0.01            # --lambda
    num_rounds: int = 200
    local_iter_frac: float = 1.0
    beta: float = 1.0
    gamma: float = 1.0
    debug_iter: int = 10
    seed: int = 0

    # --- TPU-native knobs (no reference analogue) ---
    dtype: str = "float32"       # compute dtype; reference is float64 throughout
    layout: str = "auto"         # "dense" | "sparse" (padded-CSR) | "auto"
    rng: str = "reference"       # "reference": java.util.Random, one seed shared by
                                 #   all shards per round (CoCoA.scala:45,144);
                                 # "jax": jax PRNG folded per (round, shard) —
                                 #   decorrelated across shards (improvement);
                                 # "permuted": random reshuffling — per-shard
                                 #   per-epoch permutations, every coordinate
                                 #   once per epoch (~5x fewer comm-rounds to
                                 #   the certified gap at epsilon scale)
    sampling: str = "auto"       # where index tables are generated:
                                 # "auto" (in-jit on device whenever exact —
                                 # the production default; tunneled h2d is
                                 # ~10 MB/s with shards resident), "device",
                                 # or "host" (concrete tables, debug path)
    scan_chunk: int = 0          # >0: run rounds device-side in lax.scan blocks
                                 # of this size (one dispatch per block)
    math: str = "exact"          # "exact": reference-order float ops (bit-
                                 #   matchable vs the oracle in x64);
                                 # "fast": margins decomposition — one MXU
                                 #   matvec per round + incremental Δw dots,
                                 #   auto-Pallas inner loop on TPU (CoCoA only)
    device_loop: bool = False    # run the whole train loop (incl. gap-target
                                 # early stop) as one on-device while_loop
    mesh_shape: Optional[tuple] = None  # (dp,) or (dp, fp); None = (num_splits,)
    loss: str = "hinge"
    smoothing: float = 1.0
    sigma: "float | str" = 0.0   # σ′ override (0 = the safe K·γ default;
                                 # a float, or "auto"); see Params.sigma

    def to_params(self, n: int, k: int) -> Params:
        """H = max(1, localIterFrac * n / K) as in hingeDriver.scala:70-71."""
        h = max(1, int(self.local_iter_frac * n / k))
        return Params(
            n=n,
            num_rounds=self.num_rounds,
            local_iters=h,
            lam=self.lam,
            beta=self.beta,
            gamma=self.gamma,
            loss=self.loss,
            smoothing=self.smoothing,
            sigma=("auto" if self.sigma == "auto"
                   else self.sigma if self.sigma > 0 else None),
        )

    def to_debug(self, num_rounds: Optional[int] = None) -> DebugParams:
        rounds = self.num_rounds if num_rounds is None else num_rounds
        chkpt_iter = self.chkpt_iter if self.chkpt_dir else rounds + 1
        return DebugParams(
            debug_iter=self.debug_iter,
            seed=self.seed,
            chkpt_iter=chkpt_iter,
            chkpt_dir=self.chkpt_dir,
        )


# Mapping from reference CLI flag names (hingeDriver.scala:22-38) to RunConfig
# field names.  "master" is not here: the CLI consumes it as a run-level flag
# (it selects local vs multi-host mode, cli.py).
REFERENCE_FLAGS = {
    "trainFile": "train_file",
    "testFile": "test_file",
    "numFeatures": "num_features",
    "numSplits": "num_splits",
    "chkptDir": "chkpt_dir",
    "chkptIter": "chkpt_iter",
    "justCoCoA": "just_cocoa",
    "lambda": "lam",
    "numRounds": "num_rounds",
    "localIterFrac": "local_iter_frac",
    "beta": "beta",
    "gamma": "gamma",
    "debugIter": "debug_iter",
    "seed": "seed",
}
