"""Fan-out/all-reduce execution of per-shard functions.

The communication core shared by solvers *and* evaluation: run a per-shard
function with w replicated and shard state local, then sum-reduce the first
output across shards.  Two paths with identical math:

- **mesh path**: ``shard_map`` over the dp axis; the reduce is ``lax.psum``
  over ICI.  This is the reference's ``mapPartitions`` → ``RDD.reduce``
  skeleton (CoCoA.scala:45-47) as a single XLA collective.
- **local path** (mesh=None): ``vmap`` over the leading K axis + in-device
  sum — all K logical shards resident on one chip (the analogue of the
  reference's ``local[4]`` mode), used for single-chip benchmarking.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cocoa_tpu.parallel.mesh import DP_AXIS, manual_axes


def _to_varying(x):
    """Mark a replicated value as varying over dp (VMA cast inside shard_map)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (DP_AXIS,), to="varying")
    return lax.pvary(x, DP_AXIS)  # older jax


def shards_per_device(mesh: Optional[Mesh], k: int) -> int:
    """m = logical shards per mesh position (Spark multiplexes K partitions
    onto fewer executors via ``coalesce``, OptUtils.scala:14; the mesh
    analogue stacks m = K/D shards per device and runs them under an inner
    vmap/batched kernel inside the shard_map body).  1:1 when mesh is None
    (the local path IS the all-shards-on-one-device case)."""
    if mesh is None:
        return 1
    d = mesh.shape[DP_AXIS]
    if k % d != 0:
        raise ValueError(
            f"{k} shards cannot multiplex evenly onto the {d}-device dp "
            f"axis; K must be a multiple of the mesh size"
        )
    return k // d


def fanout(
    per_shard: Callable,
    mesh: Optional[Mesh],
    w: jax.Array,
    *sharded,
):
    """Run ``per_shard(w, *shard_slices) -> (reduced, aux...)`` over K shards.

    ``sharded`` args are pytrees whose leaves have leading dim K.  The first
    output of ``per_shard`` is sum-reduced across shards (any shape — a Δw
    vector or a scalar partial sum); each aux output keeps its leading K dim
    (shard-local state, e.g. updated alpha).

    K may be a multiple m·D of the dp mesh size D (shard multiplexing —
    see :func:`shards_per_device`): each device then runs its m local
    shards under an inner vmap, sums their contributions in-device, and
    the cross-device combine stays ONE psum per call either way.
    """
    if mesh is not None:
        k = jax.tree.leaves(sharded)[0].shape[0]
        m = shards_per_device(mesh, k)

        def wrapped(w, *slices):
            # w arrives replicated (unvarying); the local solvers mix it into
            # shard-varying state, so cast it to device-varying up front to
            # keep loop-carry VMA types consistent.
            w = _to_varying(w)
            if m == 1:
                slices = jax.tree.map(lambda a: a[0], slices)
                out = per_shard(w, *slices)
                red, aux = out[0], out[1:]
                return (lax.psum(red, DP_AXIS), *(a[None] for a in aux))
            # multiplexed: the local (m, ...) block is the single-chip
            # "m logical shards on one device" case — vmap it, sum the
            # reduced outputs in-device, then the same single psum
            out = jax.vmap(per_shard, in_axes=(None, *([0] * len(slices))))(
                w, *slices
            )
            red, aux = out[0], out[1:]
            return (lax.psum(red.sum(axis=0), DP_AXIS), *aux)

        in_specs = (P(), *(jax.tree.map(lambda _: P(DP_AXIS), s) for s in sharded))
        # probe output structure abstractly to build out_specs: first output
        # replicated, aux outputs sharded on their leading dim
        probe = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), sharded
        )
        n_aux = len(jax.eval_shape(per_shard, w, *probe)) - 1
        out_specs = (P(), *([P(DP_AXIS)] * n_aux))
        # on a (dp, fp) mesh, shard_map is manual over dp only; the feature
        # axis stays GSPMD-auto (specs then only constrain the dp placement)
        return jax.shard_map(
            wrapped, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names=manual_axes(mesh),
        )(w, *sharded)

    in_axes = (None, *([0] * len(sharded)))
    out = jax.vmap(per_shard, in_axes=in_axes)(w, *sharded)
    red, aux = out[0], out[1:]
    return (red.sum(axis=0), *aux)


def invariant_from_varying(x):
    """Recover a replicated (invariant) value from a device-varying one that
    is numerically identical on every device — exactly, via a masked psum
    that selects device 0's copy (no division, so bit-exact for any K)."""
    idx = lax.axis_index(DP_AXIS)
    import jax.numpy as jnp

    return lax.psum(jnp.where(idx == 0, x, jnp.zeros_like(x)), DP_AXIS)


def chunk_fanout(
    mesh: Optional[Mesh],
    per_round: Callable,
    apply_fn: Callable,
    w: jax.Array,
    carry_sharded,      # pytree, leaves (K, ...): shard-local carry (e.g. alpha)
    xs_sharded,         # pytree: per-round inputs, see below
    static_sharded,     # pytree, leaves (K, ...): shard data (not scanned)
    per_round_batched: Optional[Callable] = None,
    check_vma: bool = True,
):
    """Run C rounds device-side as one ``lax.scan`` (one dispatch per chunk).

    ``xs_sharded`` leaves are scanned over their leading C dim; leaves of
    ndim ≥ 2 are (C, K, ...) per-shard inputs (sliced per device on the
    mesh path), leaves of ndim == 1 are (C,) replicated per-round scalars
    (e.g. the round number t for η(t) schedules — SGD.scala:44,
    DistGD.scala:35).

    ``per_round(w, carry_k, x_k, static_k) -> (dw, carry_k')`` is one outer
    round seen from a single shard, returning its *unreduced* Δw;
    ``apply_fn(w, dw_sum, x_k) -> w'`` is the replicated driver-side update
    (``x_k`` passed so t-dependent step sizes can be applied).  Returns
    (w_final, carry_final) with the same placement semantics as ``fanout``
    (w replicated, carry keeping its leading K dim).

    ``per_round_batched(w, carry, x, static) -> (dw_sum, carry')``, when
    given, replaces the vmap on the single-chip path with one call over all
    K shards at once — required for inner solvers that manage the shard axis
    themselves (the Pallas kernels' (K, H) grids cannot sit under vmap).
    """
    def x_spec(a):
        return P(None) if a.ndim == 1 else P(None, DP_AXIS)

    if mesh is not None:
        # K from the static shard arrays — the carry can be empty (the
        # mini-batch SGD chunk carries no per-shard state)
        k = jax.tree.leaves((static_sharded, carry_sharded))[0].shape[0]
        m = shards_per_device(mesh, k)

        def wrapped(w, carry, xs, static):
            w = _to_varying(w)
            if m == 1:
                carry = jax.tree.map(lambda a: a[0], carry)
                # (C, 1, ...) → (C, ...); (C,) scalar leaves pass through
                xs = jax.tree.map(
                    lambda a: a if a.ndim == 1 else a[:, 0], xs
                )
                static = jax.tree.map(lambda a: a[0], static)

                def body(c, x):
                    w, carry_k = c
                    dw, carry2 = per_round(w, carry_k, x, static)
                    w2 = apply_fn(w, lax.psum(dw, DP_AXIS), x)
                    return (w2, carry2), None
            else:
                # multiplexed (m shards per device): the local (m, ...)
                # block runs exactly like the single-chip path — batched
                # kernel or vmap — with the in-device shard sum folded
                # into the same single psum per round
                def body(c, x):
                    w, carry_k = c
                    if per_round_batched is not None:
                        dw_local, carry2 = per_round_batched(
                            w, carry_k, x, static
                        )
                    else:
                        x_axes = jax.tree.map(
                            lambda a: None if a.ndim == 0 else 0, x
                        )
                        dw, carry2 = jax.vmap(
                            per_round, in_axes=(None, 0, x_axes, 0)
                        )(w, carry_k, x, static)
                        dw_local = dw.sum(axis=0)
                    w2 = apply_fn(w, lax.psum(dw_local, DP_AXIS), x)
                    return (w2, carry2), None

            (w, carry), _ = lax.scan(body, (w, carry), xs)
            w_inv = invariant_from_varying(w)
            if m == 1:
                carry = jax.tree.map(lambda a: a[None], carry)
            return w_inv, carry

        in_specs = (
            P(),
            jax.tree.map(lambda _: P(DP_AXIS), carry_sharded),
            jax.tree.map(x_spec, xs_sharded),
            jax.tree.map(lambda _: P(DP_AXIS), static_sharded),
        )
        out_specs = (P(), jax.tree.map(lambda _: P(DP_AXIS), carry_sharded))
        return jax.shard_map(
            wrapped, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, axis_names=manual_axes(mesh),
        )(w, carry_sharded, xs_sharded, static_sharded)

    # local path: scan over rounds; per round, vmap over shards + in-device sum
    def body(c, x):
        w, carry = c
        if per_round_batched is not None:
            dw_sum, carry2 = per_round_batched(w, carry, x, static_sharded)
        else:
            x_axes = jax.tree.map(lambda a: None if a.ndim == 0 else 0, x)
            dw, carry2 = jax.vmap(per_round, in_axes=(None, 0, x_axes, 0))(
                w, carry, x, static_sharded
            )
            dw_sum = dw.sum(axis=0)
        return (apply_fn(w, dw_sum, x), carry2), None

    (w, carry), _ = lax.scan(body, (w, carry_sharded), xs_sharded)
    return w, carry


def lane_fanout(per_lane: Callable, lane_exec: str = "vmap",
                idx_axis: Optional[int] = None) -> Callable:
    """Batch a per-tenant traceable over the fleet's leading tenant axis
    (solvers/base.py ``_build_fleet_run``).

    ``per_lane(state_t, chunk, data_t, scal_t) -> state_t`` sees ONE
    tenant; the returned callable takes the stacked (T, ...) pytrees.
    ``idx_axis`` names the chunk table's tenant axis (None = one table
    shared by every lane).  ``lane_exec``:

    - ``"vmap"`` — lanes batch into one vectorized body (the throughput
      mode; batched reductions may round ~1 ulp away from the solo
      executable at T > 1);
    - ``"map"`` — lanes run sequentially via ``lax.scan`` inside the
      same jit (``lax.map``): each lane's body is the solo HLO exactly —
      the bit-parity mode (same one-compile/one-dispatch amortization).
    """
    if lane_exec not in ("vmap", "map"):
        raise ValueError(f"lane_exec must be vmap|map, got {lane_exec!r}")
    if lane_exec == "vmap":
        return jax.vmap(per_lane, in_axes=(0, idx_axis, 0, 0))
    import jax.numpy as jnp

    def mapped(state, chunk, data, scal):
        if idx_axis is not None:
            ch = jnp.moveaxis(chunk, idx_axis, 0)
            return lax.map(lambda a: per_lane(*a), (state, ch, data, scal))
        return lax.map(lambda a: per_lane(a[0], chunk, a[1], a[2]),
                       (state, data, scal))

    return mapped


def mesh_of(*arrays) -> Optional[Mesh]:
    """Infer the dp mesh from array placement (None ⇒ local/vmap path).

    An array counts as mesh-placed when it carries a NamedSharding over a
    multi-device mesh with a dp axis.
    """
    for a in arrays:
        sh = getattr(a, "sharding", None)
        if (
            isinstance(sh, NamedSharding)
            and sh.mesh.size > 1
            and DP_AXIS in sh.mesh.axis_names
        ):
            return sh.mesh
    return None
