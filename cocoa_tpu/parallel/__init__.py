from cocoa_tpu.parallel.mesh import (  # noqa: F401
    DP_AXIS,
    FP_AXIS,
    make_mesh,
    replicated,
    sharded_rows,
)
