"""Multi-host (multi-process) runtime initialization.

The reference's ``--master`` flag selects the Spark cluster manager
(hingeDriver.scala:23: ``local[4]`` or a ``spark://host:port`` URL); workers
then talk over Spark's Netty RPC fabric.  The TPU-native counterpart is JAX's
multi-controller runtime: every host of a pod slice runs the same program,
``jax.distributed.initialize`` connects them through a coordinator, and
``jax.devices()`` becomes the global device set — after which the very same
``shard_map`` + ``lax.psum`` code path runs over ICI/DCN with zero further
changes (the collectives are compiled in, not library calls; SURVEY.md §2.3).

``--master=local[...]`` / ``local`` / empty keeps the single-process path,
exactly like the reference's local mode.  Anything of the form ``host:port``
(or ``spark://host:port``, accepted for drop-in compatibility) is treated as
the coordinator address.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


def parse_master(master: Optional[str]) -> Optional[str]:
    """Coordinator address from a reference-style --master value, or None
    for local mode."""
    if not master:
        return None
    m = master.strip()
    if m == "local" or m.startswith("local["):
        return None
    for prefix in ("spark://", "jax://", "grpc://"):
        if m.startswith(prefix):
            m = m[len(prefix):]
            if ":" not in m:
                # an explicit scheme unambiguously requests cluster mode —
                # silently degrading to local would train K independent
                # copies, one per host
                raise ValueError(
                    f"--master={master!r} requests cluster mode but has no "
                    f"port; use {prefix}host:port"
                )
            return m
    return m if ":" in m else None


def maybe_initialize(
    master: Optional[str],
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> bool:
    """Connect this process to the multi-host runtime if --master names a
    coordinator.  Returns True iff distributed mode was initialized.

    ``process_id`` / ``num_processes`` fall back to COCOA_PROCESS_ID /
    COCOA_NUM_PROCESSES, then to JAX's auto-detection (TPU pods populate
    both from the metadata server).
    """
    coordinator = parse_master(master)
    if coordinator is None:
        return False
    import jax

    if process_id is None and os.environ.get("COCOA_PROCESS_ID"):
        process_id = int(os.environ["COCOA_PROCESS_ID"])
    if num_processes is None and os.environ.get("COCOA_NUM_PROCESSES"):
        num_processes = int(os.environ["COCOA_NUM_PROCESSES"])
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


# --- host-side metadata exchange -------------------------------------------
#
# Streaming ingest (data/ingest.py) assembles its global row index and
# column histogram from per-process partials.  That exchange is HOST data
# (numpy, before any device placement), so it rides the jax.distributed
# coordination service's key-value store rather than an XLA collective:
# no device round-trip, no dependency on cross-process jit support (which
# older CPU backends lack — the Gloo collective path only has to carry
# the training psums, exactly as before).


def kv_client():
    """The distributed coordination client, or None single-process.

    Raises when multiple processes are live but the coordination service
    is not — host-side exchanges have no fallback path in that state.
    """
    import jax

    if jax.process_count() == 1:
        return None
    from jax._src import distributed as _dist

    client = getattr(_dist.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "multi-process run without a jax.distributed coordination "
            "client; initialize via --master=host:port (or "
            "jax.distributed.initialize) before streaming ingest"
        )
    return client


# one raw chunk per KV value, base64-encoded below the coordinator's gRPC
# message ceiling (4 MB default; 2 MB raw -> ~2.7 MB encoded)
_KV_CHUNK = 2 << 20

# KV-store wait policy (docs/DESIGN.md §13): one logical get is a BOUNDED
# sequence of short blocking attempts with exponential backoff between
# them, not one monolithic 600 s block.  Same total budget, but a
# transient coordinator error retries instead of aborting the run, and
# the final failure is an actionable message naming the missing peer/key
# instead of a bare 10-minute gRPC deadline traceback.
KV_TIMEOUT_S = 600.0       # total budget per logical key
KV_ATTEMPT_S = 20.0        # per-attempt blocking wait
_KV_BACKOFF_BASE_S = 0.25  # pause after a FAST failure (doubled, capped)
_KV_BACKOFF_CAP_S = 5.0


def blocking_kv_get(client, key: str, *, timeout_s: float = KV_TIMEOUT_S,
                    attempt_s: float = KV_ATTEMPT_S,
                    what: Optional[str] = None) -> str:
    """A bounded, retrying ``blocking_key_value_get``.

    Retries short blocking attempts (with capped exponential backoff
    after fast failures) until ``timeout_s`` is spent, then raises a
    RuntimeError naming the key — and ``what``, the peer/exchange it
    stands for — with the remedy, chaining the last underlying error.
    """
    from cocoa_tpu.telemetry import tracing as _tracing

    with _tracing.span("kv_get", key=key, what=what):
        return _blocking_kv_get(client, key, timeout_s=timeout_s,
                                attempt_s=attempt_s, what=what)


def _blocking_kv_get(client, key: str, *, timeout_s: float,
                     attempt_s: float, what: Optional[str]) -> str:
    deadline = time.monotonic() + timeout_s
    attempts = 0
    fast_failures = 0
    last_err = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        attempts += 1
        wait_s = min(attempt_s, remaining)
        t0 = time.monotonic()
        try:
            return client.blocking_key_value_get(
                key, max(1, int(wait_s * 1000)))
        except Exception as e:  # timeout / transient coordinator error
            last_err = e
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        # back off only after FAST failures (coordinator refused or
        # errored immediately): an attempt that consumed its blocking
        # wait was already listening the whole time — sleeping after it
        # would spend budget deaf and notice a late-published key up to
        # cap-seconds late
        if time.monotonic() - t0 < wait_s / 2.0:
            fast_failures += 1
            pause = min(_KV_BACKOFF_CAP_S,
                        _KV_BACKOFF_BASE_S * (2.0 ** (fast_failures - 1)))
            time.sleep(min(pause, remaining))
        else:
            # a full-length attempt proves the coordinator is reachable
            # and listening — the exponential state accumulated from
            # earlier fast failures (startup noise) must not stretch the
            # budget by re-applying the CAPPED pause to the next
            # transient error (the PR-9 pin's slow-attempt corollary,
            # extended by tests/test_chaos.py)
            fast_failures = 0
    raise RuntimeError(
        f"KV-store key {key!r}"
        + (f" ({what})" if what else "")
        + f" never appeared within {timeout_s:g}s ({attempts} attempt(s)): "
        f"the process that should publish it likely died, or never "
        f"reached this exchange — check its log; under --elastic the "
        f"supervisor restarts (or shrinks) the gang automatically"
    ) from last_err


def host_allgather_bytes(tag: str, payload: bytes,
                         timeout_s: float = KV_TIMEOUT_S,
                         attempt_s: float = KV_ATTEMPT_S) -> list:
    """All-gather one bytes payload per process through the KV store.

    Returns the payloads in process order (every process sees the same
    list).  ``tag`` must be unique per logical exchange AND identical
    across processes — callers derive it from an SPMD-deterministic
    counter.  Single-process: returns ``[payload]`` with no coordinator.

    Every get rides :func:`blocking_kv_get`, so a peer that died before
    publishing fails THIS process in bounded time with a message naming
    the peer — the elastic supervisor then tears the gang down and
    restarts or shrinks it, instead of every survivor hanging ~10
    minutes in an uninformative gRPC deadline.
    """
    from cocoa_tpu.telemetry import tracing as _tracing

    client = kv_client()
    if client is None:
        return [payload]
    with _tracing.span("kv_allgather", tag=tag, bytes=len(payload)):
        return _host_allgather(client, tag, payload, timeout_s, attempt_s)


def _host_allgather(client, tag, payload, timeout_s, attempt_s) -> list:
    _post_chunks(client, tag, payload)
    return _collect_allgather(client, tag, payload, timeout_s, attempt_s)


def _post_chunks(client, tag, payload) -> None:
    """Publish THIS process's payload under ``tag`` (chunked, base64).
    Pure non-blocking sets — peers unblock the moment this returns, even
    if this process never collects the gather itself."""
    import base64

    import jax

    me = jax.process_index()
    nchunk = (len(payload) + _KV_CHUNK - 1) // _KV_CHUNK
    for i in range(nchunk):
        chunk = payload[i * _KV_CHUNK:(i + 1) * _KV_CHUNK]
        client.key_value_set(f"cocoa/{tag}/{me}/{i}",
                             base64.b64encode(chunk).decode())
    client.key_value_set(f"cocoa/{tag}/{me}/n", str(nchunk))


def _collect_allgather(client, tag, payload, timeout_s, attempt_s) -> list:
    """The blocking half of :func:`_host_allgather`: fetch every PEER's
    chunks (own payload slots in from the argument).  Runs on the
    caller's thread for the synchronous path and on the collector daemon
    for :func:`async_host_allgather_bytes`."""
    import base64

    import jax

    me = jax.process_index()
    out = []
    for p in range(jax.process_count()):
        if p == me:
            out.append(payload)
            continue
        n = int(blocking_kv_get(
            client, f"cocoa/{tag}/{p}/n", timeout_s=timeout_s,
            attempt_s=attempt_s,
            what=f"peer process {p}, exchange {tag!r}"))
        parts = [
            base64.b64decode(blocking_kv_get(
                client, f"cocoa/{tag}/{p}/{i}", timeout_s=timeout_s,
                attempt_s=attempt_s,
                what=f"peer process {p}, exchange {tag!r} chunk {i}/{n}"))
            for i in range(n)
        ]
        out.append(b"".join(parts))
    return out


# --- overlapped (asynchronous) exchanges ------------------------------------
#
# The synchronous exchanges above serialize against whatever the caller
# does next: a gang round pays (local solve) + (exchange wait) even
# though the wait is mostly "listening for the slowest peer".  The async
# front end below splits one exchange into
#
#   post   — this worker's payload is published IMMEDIATELY, on the
#            caller's thread (cheap non-blocking sets; peers unblock the
#            moment local work finishes, not when we get around to
#            collecting), and
#   collect — the peer gets run on a daemon collector thread, so the
#            exchange span runs CONCURRENTLY with the caller's next
#            compute instead of after it,
#
# joined by an :class:`ExchangeHandle` at the caller's barrier of
# choice (solvers/cocoa.StaleJoinWindow picks the round it must land
# by).  Payloads are HOST BYTES by contract — a jax array (worse, a
# tracer) crossing into the collector thread would race the dispatch
# that produced it, so :func:`_require_host_bytes` rejects anything
# that is not already plain bytes (the runtime half of the jaxlint
# ``overlap-hygiene`` rule).  Collector threads are daemons and every
# underlying get is the bounded :func:`blocking_kv_get`, so an
# abandoned handle (gang teardown, elastic resize) can neither hang
# process exit nor wait past the KV budget.


def _require_host_bytes(payload) -> bytes:
    """The exchange-thread safety contract: payloads must already be
    host bytes when the exchange launches.  Device arrays (or traced
    values) must be materialized on the CALLER's thread —
    ``np.asarray(x).tobytes()`` — never inside the collector, where the
    fetch would race the dispatch that produced them."""
    if isinstance(payload, (bytes, bytearray)):
        return bytes(payload)
    raise TypeError(
        f"exchange payloads must be host bytes, got "
        f"{type(payload).__name__}: device/traced values must not escape "
        f"into the exchange thread (jaxlint overlap-hygiene) — convert "
        f"with np.asarray(x).tobytes() on the caller's thread first"
    )


class ExchangeHandle:
    """One in-flight asynchronous host-side exchange.

    ``join()`` blocks until the collector finishes (re-raising its
    error), returns its result, and emits one typed ``comm_overlap``
    event accounting the overlap:

    - ``hidden_s`` — exchange wall-clock that ran CONCURRENTLY with the
      caller's own work (launch → min(collector done, join called)):
      the seconds the overlap actually took off the critical path;
    - ``wait_s``  — the residual blocking wait inside ``join()``.

    ``done()`` is a non-blocking poll.  Handles are single-join (a
    second ``join()`` returns the cached result without re-emitting).
    """

    def __init__(self, tag: str, collect=None, result=None, attrs=None):
        self.tag = tag
        self._attrs = dict(attrs or {})
        self._result = result
        self._err = None
        self._joined = False
        self._t0 = time.monotonic()
        self._t_done = self._t0 if collect is None else None
        self._thread = None
        if collect is not None:
            self._thread = threading.Thread(
                target=self._run, args=(collect,), daemon=True,
                name=f"cocoa-exchange-{tag}")
            self._thread.start()

    def _run(self, collect):
        try:
            self._result = collect()
        except BaseException as e:  # re-raised at join()
            self._err = e
        finally:
            self._t_done = time.monotonic()

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def join(self):
        """Barrier: the collected payloads (or the collector's error)."""
        if self._joined:
            if self._err is not None:
                raise self._err
            return self._result
        from cocoa_tpu.telemetry import events as _events
        from cocoa_tpu.telemetry import tracing as _tracing

        t_join = time.monotonic()
        with _tracing.span("exchange_join", tag=self.tag, **self._attrs):
            if self._thread is not None:
                self._thread.join()
        self._joined = True
        t_done = self._t_done if self._t_done is not None else t_join
        hidden = max(0.0, min(t_done, t_join) - self._t0)
        wait = max(0.0, t_done - t_join)
        _events.get_bus().emit("comm_overlap", tag=self.tag,
                               hidden_s=hidden, wait_s=wait, **self._attrs)
        if self._err is not None:
            raise self._err
        return self._result


def async_host_allgather_bytes(tag: str, payload: bytes,
                               timeout_s: float = KV_TIMEOUT_S,
                               attempt_s: float = KV_ATTEMPT_S,
                               trace_attrs: Optional[dict] = None
                               ) -> ExchangeHandle:
    """Overlapped :func:`host_allgather_bytes`: post now, collect on a
    background thread, join at the barrier of the caller's choice.

    This worker's payload is published on the CALLER's thread before
    this returns (peers can complete their gathers even if this handle
    is never joined); the peer gets then run concurrently with whatever
    the caller does next.  ``trace_attrs`` (e.g. ``{"round": t}``) tag
    the collector's spans so trace_report can attribute the overlapped
    exchange to its round despite running off the round span's thread.
    Single-process: an already-done handle carrying ``[payload]``.
    """
    from cocoa_tpu.telemetry import tracing as _tracing

    payload = _require_host_bytes(payload)
    attrs = dict(trace_attrs or {})
    client = kv_client()
    if client is None:
        return ExchangeHandle(tag, result=[payload], attrs=attrs)
    with _tracing.span("kv_post", tag=tag, bytes=len(payload), **attrs):
        _post_chunks(client, tag, payload)

    def collect():
        with _tracing.span("kv_allgather", tag=tag, bytes=len(payload),
                           overlapped=True, **attrs):
            return _collect_allgather(client, tag, payload, timeout_s,
                                      attempt_s)

    return ExchangeHandle(tag, collect=collect, attrs=attrs)


def async_kv_get(client, key: str, *, timeout_s: float = KV_TIMEOUT_S,
                 attempt_s: float = KV_ATTEMPT_S,
                 what: Optional[str] = None,
                 trace_attrs: Optional[dict] = None) -> ExchangeHandle:
    """Overlapped :func:`blocking_kv_get`: the bounded retrying get runs
    on a collector daemon; ``join()`` returns the value (or raises the
    bounded, peer-naming error)."""
    attrs = dict(trace_attrs or {})

    def collect():
        return blocking_kv_get(client, key, timeout_s=timeout_s,
                               attempt_s=attempt_s, what=what)

    return ExchangeHandle(f"get:{key}", collect=collect, attrs=attrs)
