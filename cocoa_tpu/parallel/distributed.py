"""Multi-host (multi-process) runtime initialization.

The reference's ``--master`` flag selects the Spark cluster manager
(hingeDriver.scala:23: ``local[4]`` or a ``spark://host:port`` URL); workers
then talk over Spark's Netty RPC fabric.  The TPU-native counterpart is JAX's
multi-controller runtime: every host of a pod slice runs the same program,
``jax.distributed.initialize`` connects them through a coordinator, and
``jax.devices()`` becomes the global device set — after which the very same
``shard_map`` + ``lax.psum`` code path runs over ICI/DCN with zero further
changes (the collectives are compiled in, not library calls; SURVEY.md §2.3).

``--master=local[...]`` / ``local`` / empty keeps the single-process path,
exactly like the reference's local mode.  Anything of the form ``host:port``
(or ``spark://host:port``, accepted for drop-in compatibility) is treated as
the coordinator address.
"""

from __future__ import annotations

import os
from typing import Optional


def parse_master(master: Optional[str]) -> Optional[str]:
    """Coordinator address from a reference-style --master value, or None
    for local mode."""
    if not master:
        return None
    m = master.strip()
    if m == "local" or m.startswith("local["):
        return None
    for prefix in ("spark://", "jax://", "grpc://"):
        if m.startswith(prefix):
            m = m[len(prefix):]
            if ":" not in m:
                # an explicit scheme unambiguously requests cluster mode —
                # silently degrading to local would train K independent
                # copies, one per host
                raise ValueError(
                    f"--master={master!r} requests cluster mode but has no "
                    f"port; use {prefix}host:port"
                )
            return m
    return m if ":" in m else None


def maybe_initialize(
    master: Optional[str],
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> bool:
    """Connect this process to the multi-host runtime if --master names a
    coordinator.  Returns True iff distributed mode was initialized.

    ``process_id`` / ``num_processes`` fall back to COCOA_PROCESS_ID /
    COCOA_NUM_PROCESSES, then to JAX's auto-detection (TPU pods populate
    both from the metadata server).
    """
    coordinator = parse_master(master)
    if coordinator is None:
        return False
    import jax

    if process_id is None and os.environ.get("COCOA_PROCESS_ID"):
        process_id = int(os.environ["COCOA_PROCESS_ID"])
    if num_processes is None and os.environ.get("COCOA_NUM_PROCESSES"):
        num_processes = int(os.environ["COCOA_NUM_PROCESSES"])
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
