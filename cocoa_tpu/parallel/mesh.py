"""Device mesh and collective-communication backend.

The reference's entire communication layer is implicit Spark dataflow: the
driver broadcasts ``w`` by closure capture to K executors and sum-reduces the
per-shard ``Δw`` with ``RDD.reduce(_ + _)`` (CoCoA.scala:45-47) — one O(d)
all-reduce per outer round.  Here the same contract is carried by XLA
collectives over the ICI mesh:

- ``w`` lives **replicated** on every device: the broadcast costs nothing.
- ``Δw`` is combined with one ``lax.psum`` over the data-parallel axis.
- shard-local state (``α``, the data shard) is pinned per-device in HBM and
  never moves — the analogue of ``preservesPartitioning=true`` + per-partition
  ``α`` RDDs (CoCoA.scala:33-34,45).

Mesh axes:

- ``dp`` — data parallelism over example shards (the reference's only
  parallelism strategy; K = number of Spark partitions).
- ``fp`` — optional feature-dimension sharding of ``w``/``X`` for very large d
  (a TPU extension with no reference analogue; see SURVEY.md §2.2).

On a real pod the mesh should be built so ``dp`` rides ICI; a multi-slice
deployment puts the slowest axis on DCN.  Tests simulate K devices on CPU via
``--xla_force_host_platform_device_count`` (see tests/conftest.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
FP_AXIS = "fp"


def make_mesh(
    k: Optional[int] = None,
    fp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp,) or (dp, fp) mesh over ``k * fp`` devices.

    ``k`` defaults to using every available device on the dp axis.  Raises if
    the device count cannot satisfy the request — shards must map 1:1 onto
    mesh positions (unlike Spark, where K partitions multiplex onto fewer
    executors; on TPU the mesh *is* the worker set).
    """
    devices = list(devices if devices is not None else jax.devices())
    if k is None:
        k = len(devices) // fp
    need = k * fp
    if need > len(devices):
        raise ValueError(
            f"mesh ({k} dp x {fp} fp) needs {need} devices, "
            f"have {len(devices)}"
        )
    if fp == 1:
        return jax.make_mesh((k,), (DP_AXIS,), devices=devices[:need])
    return jax.make_mesh((k, fp), (DP_AXIS, FP_AXIS), devices=devices[:need])


def sharded_rows(mesh: Mesh, *, extra_dims: int = 0) -> NamedSharding:
    """Sharding for per-shard stacked arrays of shape (K, ...): axis 0 on dp."""
    return NamedSharding(mesh, P(DP_AXIS, *([None] * extra_dims)))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for fully replicated arrays (the global primal vector w)."""
    return NamedSharding(mesh, P())
