"""Device mesh and collective-communication backend.

The reference's entire communication layer is implicit Spark dataflow: the
driver broadcasts ``w`` by closure capture to K executors and sum-reduces the
per-shard ``Δw`` with ``RDD.reduce(_ + _)`` (CoCoA.scala:45-47) — one O(d)
all-reduce per outer round.  Here the same contract is carried by XLA
collectives over the ICI mesh:

- ``w`` lives **replicated** on every device: the broadcast costs nothing.
- ``Δw`` is combined with one ``lax.psum`` over the data-parallel axis.
- shard-local state (``α``, the data shard) is pinned per-device in HBM and
  never moves — the analogue of ``preservesPartitioning=true`` + per-partition
  ``α`` RDDs (CoCoA.scala:33-34,45).

Mesh axes:

- ``dp`` — data parallelism over example shards (the reference's only
  parallelism strategy; K = number of Spark partitions).
- ``fp`` — feature-dimension sharding of ``w``/``X`` columns for very large d
  (a TPU extension with no reference analogue; see SURVEY.md §2.2).  The fp
  axis is ``AxisType.Auto``: solvers shard_map manually over dp only and
  GSPMD inserts the fp collectives for every d-contraction (data/sharding.py
  places X as P('dp', None, 'fp'); w is P('fp') via :func:`primal_sharding`).
  fp is a *capacity* axis — it fits a d/F slice of the model and data columns
  per device; the sequential SDCA inner loop still pays one fp-reduction per
  coordinate step, so use it when d forces it, not for speed.

On a real pod the mesh should be built so ``dp`` rides ICI; a multi-slice
deployment puts the slowest axis on DCN.  Tests simulate K devices on CPU via
``--xla_force_host_platform_device_count`` (see tests/conftest.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5: explicit/auto axis types (the fp axis rides Auto)
    from jax.sharding import AxisType
except ImportError:  # older jax: dp-only meshes work; fp needs AxisType
    AxisType = None

DP_AXIS = "dp"
FP_AXIS = "fp"
TENANT_AXIS = "tenant"   # the fleet's spare axis: independent models,
                         # not shards — no collective ever crosses it


def make_mesh(
    k: Optional[int] = None,
    fp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp,) or (dp, fp) mesh over ``k * fp`` devices.

    ``k`` defaults to using every available device on the dp axis.  Raises if
    the device count cannot satisfy the request — shards must map 1:1 onto
    mesh positions (unlike Spark, where K partitions multiplex onto fewer
    executors; on TPU the mesh *is* the worker set).

    The fp axis is created with ``AxisType.Auto``: the solvers run
    ``shard_map`` manually over dp only and leave the feature dimension to
    GSPMD — annotate the shardings (X columns and w on fp), let XLA insert
    the collectives for every d-contraction.  dp stays Explicit/manual so the
    one Δw psum per round remains the visible communication contract.
    """
    devices = list(devices if devices is not None else jax.devices())
    if k is None:
        k = len(devices) // fp
    need = k * fp
    if need > len(devices):
        raise ValueError(
            f"mesh ({k} dp x {fp} fp) needs {need} devices, "
            f"have {len(devices)}"
        )
    if fp == 1:
        return jax.make_mesh((k,), (DP_AXIS,), devices=devices[:need])
    if AxisType is None:
        raise ValueError(
            "feature-parallel (fp) meshes need jax.sharding.AxisType "
            "(jax >= 0.5); this jax only supports dp meshes"
        )
    return jax.make_mesh(
        (k, fp), (DP_AXIS, FP_AXIS), devices=devices[:need],
        axis_types=(AxisType.Explicit, AxisType.Auto),
    )


def has_fp(mesh: Optional[Mesh]) -> bool:
    """True when the mesh carries a feature-parallel axis."""
    return mesh is not None and FP_AXIS in mesh.axis_names


def manual_axes(mesh: Optional[Mesh]) -> frozenset:
    """The axes shard_map runs manually over: dp only on an fp mesh (the
    feature axis is GSPMD-auto), every axis otherwise (empty set = all)."""
    return frozenset({DP_AXIS}) if has_fp(mesh) else frozenset()


def dp_local_shards(mesh: Mesh, k: int) -> list:
    """``[(device, shard_lo, shard_hi)]`` for THIS process's dp positions.

    Under ``P('dp', ...)`` sharding of a (K, ...) array on a D-device dp
    axis, dp position i holds the m = K/D consecutive logical shards
    [i·m, (i+1)·m) — the same multiplexing contract
    :func:`cocoa_tpu.parallel.fanout.shards_per_device` runs the solvers
    under.  This is the placement map the distributed dataset builders
    (whole-file and streaming ingest alike) use to materialize ONLY the
    shards whose device lives in this process.
    """
    import numpy as np

    d = mesh.shape[DP_AXIS]
    if k % d != 0:
        raise ValueError(
            f"{k} shards cannot multiplex evenly onto the {d}-device dp "
            f"axis; K must be a multiple of the mesh size (the elastic "
            f"supervisor's shrink path only ever reforms gangs whose "
            f"device count divides K — elastic.shrink_gang_size)"
        )
    m = k // d
    grid = np.asarray(mesh.devices).reshape(d, -1)
    me = jax.process_index()
    return [
        (grid[i, 0], i * m, (i + 1) * m)
        for i in range(d)
        if grid[i, 0].process_index == me
    ]


def sharded_rows(mesh: Mesh, *, extra_dims: int = 0) -> NamedSharding:
    """Sharding for per-shard stacked arrays of shape (K, ...): axis 0 on dp."""
    return NamedSharding(mesh, P(DP_AXIS, *([None] * extra_dims)))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for fully replicated arrays."""
    return NamedSharding(mesh, P())


def primal_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the global primal vector w: replicated on a dp mesh,
    split over the feature axis on a (dp, fp) mesh — each device then holds
    d/fp of w (and the matching column block of X, see data/sharding.py)."""
    return NamedSharding(mesh, P(FP_AXIS) if has_fp(mesh) else P())


def x_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the dense (K, n_shard, d) design matrix: rows over dp,
    columns over fp (when present) so each device holds the (n_shard, d/fp)
    block matching its slice of w."""
    return NamedSharding(
        mesh, P(DP_AXIS, None, FP_AXIS if has_fp(mesh) else None)
    )


# --- fleet: regex-rule partition specs over the tenant axis -----------------
#
# The fleet path (solvers/fleet.py) stacks T independent tenants on a
# leading axis of every state and data leaf.  Placement is described the
# way large-model codebases describe theirs (SNIPPETS.md [2]
# ``match_partition_rules``): an ordered list of (regex, PartitionSpec)
# rules matched against each leaf's '/'-joined tree path, first match
# wins.  Because tenants are INDEPENDENT (no collective crosses the
# tenant axis), the whole rule set is one idea — "shard the leading T
# axis, replicate the rest" — and the regex form exists so future
# composite meshes (tenant × dp) can grow per-leaf exceptions without
# touching the solver.


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """Map every leaf of ``tree`` to the PartitionSpec of the first rule
    whose regex searches its '/'-joined path (the SNIPPETS.md [2] idiom).
    Raises on an unmatched leaf — a silent default is how a new state
    leaf ends up replicated across a thousand tenants."""
    import re

    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf):
        name = _path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec
        raise ValueError(
            f"no partition rule matches tree path {name!r}; add a rule "
            f"(the catch-all '.*' usually belongs at the end)")

    return jax.tree_util.tree_map_with_path(assign, tree)


def fleet_partition_rules(tree) -> tuple:
    """The fleet rule set: every leaf with a leading tenant axis shards
    that axis; per-tenant scalars ((T,) leaves) likewise; anything else
    would be a bug — tenants share nothing."""
    del tree  # one rule covers the whole fleet state/data surface today
    return ((r".*", P(TENANT_AXIS)),)


def make_fleet_mesh(t_devices: Optional[int] = None,
                    devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-axis ``tenant`` mesh over ``t_devices`` chips: the fleet's
    (T, ...) slabs split T-major across it via
    :func:`fleet_shardings`, each chip running its T/D lanes of the one
    compiled round.  ``t_devices=1`` is the degenerate single-chip
    control — the pure-vmap path, bit-identical by construction."""
    devices = list(devices if devices is not None else jax.devices())
    t_devices = len(devices) if t_devices is None else int(t_devices)
    if t_devices > len(devices):
        raise ValueError(f"fleet mesh needs {t_devices} devices, have "
                         f"{len(devices)}")
    return jax.make_mesh((t_devices,), (TENANT_AXIS,),
                         devices=devices[:t_devices])


def fleet_shardings(mesh: Mesh, tree):
    """NamedShardings for a fleet pytree from the regex rules — the
    device_put map for state, shard slabs, and per-tenant scalars."""
    specs = match_partition_rules(fleet_partition_rules(tree), tree)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def pad_features(d: int, mesh: Optional[Mesh]) -> int:
    """d rounded up to an fp-and-sublane multiple.  The feature-parallel
    column split needs equal blocks; the Pallas SDCA kernel's folded-row
    layout needs d % 8 == 0.  Zero pad columns touch nothing — no update
    ever flows into them and w's matching entries stay exactly 0."""
    import math

    fp = mesh.shape[FP_AXIS] if has_fp(mesh) else 1
    m = math.lcm(fp, 8)
    return -(-d // m) * m
