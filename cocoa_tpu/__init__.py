"""cocoa_tpu — a TPU-native (JAX/XLA) framework for communication-efficient
distributed primal-dual optimization.

Re-implementation, from scratch and TPU-first, of the capabilities of the
reference Spark/Scala framework (calvinmccarter/cocoa): CoCoA / CoCoA+ /
mini-batch SDCA / local SGD / mini-batch SGD / distributed subgradient descent
for L2-regularized hinge-loss SVMs, with duality-gap convergence certificates.

Architecture (see SURVEY.md for the reference layer map this mirrors):

- ``cocoa_tpu.data``     — LIBSVM ingestion + device-sharded dataset layouts
                           (dense and padded-CSR), replacing the reference's
                           Spark RDD loader (OptUtils.scala:11-53).
- ``cocoa_tpu.parallel`` — device mesh + collective helpers; the Spark
                           closure-broadcast / ``RDD.reduce`` communication
                           backend (CoCoA.scala:45-47) becomes a single
                           ``lax.psum`` over the ICI mesh.
- ``cocoa_tpu.ops``      — jit-compiled local solvers (the per-worker inner
                           loops: SDCA, SGD, subgradient pass), the TPU
                           equivalents of CoCoA.scala:130-192 etc.
- ``cocoa_tpu.solvers``  — outer-loop drivers (CoCoA.scala:39-63 skeleton):
                           one jitted, shard_mapped round-step per algorithm,
                           driven by a pure-Python (or lax.scan) outer loop.
- ``cocoa_tpu.evals``    — primal/dual objectives, duality gap, classification
                           error (OptUtils.scala:57-98 math) as sharded
                           reductions.
- ``cocoa_tpu.utils``    — reference-faithful RNG (java.util.Random LCG),
                           trajectory logging, misc.
- ``cocoa_tpu.checkpoint`` — round-stamped save/restore of (w, alpha, t, key);
                           strictly more capable than the reference's RDD
                           lineage checkpointing (CoCoA.scala:59-62).
- ``cocoa_tpu.cli``      — accepts the full reference flag set
                           (hingeDriver.scala:22-38) and runs the same
                           algorithm menu.
"""

__version__ = "0.1.0"

from cocoa_tpu.config import Params, DebugParams  # noqa: F401
