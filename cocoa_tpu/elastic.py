"""Elastic multi-process supervision: gang restart, shrink-to-survivors,
checkpoint resume.

The reference inherits implicit fault recovery from Spark — a lost task is
recomputed from RDD lineage (OptClasses.scala:36 "ensure persistence and
shorter dependencies", hinge/CoCoA.scala:59-62 checkpoint truncation).
That model does not transplant to a multi-controller all-reduce runtime:
when one process of a JAX gang dies, the surviving processes are wedged
inside a collective — there is no per-task granularity to recompute.  The
honest equivalent is **gang restart from the last checkpoint**: a
supervisor launches the N worker processes, watches them, and on any
worker death kills the survivors and relaunches the whole gang with
``--resume``.  Round-keyed sampling makes the resumed trajectory identical
to an uninterrupted run (tests/test_crash_resume.py), so the only cost of
a failure is the rounds since the last ``--chkptIter`` save — the same
bound Spark's lineage recomputation gives, without keeping every round's
lineage alive.

**Shrink-to-survivors** (docs/DESIGN.md §13): same-size restart assumes
the dead worker's host is coming back.  When it is not (a preempted VM, a
failed machine), relaunching at the same P deadlocks forever — every
generation stalls at the rendezvous.  CoCoA+'s math is keyed to the K data
shards, not to the processes hosting them (Ma et al., arXiv:1502.03508:
the dual decomposition, the round-keyed sampling tables and the σ′/Θ/accel
schedules are all shard-count-keyed), so the process→shard mapping is a
free variable the runtime may re-solve after a failure.  With
``num_splits`` given, the supervisor reforms the gang at the largest
P′ < P whose device count still divides K (``--elastic=N``: after
``max_restarts`` consecutive failed same-size generations;
``--elastic=shrink``: immediately on the first loss), relaunches with
``--numProcesses=P′ --resume``, and each survivor re-ingests only its
newly inherited shards through the streaming two-pass pipeline
(data/ingest.py).  A K that no smaller gang can divide is rejected loudly
— never a silent hang.

Between restart generations the supervisor backs off exponentially with
seeded jitter (capped, reset on progress) instead of spinning on the
relaunch: a crash-looping gang must not hammer a shared coordinator or
filesystem at poll speed.

**Overlapped/stale exchanges across a resize** (docs/DESIGN.md §15):
workers running with ``--overlapComm``/``--staleRounds`` may hold
in-flight exchange handles and a window of pending stale joins when the
gang dies.  Nothing here needs to unwind them: the collector threads
are daemons bounded by the KV budget (parallel/distributed.py), so the
SIGKILL teardown above cannot deadlock on them, and the pending joins
die with the generation's processes (StaleJoinWindow.abort is the
in-process spelling of the same rule).  Soundness across the resize
comes from the checkpoint discipline — the gang path only checkpoints
at DRAINED boundaries, where every contribution has been applied and
w = w(α) holds exactly — so the reformed gang resumes from a state
that embeds no half-joined round (pinned: tests/test_overlap.py
``test_gang_resize_with_staleness_drops_pending_joins``).

**Serving across failures** (docs/DESIGN.md §17): a ``--serve`` process
pointed at this gang's ``--chkptDir`` is deliberately OUTSIDE the gang
— it reads validated checkpoint generations, never joins a collective —
so nothing the supervisor does (SIGKILL teardown, shrink, restart
backoff) can wedge or drop a query.  During an outage the server keeps
answering from the last validated generation with its gap-age gauge
climbing; the first save of the reformed gang is picked up by the swap
watcher like any other generation.  The checkpoint discipline this
relies on is already the shrink contract above: generations are
complete (full gathered α, shard-count-keyed state) and validated
newest-first with fallback, so a kill mid-save can never publish a torn
model to the server.

Activated by ``--elastic=N`` (or ``--elastic=N,shrink`` /
``--elastic=shrink``) on the CLI: the invoking process becomes the
supervisor and re-executes its own command line N times with
``--master=127.0.0.1:<port> --processId=i --numProcesses=N --resume``.
A fresh coordinator port is chosen per generation (a dying coordinator can
leave the old port lingering in TIME_WAIT, and a shrunk gang must not
rendezvous with a stale generation's store).

Each (re)launched worker ingests data exactly like any multi-process run:
``--ingest=auto`` streams — pass-1 index scan of 1/P of the LIBSVM file,
pass-2 parse of only that worker's own shards' byte ranges (data/ingest.py,
docs/DESIGN.md §12, README "Multi-host quickstart") — so a gang restart
re-pays ~2/P of a full parse per worker, not P redundant whole-file
parses; after a shrink the same pipeline hands each survivor its
inherited m = K/P′ shards with no resharding code of its own.

With ``--ingestCache=DIR`` (data/slab_cache.py, docs/DESIGN.md §18) a
restart generation re-pays NOTHING: the supervisor re-executes the
user's command line verbatim (``strip_elastic_flags`` removes only the
flags the supervisor owns, so the cache dir is forwarded to every
relaunched generation), and because the slab artifacts are keyed by
SHARD — not by process count or mesh — a shrunk gang's survivors re-map
their newly inherited shards warm: the shrink re-ingest parses zero
bytes (pinned by the chaos suite's cache variant).
"""

from __future__ import annotations

import random
import signal
import socket
import subprocess
import sys
import time
from typing import Optional


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def shrink_gang_size(num_splits: int, current: int,
                     devices_per_worker: int = 1) -> Optional[int]:
    """The largest gang size P′ < ``current`` whose device count divides
    the K logical shards, or None when no smaller gang can carry them.

    K must split evenly over the new gang's devices because the dp mesh
    multiplexes m = K/D whole shards per device (parallel/mesh.py
    ``dp_local_shards``) — the shard set, and with it the trajectory, is
    preserved exactly; only its placement moves.  With one device per
    worker P′=1 always qualifies (every K divides one device); multi-chip
    workers can genuinely strand a K, which callers must reject loudly.
    """
    for p in range(current - 1, 0, -1):
        if num_splits % (p * devices_per_worker) == 0:
            return p
    return None


def backoff_seconds(streak: int, base_s: float, cap_s: float,
                    jitter: float, rng: random.Random) -> float:
    """Exponential backoff with jitter for the ``streak``-th consecutive
    failed generation: min(cap, base·2^(streak-1)) scaled by a uniform
    factor in [1-jitter, 1+jitter].  ``base_s <= 0`` disables the wait
    (tests); the seeded ``rng`` keeps chaos runs deterministic."""
    if base_s <= 0 or streak <= 0:
        return 0.0
    delay = min(cap_s, base_s * (2.0 ** (streak - 1)))
    return delay * (1.0 + jitter * (2.0 * rng.random() - 1.0))


def _spawn(worker_argv, i, n, port, python, module, quiet_tail, resume):
    argv = [
        python, "-m", module, *worker_argv,
        f"--master=127.0.0.1:{port}",
        f"--processId={i}", f"--numProcesses={n}",
        *(["--resume"] if resume else []),
    ]
    out = None if (i == 0 or not quiet_tail) else subprocess.DEVNULL
    return subprocess.Popen(argv, stdout=out, stderr=out)


def supervise(
    worker_argv: list,
    num_processes: int,
    max_restarts: int = 5,
    poll_s: float = 0.25,
    python: Optional[str] = None,
    module: str = "cocoa_tpu.cli",
    quiet_tail: bool = True,
    on_generation=None,   # hook(gen_index, procs) after each gang spawn —
                          # fault-injection handle (tests/_faults.FaultPlan)
    resume: bool = True,  # pass --resume to workers (False when there is
                          # no --chkptDir: the CLI rejects --resume
                          # without one, and there is nothing to resume)
    progress_token=None,  # 0-arg callable capturing run progress (e.g. the
                          # checkpoint-directory state); when it CHANGES
                          # between generations the restart budget resets —
                          # "max_restarts" bounds CONSECUTIVE failed
                          # generations, not lifetime failures of a long
                          # run that keeps advancing
    stall_timeout_s: Optional[float] = None,
                          # no-progress watchdog (ADVICE r4): a gang can
                          # wedge with every process still alive — a dead
                          # device tunnel hangs the dispatch (the failure
                          # mode that cost round 4 its benchmark artifact),
                          # or one worker exits 0 while its peers block in
                          # a collective that will never complete.  With
                          # ``progress_token`` set, a generation whose
                          # token has not changed for this many seconds is
                          # killed and restarted exactly like a death
                          # (counting against the consecutive-failure
                          # budget — a stalled generation made no
                          # progress, so the budget must not reset).
    num_splits: Optional[int] = None,
                          # K logical shards — what shrink re-divides.
                          # None disables shrink entirely (the pre-shrink
                          # kill-and-relaunch-same-N behavior).
    shrink: str = "auto", # "auto": same-size restarts until max_restarts
                          # consecutive failures, THEN reform at P′ < P
                          # instead of giving up; "now": reform on the
                          # first loss (--elastic=shrink — the dead host
                          # is known not to come back); "off": never
                          # resize (give up after the budget, as before)
    devices_per_worker: int = 1,
                          # local devices each worker process owns (1 for
                          # a localhost CPU gang; the per-host chip count
                          # on TPU) — the granularity K must divide
    backoff_base_s: float = 1.0,
    backoff_cap_s: float = 60.0,
    backoff_jitter: float = 0.5,
    backoff_seed: int = 0,
                          # exponential-backoff-with-jitter policy between
                          # restart generations; the seed keeps chaos runs
                          # deterministic.  base <= 0 disables the wait.
    on_restart=None,      # hook(generation, reason, old_size, new_size,
                          # backoff_s) before each relaunch — the restart
                          # decisions, observable without parsing stderr
) -> int:
    """Run the gang to completion, restarting it (from the latest
    checkpoint, via the workers' ``--resume``) whenever any member dies —
    or, with ``stall_timeout_s``, whenever it stops making progress —
    and, with ``num_splits``, reforming it at P′ < P survivors when the
    same-size gang cannot be kept alive (see module docstring).
    Returns the final exit code (0 on success; the failing worker's code
    after the budget is exhausted with no smaller gang to fall back to).

    ``worker_argv`` is the user's flag list WITHOUT --master/--processId/
    --numProcesses/--elastic (the supervisor owns those).  Worker 0
    inherits stdout (the reference prints from the driver); other workers
    are silenced unless ``quiet_tail=False``.  On a shrunk generation any
    user ``--mesh`` is dropped from the worker line — the old device grid
    no longer exists; the workers re-infer the mesh from P′.
    """
    python = python or sys.executable
    if stall_timeout_s is not None and progress_token is None:
        raise ValueError("stall_timeout_s needs progress_token — without "
                         "a token there is no progress signal to watch")
    if shrink not in ("auto", "now", "off"):
        raise ValueError(f"shrink must be auto|now|off, got {shrink!r}")
    rng = random.Random(backoff_seed)
    n_cur = num_processes
    argv_cur = list(worker_argv)
    restarts = 0   # consecutive failed generations at the CURRENT size —
                   # the give-up / shrink budget (reset on progress AND on
                   # resize: a reformed gang earns a fresh budget)
    streak = 0     # consecutive failed generations since the last
                   # PROGRESS — the backoff exponent (a resize does not
                   # reset it: the run is still failing, keep backing off)
    gen = 0
    last_token = progress_token() if progress_token else None
    from cocoa_tpu.telemetry import tracing as _tracing

    while True:
        port = free_port()
        # span numbering matches the restart/gang_resize EVENTS and the
        # flightrec manifest ("gangs spawned so far", 1-based: this gang
        # is gen+1 until the post-spawn increment below) — only the
        # on_generation test hook keeps its historical 0-based index
        with _tracing.span("gang_generation", generation=gen + 1,
                           gang_size=n_cur):
            procs = [
                _spawn(argv_cur, i, n_cur, port, python, module,
                       quiet_tail, resume)
                for i in range(n_cur)
            ]
            if on_generation is not None:
                on_generation(gen, procs)
            gen += 1
            failed = None
            failed_idx = None
            stalled = False
            last_change = time.monotonic()
            try:
                while True:
                    codes = [p.poll() for p in procs]
                    for idx, c in enumerate(codes):
                        if c not in (None, 0):
                            failed = c
                            failed_idx = idx
                            break
                    if failed is not None:
                        break
                    if all(c == 0 for c in codes):
                        return 0
                    if stall_timeout_s is not None:
                        token = progress_token()
                        if token != last_token:
                            last_token = token
                            last_change = time.monotonic()
                            restarts = 0  # live progress breaks the streak
                            streak = 0
                        elif (time.monotonic() - last_change
                                > stall_timeout_s):
                            stalled = True
                            break
                    time.sleep(poll_s)
            finally:
                # any survivors are wedged inside a collective whose peer
                # died (or we are unwinding on KeyboardInterrupt) — kill
                # the gang
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.send_signal(signal.SIGKILL)
                        except OSError:
                            pass
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
        if progress_token is not None:
            token = progress_token()
            if token != last_token:
                restarts = 0      # the dead generation still advanced the
                streak = 0        # run — the failure streak is broken
                last_token = token
        restarts += 1
        streak += 1
        attempt_used = restarts   # what the restart event reports: the
        # consecutive failures that led HERE — a resize zeroes the budget
        # counter below, but the event must still say the budget was
        # exhausted, not "attempt 0"
        reason = "gang_stalled" if stalled else "worker_died"
        # machine-readable restart trace: the supervisor's bus (configured
        # by the CLI's --events; inert otherwise) appends to the same
        # JSONL the workers write — whole-line appends interleave safely
        from cocoa_tpu.telemetry import events as _tele

        # flight-recorder dump on the victim's behalf: a SIGKILLed worker
        # cannot dump its own ring, but its events were streaming to its
        # per-process JSONL — tail it and leave the `.flightrec`
        # explanation artifact next to it (telemetry/recorder.py).  A
        # stall has no single victim; dump worker 0's tail as the gang's
        # last-known state instead.
        if _tele.get_bus().jsonl_path:
            from cocoa_tpu.telemetry import recorder as _recorder

            # victim_pid scopes the tail to the dead process's own
            # records (worker 0 shares its file with the supervisor, and
            # every stream accumulates prior generations); a stall has
            # no single victim — dump worker 0's stream unscoped as the
            # gang's last-known state
            victim_pid = (getattr(procs[failed_idx], "pid", None)
                          if failed_idx is not None else None)
            _recorder.dump_victim(
                _tele.get_bus().jsonl_path,
                failed_idx if failed_idx is not None else 0,
                reason, exit_code=failed, generation=gen,
                victim_pid=victim_pid)

        old_n = n_cur
        can_shrink = (num_splits is not None and shrink != "off"
                      and n_cur > 1)
        # "now" fast-path applies to worker LOSS only: a stall has every
        # process alive (transient wedge — NFS hiccup, slow device), so
        # shrinking on the first one would permanently downsize a healthy
        # gang; stalls burn the restart budget instead (the fault model
        # table, docs/DESIGN.md §13) and shrink only when it exhausts
        if can_shrink and ((shrink == "now" and not stalled)
                           or restarts > max_restarts):
            n_new = shrink_gang_size(num_splits, n_cur, devices_per_worker)
            if n_new is None:
                # reject loudly: no smaller gang's devices divide K — a
                # relaunch at any P′ would fail its own divisibility
                # check, so say why and stop instead of crash-looping
                print(f"elastic: cannot reform the gang below {n_cur} "
                      f"workers — numSplits={num_splits} does not divide "
                      f"across any smaller gang's devices "
                      f"({devices_per_worker} per worker); giving up "
                      f"(pick a numSplits with more divisors to allow "
                      f"deeper shrink)", file=sys.stderr, flush=True)
                return int(failed or 1)
            _tele.get_bus().emit(
                "gang_resize", reason=reason, old_size=n_cur,
                new_size=n_new, generation=gen, num_splits=num_splits,
                exit_code=failed)
            stripped = [a for a in argv_cur
                        if a.lstrip("-").split("=", 1)[0] != "mesh"]
            if len(stripped) != len(argv_cur):
                print("elastic: dropping the explicit --mesh from the "
                      "worker line — the reformed gang re-infers its mesh "
                      f"from {n_new} worker(s)", file=sys.stderr)
            argv_cur = stripped
            n_cur = n_new
            restarts = 0   # a reformed gang earns a fresh same-size budget
        elif restarts > max_restarts:
            why = ("stalled" if stalled
                   else f"failed (last exit code {failed})")
            print(f"elastic: giving up after {max_restarts} consecutive "
                  f"{why} generations", file=sys.stderr)
            return int(failed or 1)
        backoff = backoff_seconds(streak, backoff_base_s, backoff_cap_s,
                                  backoff_jitter, rng)
        _tele.get_bus().emit(
            "restart", reason=reason,
            attempt=attempt_used, max_restarts=max_restarts,
            exit_code=failed, generation=gen, gang_size=n_cur,
            backoff_s=backoff)
        if on_restart is not None:
            on_restart(gen, reason, old_n, n_cur, backoff)
        what = (f"gang made no progress for {stall_timeout_s:g}s"
                if stalled else f"worker died (exit {failed})")
        if n_cur != old_n:
            print(f"elastic: {what}; reforming the gang at {n_cur} of "
                  f"{old_n} workers ({num_splits} shards re-divided over "
                  f"the survivors) from the latest checkpoint"
                  + (f" after {backoff:.1f}s backoff" if backoff else ""),
                  file=sys.stderr, flush=True)
        else:
            print(f"elastic: {what}; restarting gang "
                  f"(attempt {restarts}/{max_restarts}) from the latest "
                  f"checkpoint"
                  + (f" after {backoff:.1f}s backoff" if backoff else ""),
                  file=sys.stderr, flush=True)
        if backoff > 0:
            with _tracing.span("restart_backoff", generation=gen,
                               backoff_s=backoff):
                time.sleep(backoff)


def strip_elastic_flags(argv: list) -> list:
    """The worker command line = the user's line minus the flags the
    supervisor owns (it re-adds its own --master/--processId/...)."""
    own = ("elastic", "master", "processId", "numProcesses", "resume",
           "stallTimeout")
    out = []
    for a in argv:
        key = a.lstrip("-").split("=", 1)[0]
        if key not in own:
            out.append(a)
    return out
