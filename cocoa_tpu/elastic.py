"""Elastic multi-process supervision: gang restart + checkpoint resume.

The reference inherits implicit fault recovery from Spark — a lost task is
recomputed from RDD lineage (OptClasses.scala:36 "ensure persistence and
shorter dependencies", hinge/CoCoA.scala:59-62 checkpoint truncation).
That model does not transplant to a multi-controller all-reduce runtime:
when one process of a JAX gang dies, the surviving processes are wedged
inside a collective — there is no per-task granularity to recompute.  The
honest equivalent is **gang restart from the last checkpoint**: a
supervisor launches the N worker processes, watches them, and on any
worker death kills the survivors and relaunches the whole gang with
``--resume``.  Round-keyed sampling makes the resumed trajectory identical
to an uninterrupted run (tests/test_crash_resume.py), so the only cost of
a failure is the rounds since the last ``--chkptIter`` save — the same
bound Spark's lineage recomputation gives, without keeping every round's
lineage alive.

Activated by ``--elastic=N`` on the CLI: the invoking process becomes the
supervisor and re-executes its own command line N times with
``--master=127.0.0.1:<port> --processId=i --numProcesses=N --resume``.
A fresh coordinator port is chosen per generation (a dying coordinator can
leave the old port lingering in TIME_WAIT).

Each (re)launched worker ingests data exactly like any multi-process run:
``--ingest=auto`` streams — pass-1 index scan of 1/P of the LIBSVM file,
pass-2 parse of only that worker's own shards' byte ranges (data/ingest.py,
docs/DESIGN.md §12, README "Multi-host quickstart") — so a gang restart
re-pays ~2/P of a full parse per worker, not P redundant whole-file
parses.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(worker_argv, i, n, port, python, module, quiet_tail, resume):
    argv = [
        python, "-m", module, *worker_argv,
        f"--master=127.0.0.1:{port}",
        f"--processId={i}", f"--numProcesses={n}",
        *(["--resume"] if resume else []),
    ]
    out = None if (i == 0 or not quiet_tail) else subprocess.DEVNULL
    return subprocess.Popen(argv, stdout=out, stderr=out)


def supervise(
    worker_argv: list,
    num_processes: int,
    max_restarts: int = 5,
    poll_s: float = 0.25,
    python: Optional[str] = None,
    module: str = "cocoa_tpu.cli",
    quiet_tail: bool = True,
    on_generation=None,   # hook(gen_index, procs) after each gang spawn —
                          # fault-injection handle for tests
    resume: bool = True,  # pass --resume to workers (False when there is
                          # no --chkptDir: the CLI rejects --resume
                          # without one, and there is nothing to resume)
    progress_token=None,  # 0-arg callable capturing run progress (e.g. the
                          # checkpoint-directory state); when it CHANGES
                          # between generations the restart budget resets —
                          # "max_restarts" bounds CONSECUTIVE failed
                          # generations, not lifetime failures of a long
                          # run that keeps advancing
    stall_timeout_s: Optional[float] = None,
                          # no-progress watchdog (ADVICE r4): a gang can
                          # wedge with every process still alive — a dead
                          # device tunnel hangs the dispatch (the failure
                          # mode that cost round 4 its benchmark artifact),
                          # or one worker exits 0 while its peers block in
                          # a collective that will never complete.  With
                          # ``progress_token`` set, a generation whose
                          # token has not changed for this many seconds is
                          # killed and restarted exactly like a death
                          # (counting against the consecutive-failure
                          # budget — a stalled generation made no
                          # progress, so the budget must not reset).
) -> int:
    """Run the gang to completion, restarting it (from the latest
    checkpoint, via the workers' ``--resume``) whenever any member dies —
    or, with ``stall_timeout_s``, whenever it stops making progress.
    Returns the final exit code (0 on success; the failing worker's code
    after ``max_restarts`` consecutive failed generations).

    ``worker_argv`` is the user's flag list WITHOUT --master/--processId/
    --numProcesses/--elastic (the supervisor owns those).  Worker 0
    inherits stdout (the reference prints from the driver); other workers
    are silenced unless ``quiet_tail=False``.
    """
    python = python or sys.executable
    if stall_timeout_s is not None and progress_token is None:
        raise ValueError("stall_timeout_s needs progress_token — without "
                         "a token there is no progress signal to watch")
    restarts = 0
    gen = 0
    last_token = progress_token() if progress_token else None
    while True:
        port = free_port()
        procs = [
            _spawn(worker_argv, i, num_processes, port, python, module,
                   quiet_tail, resume)
            for i in range(num_processes)
        ]
        if on_generation is not None:
            on_generation(gen, procs)
        gen += 1
        failed = None
        stalled = False
        last_change = time.monotonic()
        try:
            while True:
                codes = [p.poll() for p in procs]
                bad = [c for c in codes if c not in (None, 0)]
                if bad:
                    failed = bad[0]
                    break
                if all(c == 0 for c in codes):
                    return 0
                if stall_timeout_s is not None:
                    token = progress_token()
                    if token != last_token:
                        last_token = token
                        last_change = time.monotonic()
                        restarts = 0   # live progress breaks the streak
                    elif time.monotonic() - last_change > stall_timeout_s:
                        stalled = True
                        break
                time.sleep(poll_s)
        finally:
            # any survivors are wedged inside a collective whose peer died
            # (or we are unwinding on KeyboardInterrupt) — kill the gang
            for p in procs:
                if p.poll() is None:
                    try:
                        p.send_signal(signal.SIGKILL)
                    except OSError:
                        pass
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        if progress_token is not None:
            token = progress_token()
            if token != last_token:
                restarts = 0      # the dead generation still advanced the
                last_token = token  # run — the failure streak is broken
        restarts += 1
        if restarts > max_restarts:
            why = ("stalled" if stalled
                   else f"failed (last exit code {failed})")
            print(f"elastic: giving up after {max_restarts} consecutive "
                  f"{why} generations", file=sys.stderr)
            return int(failed or 1)
        what = (f"gang made no progress for {stall_timeout_s:g}s"
                if stalled else f"worker died (exit {failed})")
        # machine-readable restart trace: the supervisor's bus (configured
        # by the CLI's --events; inert otherwise) appends to the same
        # JSONL the workers write — whole-line appends interleave safely
        from cocoa_tpu.telemetry import events as _tele

        _tele.get_bus().emit(
            "restart", reason="gang_stalled" if stalled else "worker_died",
            attempt=restarts, max_restarts=max_restarts,
            exit_code=failed, generation=gen)
        print(f"elastic: {what}; restarting gang "
              f"(attempt {restarts}/{max_restarts}) from the latest "
              f"checkpoint", file=sys.stderr, flush=True)


def strip_elastic_flags(argv: list) -> list:
    """The worker command line = the user's line minus the flags the
    supervisor owns (it re-adds its own --master/--processId/...)."""
    own = ("elastic", "master", "processId", "numProcesses", "resume",
           "stallTimeout")
    out = []
    for a in argv:
        key = a.lstrip("-").split("=", 1)[0]
        if key not in own:
            out.append(a)
    return out
