from cocoa_tpu.evals.objectives import (  # noqa: F401
    classification_error,
    dual_objective,
    duality_gap,
    primal_objective,
)
