"""Objectives and the duality-gap convergence certificate.

Math from OptUtils.scala:57-98:

- hinge loss           max(1 − y·(x·w), 0)                      (:57-61)
- primal objective     avg hinge + (λ/2)‖w‖²                    (:73-75)
- dual objective       −(λ/2)‖w‖² + Σα/n                        (:80-84)
- duality gap          primal − dual                            (:89-91)
- classification error mean over examples of [y·(x·w) ≤ 0]      (:95-98)

These cost a full data pass (the reference gates them to every ``debugIter``
rounds — CoCoA.scala:51); same policy here.  Each reduction runs through the
same fan-out machinery as the solvers (parallel/fanout.py): per-shard partial
sums, one scalar ``lax.psum`` — the TPU equivalent of
``data.map(...).reduce(_ + _)`` (OptUtils.scala:67).  Padded rows are
excluded via the mask.  The dp mesh is inferred from array placement, so the
same code serves the multi-device and single-chip paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.ops import losses
from cocoa_tpu.ops.rows import eval_margins
from cocoa_tpu.parallel.fanout import fanout, mesh_of


@functools.lru_cache(maxsize=None)
def _loss_sum_fn(mesh, loss, smoothing):
    def per_shard(w, shard):
        vals = losses.primal(loss, shard["labels"] * eval_margins(w, shard),
                             smoothing=smoothing)
        return (jnp.sum(vals * shard["mask"]),)

    @jax.jit
    def f(w, shard_arrays):
        (total,) = fanout(per_shard, mesh, w, shard_arrays)
        return total

    return f


@functools.lru_cache(maxsize=None)
def _dual_sum_fn(mesh, loss, smoothing):
    def per_shard(w, alpha_k, shard):
        return (jnp.sum(losses.dual_term(loss, alpha_k, smoothing=smoothing)
                        * shard["mask"]),)

    @jax.jit
    def f(w, alpha, shard_arrays):
        (total,) = fanout(per_shard, mesh, w, alpha, shard_arrays)
        return total

    return f


@functools.lru_cache(maxsize=None)
def _error_sum_fn(mesh):
    def per_shard(w, shard):
        correct = (eval_margins(w, shard) * shard["labels"]) > 0.0
        return (jnp.sum(jnp.where(correct, 0.0, 1.0) * shard["mask"]),)

    @jax.jit
    def f(w, shard_arrays):
        (total,) = fanout(per_shard, mesh, w, shard_arrays)
        return total

    return f


def eval_metrics(
    w, alpha, shard_arrays, lam, n, mesh=None,
    test_shard_arrays=None, test_n: int = 0,
    loss: str = "hinge", smoothing: float = 1.0,
    inv_n=None,
):
    """Jit-traceable fused evaluation: (primal, gap, test_error) as one
    stacked device array — a single fan-out over the training data (plus one
    over the test data when given) and ZERO host syncs.  The building block
    for both the fused host-side ``evaluate`` (one fetch per eval instead of
    four) and the fully device-resident driver (solvers/base.py
    ``drive_on_device``), where a host round-trip through the device tunnel
    costs ~100ms — 1000x the eval compute itself.

    ``test_error`` is NaN when no test set is given; ``gap`` is NaN for
    primal-only solvers (``alpha=None`` — SGD / DistGD have no dual state).

    ``inv_n`` (the fleet path, solvers/fleet.py): a precomputed — possibly
    TRACED, per-tenant — 1/n scalar replacing the ``/ n`` division.  The
    static path's jit folds division by the constant n into one f32
    reciprocal multiply; a traced n cannot be folded, so the fleet passes
    the same f32 reciprocal explicitly — which is what keeps a T=1 fleet
    eval bit-identical to the solo certificate (tests/test_fleet.py).
    """
    def over_n(x):
        return x / n if inv_n is None else x * inv_n

    w_norm_sq = w @ w
    if alpha is not None:

        def per_shard(w, alpha_k, shard):
            margins = eval_margins(w, shard)
            vals = losses.primal(loss, shard["labels"] * margins,
                                 smoothing=smoothing)
            dual_vals = losses.dual_term(loss, alpha_k, smoothing=smoothing)
            mask = shard["mask"]
            return (jnp.stack([jnp.sum(vals * mask),
                               jnp.sum(dual_vals * mask)]),)

        (sums,) = fanout(per_shard, mesh, w, alpha, shard_arrays)
        primal = over_n(sums[0]) + 0.5 * lam * w_norm_sq
        dual = -0.5 * lam * w_norm_sq + over_n(sums[1])
        gap = primal - dual
    else:

        def per_shard(w, shard):
            margins = eval_margins(w, shard)
            vals = losses.primal(loss, shard["labels"] * margins,
                                 smoothing=smoothing)
            return (jnp.sum(vals * shard["mask"]),)

        (loss_sum,) = fanout(per_shard, mesh, w, shard_arrays)
        primal = over_n(loss_sum) + 0.5 * lam * w_norm_sq
        gap = jnp.asarray(jnp.nan, primal.dtype)

    if test_shard_arrays is not None:

        def per_test_shard(w, shard):
            wrong = (eval_margins(w, shard) * shard["labels"]) <= 0.0
            return (jnp.sum(jnp.where(wrong, 1.0, 0.0) * shard["mask"]),)

        (errors,) = fanout(per_test_shard, mesh, w, test_shard_arrays)
        test_err = errors / test_n
    else:
        test_err = jnp.asarray(jnp.nan, primal.dtype)
    return jnp.stack([primal, gap, test_err])


@functools.lru_cache(maxsize=None)
def _eval_metrics_fn(mesh, lam, n, test_n, loss, smoothing):
    # None arguments (no dual state / no test set) are empty pytrees — jit
    # specializes on the pytree structure, no separate static flags needed
    @jax.jit
    def f(w, alpha, shard_arrays, test_shard_arrays):
        return eval_metrics(
            w, alpha, shard_arrays, lam, n, mesh=mesh,
            test_shard_arrays=test_shard_arrays, test_n=test_n,
            loss=loss, smoothing=smoothing,
        )

    return f


def evaluate(ds: ShardedDataset, w, alpha, lam, test_ds=None,
             loss: str = "hinge", smoothing: float = 1.0):
    """Fused host-side eval: returns (primal, gap_or_None,
    test_error_or_None) with exactly ONE device→host transfer (a tunneled
    device costs ~90ms per fetch; the unfused path pays four).
    ``alpha=None`` for primal-only solvers → gap is None."""
    import numpy as np

    from cocoa_tpu.analysis import sanitize

    f = _eval_metrics_fn(
        mesh_of(ds.labels), float(lam), ds.n,
        test_ds.n if test_ds is not None else 0,
        loss, float(smoothing),
    )
    out = f(
        w, alpha, ds.shard_arrays(),
        None if test_ds is None else test_ds.shard_arrays(),
    )
    # the one sanctioned device→host fetch of the host-stepped eval
    # cadence (the transfer-guard sanitizer disallows any other)
    with sanitize.intended_fetch("eval_fetch"):
        out = np.asarray(out)
        primal, gap, test_err = (float(v) for v in out)
    return (
        primal,
        None if np.isnan(gap) else gap,
        None if np.isnan(test_err) else test_err,
    )


def primal_objective(ds: ShardedDataset, w, lam, loss: str = "hinge",
                     smoothing: float = 1.0) -> float:
    loss_sum = _loss_sum_fn(mesh_of(ds.labels), loss, float(smoothing))(
        w, ds.shard_arrays()
    )
    return float(loss_sum) / ds.n + 0.5 * lam * float(w @ w)


def dual_objective(ds: ShardedDataset, w, alpha, lam, loss: str = "hinge",
                   smoothing: float = 1.0) -> float:
    """alpha: (K, n_shard) sharded dual variables."""
    dual_sum = _dual_sum_fn(mesh_of(ds.labels), loss, float(smoothing))(
        w, alpha, ds.shard_arrays()
    )
    return -0.5 * lam * float(w @ w) + float(dual_sum) / ds.n


def duality_gap(ds: ShardedDataset, w, alpha, lam, loss: str = "hinge",
                smoothing: float = 1.0) -> float:
    return (primal_objective(ds, w, lam, loss, smoothing)
            - dual_objective(ds, w, alpha, lam, loss, smoothing))


def classification_error(ds: ShardedDataset, w) -> float:
    errors = _error_sum_fn(mesh_of(ds.labels))(w, ds.shard_arrays())
    return float(errors) / ds.n
