"""Objectives and the duality-gap convergence certificate.

Math from OptUtils.scala:57-98:

- hinge loss           max(1 − y·(x·w), 0)                      (:57-61)
- primal objective     avg hinge + (λ/2)‖w‖²                    (:73-75)
- dual objective       −(λ/2)‖w‖² + Σα/n                        (:80-84)
- duality gap          primal − dual                            (:89-91)
- classification error mean over examples of [y·(x·w) ≤ 0]      (:95-98)

These cost a full data pass (the reference gates them to every ``debugIter``
rounds — CoCoA.scala:51); same policy here.  Each reduction runs through the
same fan-out machinery as the solvers (parallel/fanout.py): per-shard partial
sums, one scalar ``lax.psum`` — the TPU equivalent of
``data.map(...).reduce(_ + _)`` (OptUtils.scala:67).  Padded rows are
excluded via the mask.  The dp mesh is inferred from array placement, so the
same code serves the multi-device and single-chip paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cocoa_tpu.data.sharding import ShardedDataset
from cocoa_tpu.ops.rows import shard_margins
from cocoa_tpu.parallel.fanout import fanout, mesh_of


@functools.lru_cache(maxsize=None)
def _hinge_sum_fn(mesh):
    def per_shard(w, shard):
        hinge = jnp.maximum(1.0 - shard["labels"] * shard_margins(w, shard), 0.0)
        return (jnp.sum(hinge * shard["mask"]),)

    @jax.jit
    def f(w, shard_arrays):
        (total,) = fanout(per_shard, mesh, w, shard_arrays)
        return total

    return f


@functools.lru_cache(maxsize=None)
def _alpha_sum_fn(mesh):
    def per_shard(w, alpha_k, shard):
        return (jnp.sum(alpha_k * shard["mask"]),)

    @jax.jit
    def f(w, alpha, shard_arrays):
        (total,) = fanout(per_shard, mesh, w, alpha, shard_arrays)
        return total

    return f


@functools.lru_cache(maxsize=None)
def _error_sum_fn(mesh):
    def per_shard(w, shard):
        correct = (shard_margins(w, shard) * shard["labels"]) > 0.0
        return (jnp.sum(jnp.where(correct, 0.0, 1.0) * shard["mask"]),)

    @jax.jit
    def f(w, shard_arrays):
        (total,) = fanout(per_shard, mesh, w, shard_arrays)
        return total

    return f


def primal_objective(ds: ShardedDataset, w, lam) -> float:
    hinge_sum = _hinge_sum_fn(mesh_of(ds.labels))(w, ds.shard_arrays())
    return float(hinge_sum) / ds.n + 0.5 * lam * float(w @ w)


def dual_objective(ds: ShardedDataset, w, alpha, lam) -> float:
    """alpha: (K, n_shard) sharded dual variables."""
    sum_alpha = _alpha_sum_fn(mesh_of(ds.labels))(w, alpha, ds.shard_arrays())
    return -0.5 * lam * float(w @ w) + float(sum_alpha) / ds.n


def duality_gap(ds: ShardedDataset, w, alpha, lam) -> float:
    return primal_objective(ds, w, lam) - dual_objective(ds, w, alpha, lam)


def classification_error(ds: ShardedDataset, w) -> float:
    errors = _error_sum_fn(mesh_of(ds.labels))(w, ds.shard_arrays())
    return float(errors) / ds.n
