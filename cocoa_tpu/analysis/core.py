"""jaxlint core: findings, file scanning, suppression, baseline, report.

The analyzer is **repo-native**: its rules encode this repo's own proven
failure classes (the PR-2 donation miss, the io_callback ordering
conventions, the f64-only-in-certificate-math policy, the Pallas budget
gates, the jax-0.4.37 mesh-API debt) rather than generic style.  The
machinery here is rule-agnostic:

- :class:`Finding` — one diagnostic, with a line-number-independent
  ``fingerprint`` (rule + path + normalized source line + occurrence
  index) so a baseline survives unrelated edits;
- inline suppression — ``# jaxlint: allow=<rule>[,<rule>] -- reason`` on
  the finding's line or the line directly above it.  A suppression MUST
  carry a reason after ``--``: silence is the failure mode this tool
  exists to remove;
- the committed baseline (``cocoa_tpu/analysis/baseline.json``) — known
  findings with justifications.  CI fails only on findings that are
  neither suppressed nor baselined, so the mesh-API worklist (ROADMAP
  item 4) can ride along as an inventory without blocking merges;
- the JSONL report — one ``analysis_manifest`` header line plus one line
  per finding, validated by ``cocoa_tpu/telemetry/schema.py`` (the same
  checker CI runs on every other JSONL artifact this repo emits).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Iterable, Optional

SEVERITIES = ("error", "warning", "inventory")

# the scan surface: package + benchmark drivers.  tests/ is excluded on
# purpose — the known-bad rule fixtures live there, and f64 parity
# pinning is the tests' JOB (the f64 rule's allowlist made code-level).
DEFAULT_SCAN = ("cocoa_tpu", "benchmarks", "bench.py")

_ALLOW_RE = re.compile(
    r"#\s*jaxlint:\s*allow=([\w,\-]+)\s*(?:--\s*(.*))?")


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str          # error | warning | inventory
    path: str              # repo-relative, forward slashes
    line: int              # 1-based
    col: int
    message: str
    replacement: Optional[str] = None   # mesh-api: the supported API
    fingerprint: str = ""
    suppressed: bool = False            # inline ``jaxlint: allow``
    suppression_reason: Optional[str] = None
    baselined: bool = False
    justification: Optional[str] = None  # from the baseline entry

    def to_json(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "path": self.path, "line": self.line, "col": self.col,
             "message": self.message, "fingerprint": self.fingerprint,
             "suppressed": self.suppressed, "baselined": self.baselined}
        if self.replacement is not None:
            d["replacement"] = self.replacement
        if self.suppression_reason is not None:
            d["suppression_reason"] = self.suppression_reason
        if self.justification is not None:
            d["justification"] = self.justification
        return d

    @property
    def actionable(self) -> bool:
        """Counts against the exit code: not suppressed, not baselined."""
        return not (self.suppressed or self.baselined)

    def location(self) -> str:
        return f"{self.path}:{self.line}"


@dataclasses.dataclass
class SourceFile:
    """One parsed module, shared by every rule (parse once, visit N times)."""
    path: str              # repo-relative
    abspath: str
    text: str
    lines: list            # raw source lines (no trailing newline)
    tree: ast.AST
    allows: dict           # line (1-based) -> (set of rules | {"*"}, reason)


def repo_root() -> str:
    """The directory holding the ``cocoa_tpu`` package (the repo root in
    every supported layout — editable install and in-tree runs alike)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def iter_py_files(root: str, targets: Iterable[str] = DEFAULT_SCAN):
    """Yield repo-relative paths of the .py files to scan."""
    for target in targets:
        top = os.path.join(root, target)
        if os.path.isfile(top):
            if top.endswith(".py"):
                yield os.path.relpath(top, root)
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.relpath(os.path.join(dirpath, fn), root)


def _collect_allows(text: str) -> dict:
    """Map line number -> (allowed rule set, reason) from ``jaxlint:
    allow`` comments.  An allow comment covers its own line; a
    comment-only allow also covers the comment block it opens and the
    first code line after it (so a wrapped multi-line justification
    still lands on the statement it annotates)."""
    allows = {}
    comment_only = set()
    entries = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            if tok.line.strip().startswith("#"):
                comment_only.add(tok.start[0])
            m = _ALLOW_RE.search(tok.string)
            if m:
                rules = frozenset(r.strip() for r in m.group(1).split(","))
                reason = (m.group(2) or "").strip() or None
                entries.append((tok.start[0], rules, reason))
    except tokenize.TokenError:
        pass
    for ln, rules, reason in entries:
        allows[ln] = (rules, reason)
        if ln in comment_only:
            nxt = ln + 1
            while nxt in comment_only:
                allows[nxt] = (rules, reason)
                nxt += 1
            allows[nxt] = (rules, reason)
    return allows


def load_source(root: str, relpath: str) -> Optional[SourceFile]:
    abspath = os.path.join(root, relpath)
    with open(abspath, encoding="utf-8") as f:
        text = f.read()
    try:
        tree = ast.parse(text, filename=relpath)
    except SyntaxError:
        return None  # py_compile / CI catches those; not lint's job
    return SourceFile(
        path=relpath.replace(os.sep, "/"), abspath=abspath, text=text,
        lines=text.splitlines(), tree=tree,
        allows=_collect_allows(text))


def fingerprint_findings(findings: list, sources: dict) -> None:
    """Assign stable fingerprints: sha256(rule | path | normalized source
    line | message | occurrence index) — line-number independent, so the
    baseline survives edits elsewhere in the file.  The message is part
    of the identity because synthetic findings (the numeric
    pallas-budget sweep) share one (path, line) — without it a baselined
    entry could silently absorb a DIFFERENT later violation at the same
    anchor.  The occurrence index disambiguates exact duplicates (and
    makes fingerprints unique, which the schema checker asserts)."""
    seen: dict = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        src = sources.get(f.path)
        line_text = ""
        if src is not None and 1 <= f.line <= len(src.lines):
            line_text = " ".join(src.lines[f.line - 1].split())
        key = (f.rule, f.path, line_text, f.message)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        blob = "|".join((f.rule, f.path, line_text, f.message, str(idx)))
        f.fingerprint = hashlib.sha256(blob.encode()).hexdigest()[:16]


def apply_suppressions(findings: list, sources: dict) -> None:
    for f in findings:
        src = sources.get(f.path)
        if src is None:
            continue
        entry = src.allows.get(f.line)
        if entry is None:
            continue
        rules, reason = entry
        if f.rule in rules or "*" in rules:
            f.suppressed = True
            f.suppression_reason = reason


# --- baseline ---------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str = BASELINE_PATH) -> dict:
    """fingerprint -> entry dict.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out = {}
    for e in data.get("entries", []):
        out[e["fingerprint"]] = e
    return out


def apply_baseline(findings: list, baseline: dict,
                   scanned_paths=None) -> list:
    """Mark baselined findings; returns the STALE baseline entries
    (fingerprints no longer produced — the finding was fixed or the code
    moved enough to need re-baselining).  ``scanned_paths`` scopes
    staleness to what this run actually looked at: on a targeted run
    (``python -m cocoa_tpu.analysis cocoa_tpu/solvers``) entries for
    unscanned files are out of scope, not stale."""
    live = set()
    for f in findings:
        e = baseline.get(f.fingerprint)
        if e is not None:
            f.baselined = True
            f.justification = e.get("justification")
            live.add(f.fingerprint)
    return [e for fp, e in baseline.items()
            if fp not in live
            and (scanned_paths is None or e.get("path") in scanned_paths)]


def write_baseline(findings: list, path: str = BASELINE_PATH,
                   scanned_paths=None) -> int:
    """Write every unsuppressed finding as a baseline entry, preserving
    existing justifications.  New entries get a placeholder justification
    that the committer is expected to replace — an unexplained baseline
    is just silence with extra steps.  On a targeted run
    (``scanned_paths`` given) entries for files OUTSIDE the scan are
    carried over untouched — a path-scoped ``--update-baseline`` must
    never wipe the rest of the repo's justified baseline."""
    old = load_baseline(path)
    entries = []
    if scanned_paths is not None:
        entries += [e for e in old.values()
                    if e.get("path") not in scanned_paths]
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.suppressed:
            continue
        prev = old.get(f.fingerprint, {})
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "message": f.message,
            "justification": prev.get("justification",
                                      "TODO: justify or fix"),
        })
    entries.sort(key=lambda e: (e.get("path", ""), e.get("line", 0),
                                e.get("rule", "")))
    with open(path, "w") as f:
        json.dump({
            "_comment": (
                "jaxlint baseline: known findings CI tolerates, each with "
                "a justification.  Regenerate with `python -m "
                "cocoa_tpu.analysis --update-baseline` (existing "
                "justifications are preserved); fix code instead of "
                "adding entries whenever possible."),
            "entries": entries,
        }, f, indent=1, sort_keys=False)
        f.write("\n")
    return len(entries)


# --- report -----------------------------------------------------------------


def report_manifest(findings: list, files_scanned: int, rules: list) -> dict:
    import jax

    counts: dict = {}
    for f in findings:
        bucket = ("suppressed" if f.suppressed
                  else "baselined" if f.baselined else "new")
        counts[f.rule] = counts.get(f.rule, {"new": 0, "baselined": 0,
                                             "suppressed": 0})
        counts[f.rule][bucket] += 1
    return {
        "analysis_manifest": {
            "tool": "jaxlint",
            "version": 1,
            "jax_version": jax.__version__,
            "files_scanned": files_scanned,
            "rules": list(rules),
            "counts": counts,
        }
    }


def write_report(path: str, findings: list, files_scanned: int,
                 rules: list) -> None:
    """JSONL: header line + one line per finding (telemetry/schema.py
    validates this dialect as ``analysis``)."""
    with open(path, "w") as f:
        f.write(json.dumps(report_manifest(findings, files_scanned, rules))
                + "\n")
        for fd in sorted(findings,
                         key=lambda f: (f.path, f.line, f.col, f.rule)):
            f.write(json.dumps(fd.to_json()) + "\n")
