"""jaxlint: repo-native static analysis + runtime sanitizers.

``python -m cocoa_tpu.analysis`` lints the package against this repo's
proven JAX failure classes (donation misses, silent host syncs, f64
leaks, Pallas budget drift, the jax-0.4.37 mesh-API debt) and exits
nonzero on any finding that is neither inline-suppressed
(``# jaxlint: allow=<rule> -- reason``) nor carried by the committed
baseline with a justification.  See docs/DESIGN.md §10.

Submodules import lazily: ``analysis.sanitize`` is wired into the hot
drivers (solvers/base.py) and must not drag the ops/AST machinery in
with it.
"""

from __future__ import annotations

__all__ = ["run_analysis", "RULES"]


def __getattr__(name):
    # RULES lives in rules.py (single source of truth); resolve lazily so
    # importing the package — the drivers import analysis.sanitize on the
    # hot path — never pays for the AST machinery
    if name == "RULES":
        from cocoa_tpu.analysis.rules import RULES

        return RULES
    raise AttributeError(name)


def run_analysis(root=None, targets=None, baseline_path=None,
                 with_budget_checks=True):
    """Run every rule; returns (findings, sources, stale_baseline_entries).
    Findings come back fingerprinted, with inline suppressions and the
    baseline applied.  On a targeted run (``targets`` an explicit subset)
    baseline staleness is scoped to the scanned files."""
    from cocoa_tpu.analysis import core, rules

    root = root or core.repo_root()
    scoped = targets is not None and list(targets) != list(core.DEFAULT_SCAN)
    targets = tuple(targets) if targets else core.DEFAULT_SCAN
    sources = {}
    for rel in core.iter_py_files(root, targets):
        src = core.load_source(root, rel)
        if src is not None:
            sources[src.path] = src
    findings = rules.run_static_rules(sources)
    if with_budget_checks:
        from cocoa_tpu.analysis import pallas_budget

        findings += pallas_budget.run_budget_checks()
    core.fingerprint_findings(findings, sources)
    core.apply_suppressions(findings, sources)
    baseline = core.load_baseline(baseline_path or core.BASELINE_PATH)
    stale = core.apply_baseline(
        findings, baseline,
        scanned_paths=set(sources) if scoped else None)
    return findings, sources, stale
