"""Dynamic sanitizers: the compile-once and no-silent-transfer invariants.

The static rules (rules.py) catch host syncs and recompiles you can see
in the source; this module catches the ones you can't — a shape that
quietly retraces per super-block, a scalar read that blocks on the
device inside the round loop — by wiring two runtime probes around the
drive loops:

- **compile watch** — every XLA compile is observable.  jax logs
  ``Finished XLA compilation of jit(<name>) ...`` on the
  ``jax._src.dispatch`` logger at DEBUG (independent of the
  ``jax_log_compiles`` flag); :func:`watch_compiles` captures those
  records, and :func:`install_compile_events` bridges them onto the
  telemetry bus as typed ``compile`` events for the production
  ``--metrics`` counters.  The invariant the tests pin: the device loop
  executable compiles exactly ONCE per config — a second identical run
  compiles nothing.
- **transfer guard** — :func:`sanitizer(strict="all")` arms the
  device-loop contract: inside each dispatch→fetch region (which the
  driver marks via :func:`device_loop_guard`) jax's transfer guards
  disallow EVERY host↔device crossing on the driving thread, so any
  un-sanctioned sync raises at its exact line; the drivers mark their
  deliberate fetch points with :func:`intended_fetch`, which re-allows
  the transfer, counts it, and emits a ``host_transfer`` event when
  telemetry is active.  The invariant: zero unintended device→host
  transfers inside the round loop, telemetry-on and -off.  (On CPU,
  whole-array device→host reads are zero-copy and unguarded, but the
  host→device half of an accidental ``float(x[i])`` — the index-constant
  upload — still trips, so the CPU fixtures are a real gate and the TPU
  run of the same fixtures is strictly stricter, never looser.)

Both probes are observational: neither changes what the run computes,
and ``intended_fetch`` costs one context-manager enter per super-block
fetch — nothing rides the per-round path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import re
import threading

_DISPATCH_LOGGER = "jax._src.dispatch"
_COMPILE_RE = re.compile(
    r"Finished XLA compilation of (?:jit\(|pmap\()?([^)]+?)\)? in "
    r"([0-9.eE+-]+) sec")

# process-lifetime count of sanctioned device→host fetches (the
# production mirror of what a sanitizer context observes per run)
_counters_lock = threading.Lock()
intended_fetches_total = 0


@dataclasses.dataclass
class CompileRecord:
    name: str
    seconds: float


class _CompileLogWatch(logging.Handler):
    """Capture per-executable compile records off the dispatch logger."""

    def __init__(self, sink):
        super().__init__(level=logging.DEBUG)
        self._sink = sink

    def emit(self, record):
        try:
            m = _COMPILE_RE.search(record.getMessage())
        except Exception:   # never let logging break the run
            return
        if m:
            self._sink(CompileRecord(name=m.group(1),
                                     seconds=float(m.group(2))))


def _mute_passthrough_handlers() -> list:
    """jax installs a NOTSET StreamHandler on its root logger; once we
    lower the dispatch logger to DEBUG, that handler would echo every
    compile record to stderr.  Raise NOTSET handlers to WARNING (their
    de-facto threshold under default levels — observable behavior is
    unchanged, including ``jax_log_compiles``' WARNING-level lines) and
    return an undo list."""
    undo = []
    for h in logging.getLogger("jax").handlers:
        if h.level == logging.NOTSET:
            h.setLevel(logging.WARNING)
            undo.append(h)
    return undo


@contextlib.contextmanager
def watch_compiles():
    """Yield a list that accumulates one :class:`CompileRecord` per XLA
    compile finishing while the context is open.  Lowers the dispatch
    logger to DEBUG for the duration (console output is unchanged — see
    :func:`_mute_passthrough_handlers`)."""
    records: list = []
    handler = _CompileLogWatch(records.append)
    logger = logging.getLogger(_DISPATCH_LOGGER)
    prev_level = logger.level
    muted = _mute_passthrough_handlers()
    logger.addHandler(handler)
    if logger.getEffectiveLevel() > logging.DEBUG:
        logger.setLevel(logging.DEBUG)
    try:
        yield records
    finally:
        logger.removeHandler(handler)
        if _BUS_BRIDGE is None:
            logger.setLevel(prev_level)
            for h in muted:
                h.setLevel(logging.NOTSET)
        # else: the process-lifetime compile→event bridge (installed
        # while this watch was open, or before it) needs the DEBUG level
        # and the muted passthroughs to keep counting — leave them


_BUS_BRIDGE = None


def install_compile_events(bus) -> None:
    """Bridge compile records onto the telemetry bus as ``compile``
    events (idempotent; installed by ``EventBus.configure`` so any run
    with ``--metrics``/``--events`` gets ``compiles_total`` for free).
    The handler stays attached for the process lifetime — ``emit`` on an
    inactive bus is a no-op, so there is no tax once sinks detach.

    Known tradeoff: the dispatch logger stays at DEBUG from here on, so
    an application that attached its own DEBUG-level root handler will
    start seeing jax dispatch debug lines once telemetry was enabled
    (the default root handler drops them; ``jax_log_compiles`` output is
    unaffected)."""
    global _BUS_BRIDGE
    if _BUS_BRIDGE is not None:
        return

    def sink(rec: CompileRecord):
        bus.emit("compile", name=rec.name, seconds=rec.seconds)

    handler = _CompileLogWatch(sink)
    logger = logging.getLogger(_DISPATCH_LOGGER)
    _mute_passthrough_handlers()
    logger.addHandler(handler)
    if logger.getEffectiveLevel() > logging.DEBUG:
        logger.setLevel(logging.DEBUG)
    _BUS_BRIDGE = handler


@contextlib.contextmanager
def intended_fetch(label: str):
    """Mark a deliberate device→host sync point (the driver's one fetch
    per super-block, the eval fetch on host-stepped paths).  Inside a
    :func:`no_host_transfers` guard this is the ONLY way data may cross
    device→host; each use is counted and — when telemetry is active —
    emitted as a ``host_transfer`` event so production runs expose
    ``host_transfers_total``."""
    import jax

    from cocoa_tpu.telemetry import events as _tele

    global intended_fetches_total
    # allow every guard axis: the fetch itself is d2h, but decoding it
    # (scalar indexing) can upload index constants — all sanctioned here
    with jax.transfer_guard("allow"):
        yield
    with _counters_lock:
        intended_fetches_total += 1
    bus = _tele.get_bus()
    if bus.active():
        bus.emit("host_transfer", label=label)


@contextlib.contextmanager
def allow_transfers():
    """Plain un-counted allow — for runtime machinery of sanctioned
    paths (the ordered io_callback's zero-byte effect-token handshake at
    dispatch), which is neither a host fetch nor a leak."""
    import jax

    with jax.transfer_guard("allow"):
        yield


@contextlib.contextmanager
def no_host_transfers():
    """Disallow device→host transfers except through
    :func:`intended_fetch` — an unintended sync raises XlaRuntimeError
    at the exact offending line (thread-local, so the io_callback
    telemetry tap's rows, which arrive on the runtime's callback thread,
    stay unaffected — that path is sanctioned by design)."""
    import jax

    with jax.transfer_guard_device_to_host("disallow"):
        yield


_tls = threading.local()


def device_loop_guard():
    """The guard the device-resident driver wraps its dispatch→fetch
    region in (solvers/base.py ``drive_on_device``).  Inert (a
    nullcontext) unless a :func:`sanitizer` with ``strict="all"`` is
    active on this thread: solver SETUP legitimately uploads (state
    init, shard placement, index staging), so the no-transfer contract
    starts where the loop does — after the last staged argument, ending
    at the sanctioned fetch."""
    if getattr(_tls, "arm_device_loop", False):
        return no_transfers()
    return contextlib.nullcontext()


@contextlib.contextmanager
def _arm_device_loop():
    prev = getattr(_tls, "arm_device_loop", False)
    _tls.arm_device_loop = True
    try:
        yield
    finally:
        _tls.arm_device_loop = prev


@contextlib.contextmanager
def no_transfers():
    """Disallow transfers on EVERY guard axis except through
    :func:`intended_fetch`.  This is the device-loop contract: once the
    dispatch is in flight, nothing crosses the host↔device boundary on
    the driving thread until the sanctioned fetch — no index-constant
    uploads from stray scalar reads, no implicit device math on host
    values.  (It is also what gives the sanitizer teeth on CPU, where
    array device→host reads are zero-copy and unguarded but the
    host→device half of an accidental ``float(x[i])`` still trips.)
    Host-side staging that legitimately uploads (the index-table
    prefetch) runs on its own daemon thread, which the thread-local
    guard deliberately does not cover."""
    import jax

    with jax.transfer_guard("disallow"):
        yield


@dataclasses.dataclass
class SanitizerStats:
    compiles: list                  # CompileRecord per XLA compile
    fetches_before: int = 0

    def compile_count(self, name_substr: str = "") -> int:
        return sum(1 for c in self.compiles if name_substr in c.name)

    @property
    def intended_fetches(self) -> int:
        return intended_fetches_total - self.fetches_before


@contextlib.contextmanager
def sanitizer(strict="all"):
    """The combined harness the sanitizer fixtures run drive loops
    under: compile watch + a transfer guard.  ``strict="all"`` arms the
    device-loop contract — inside each dispatch→fetch region (marked by
    the driver via :func:`device_loop_guard`) NOTHING crosses
    host↔device outside :func:`intended_fetch`; solver setup/staging
    outside the loop is unconstrained.  ``"d2h"`` disallows device→host
    reads across the whole context instead (host-stepped paths, which
    legitimately upload index tables from the driving thread each
    chunk).  ``False`` = compile watch only.  Yields
    :class:`SanitizerStats`; an unintended transfer raises from the
    guarded code itself, so "zero unintended transfers" is simply "the
    run completed"."""
    with contextlib.ExitStack() as stack:
        records = stack.enter_context(watch_compiles())
        stats = SanitizerStats(compiles=records,
                               fetches_before=intended_fetches_total)
        if strict in (True, "all"):
            stack.enter_context(_arm_device_loop())
        elif strict == "d2h":
            stack.enter_context(no_host_transfers())
        yield stats
