"""jaxlint static rules — this repo's proven JAX failure classes, as AST
checks.

Rules (ids are what ``jaxlint: allow=<rule>`` and the baseline key on):

- ``donation`` — donation audit.  Every ``donate_argnums`` must name a
  positional argument the jitted fn actually consumes; step-shaped jit
  sites in ``solvers/`` must donate their loop-carried state; and the
  PR-2 bug shape — ``x.at[...].op(...) ± x`` on a loop-carried buffer,
  which forces XLA to keep both the old and new buffer live and silently
  defeats donation with a full copy — is an error anywhere in traced
  code (the fix shape: scatter the delta into ``zeros_like`` instead).
- ``host-sync`` — device→host syncs inside traced code: ``float()`` /
  ``int()`` / ``bool()`` on traced values, ``.item()`` / ``.tolist()`` /
  ``jax.device_get``, ``np.asarray``/``np.array`` of traced values, bare
  ``if``/``while`` on a traced value, and host ``print`` of traced
  values.  The sanctioned escape hatch — the ordered ``io_callback``
  telemetry tap (telemetry/events.py) — is allowlisted by construction:
  callbacks passed to ``io_callback``/``pure_callback``/``jax.debug.*``
  run on the host and are never treated as traced.
- ``f64`` — float64 leaks.  Repo policy (DESIGN.md §6): compute dtype is
  f32; float64 belongs only in parity tests and ``evals`` certificate
  math.  Anything else is either a bug or needs a justified
  ``jaxlint: allow=f64`` (host-side exact parsing in the data loaders).
- ``mesh-api`` — inventory of every mesh/shard_map call site using an
  API surface that does not exist on the pinned jax 0.4.37
  (``jax.shard_map``, ``lax.pcast``/``pvary``, ``jax.sharding.AxisType``,
  ``jax.make_mesh(axis_types=...)``).  These are exactly the sites behind
  the tier-1 suite's standing 40+14 mesh failures; the findings ARE the
  ROADMAP item 4 worklist, each with its supported-API replacement.
- ``pallas-budget`` — the AST half of the Pallas memory accounting
  (``pallas_budget.py`` holds the numeric half): every ``pl.pallas_call``
  must live in a module that declares a VMEM budget constant and a
  ``*_fits`` gate, and every gate must actually be consulted outside its
  own module (a gate nobody calls protects nothing).
- ``span-hygiene`` — the tracing contract (telemetry/tracing.py): a span
  enter/exit (``span(...)`` context manager or ``@traced`` decorator)
  must never appear inside jit/lax bodies — there it times the TRACE,
  not the execution, and fires once per compile — and span attributes
  must never read traced values (emitting one materializes the array on
  the host: a silent device sync).  Rides the host-sync rule's
  traced-context machinery.
- ``fleet-hygiene`` — the fleet execution contract (solvers/fleet.py):
  a Python-level loop over tenants inside a jit/lax body is an error
  (it unrolls T kernel copies — one compiled round per tenant is
  exactly what the fleet path exists to avoid; the tenant axis rides
  vmap/lax.map), and a per-tenant device fetch inside a host-side
  tenant loop is an error (T round-trips through the device tunnel is
  the serial-path cost the fleet amortizes; fetch the stacked result
  once).  Rides the host-sync rule's traced-context machinery.
- ``overlap-hygiene`` — the overlapped-exchange contract
  (parallel/distributed.py, docs/DESIGN.md §15): launching an async
  exchange (``async_host_allgather_bytes`` / ``async_kv_get``) inside
  traced code is an error (a traced value escaping into the collector
  thread races the dispatch that produces it — the runtime twin is
  ``_require_host_bytes``), and an exchange handle that is never
  ``.join()``ed — and never escapes the function (returned, stored, or
  passed on, e.g. into a ``StaleJoinWindow``) — is an error: its
  payload is unsynchronized with every dispatch it crosses, and its
  bounded-KV budget leaks onto a daemon thread nobody will ever
  account.  Rides the host-sync rule's traced-context machinery.
- ``serve-hygiene`` — the serving hot-path contract (cocoa_tpu/serving/,
  docs/DESIGN.md §17): a ``jax.jit`` built inside a hot-path def is an
  error (compile-per-request — executables are built once at startup),
  an array allocation whose shape derives from ``len(...)`` in the hot
  path is an error (request-dependent shapes compile one executable per
  batch size; pad UP to a static bucket), and inside the compiled
  scoring functions a host clock read or ``.block_until_ready()`` is an
  error (it times/syncs the trace, not the request).  Quantization
  belongs at swap time on the host (serving/quantize.py, DESIGN.md
  §20): a narrowing ``.astype(...)`` (bf16/f16/int8/…) or a
  max-of-abs scale compute inside a traced scoring def is an error —
  the compiled path serves a published form, it never re-derives one.
  Rides the host-sync rule's traced-context machinery.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from cocoa_tpu.analysis.core import Finding, SourceFile

# --- shared AST infrastructure ---------------------------------------------

# callees whose function-valued arguments are traced (control flow and this
# repo's own fan-out combinators)
_TRACED_ARG_CALLEES = {
    "while_loop", "scan", "fori_loop", "cond", "switch", "associative_scan",
    "fanout", "chunk_fanout", "vmap", "pmap", "shard_map", "grad",
    "value_and_grad", "checkpoint", "remat", "custom_vjp", "custom_jvp",
}

# callees whose function-valued arguments run on the HOST (the sanctioned
# device→host escape hatches; the io_callback telemetry tap rides these)
_CALLBACK_CALLEES = {
    "io_callback", "pure_callback", "debug_callback", "callback",
}

_STEP_NAME_RE = re.compile(r"^(round_step|chunk_step|step|run)$")

_NP_MODULES = {"np", "numpy", "onp"}


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'jax.lax.while_loop' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _callee_tail(call: ast.Call) -> str:
    chain = _attr_chain(call.func)
    return chain.rsplit(".", 1)[-1] if chain else ""


def _is_jax_jit(node: ast.AST) -> bool:
    return _attr_chain(node) in ("jax.jit", "jit")


def _const_int_tuple(node: ast.AST) -> Optional[tuple]:
    """Evaluate a donate_argnums value when it is a literal; None when the
    expression is dynamic (e.g. ``tuple(range(n_state))``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


class JitSite:
    """One jax.jit application with a resolvable target function."""

    def __init__(self, node: ast.AST, target: Optional[ast.AST],
                 donate: Optional[tuple], has_donate_kw: bool,
                 assigned_name: Optional[str], static_names=frozenset()):
        self.node = node                # the Call / decorated FunctionDef
        self.target = target            # FunctionDef | Lambda | None
        self.donate = donate            # tuple of ints | None (dynamic)
        self.has_donate_kw = has_donate_kw
        self.assigned_name = assigned_name
        self.static_names = static_names  # static_argnames/argnums params


class ModuleIndex(ast.NodeVisitor):
    """One pass over a module: def tables per scope, parent links, jit
    sites, traced-context seeds, callback targets."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.parent_def: dict = {}      # def node -> enclosing def | None
        self.defs: list = []            # every FunctionDef/Lambda
        self.scope_defs: dict = {}      # scope node (def|Module) -> {name: def}
        self.jit_sites: list = []
        self.traced_seeds: set = set()  # def ids seeded traced (lax/combinators)
        self.callback_targets: set = set()  # def ids that run on the host
        self.static_params: dict = {}   # def id -> static (untraced) params
        self._scope_stack: list = []
        self._assign_target: Optional[str] = None

    # -- scope bookkeeping

    def index(self):
        self.scope_defs[self.src.tree] = {}
        self._scope_stack = [self.src.tree]
        self.visit(self.src.tree)
        return self

    def _current_scope(self):
        return self._scope_stack[-1]

    def _resolve(self, name: str) -> Optional[ast.AST]:
        for scope in reversed(self._scope_stack):
            d = self.scope_defs.get(scope, {})
            if name in d:
                return d[name]
        return None

    def _resolve_fn_arg(self, node: ast.AST) -> Optional[ast.AST]:
        if isinstance(node, ast.Name):
            return self._resolve(node.id)
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Call):
            # functools.partial(f, ...) — resolve through to f
            if _callee_tail(node) == "partial" and node.args:
                return self._resolve_fn_arg(node.args[0])
        return None

    # -- visitors

    def visit_FunctionDef(self, node):
        self._handle_def(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._handle_def(node, None)

    def _handle_def(self, node, name):
        parent = self._scope_stack[-1]
        self.parent_def[node] = parent if parent is not self.src.tree else None
        self.defs.append(node)
        if name is not None:
            self.scope_defs.setdefault(parent, {})[name] = node
        self.scope_defs.setdefault(node, {})
        # jit decorators
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                site = self._jit_from_decorator(dec, node)
                if site is not None:
                    self.jit_sites.append(site)
        self._scope_stack.append(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._scope_stack.pop()

    def _jit_from_decorator(self, dec, fn) -> Optional[JitSite]:
        if _is_jax_jit(dec):
            return self._make_site(fn, fn, None, fn.name)
        if isinstance(dec, ast.Call):
            # @jax.jit(...) or @functools.partial(jax.jit, ...)
            if not (_is_jax_jit(dec.func)
                    or (_callee_tail(dec) == "partial" and dec.args
                        and _is_jax_jit(dec.args[0]))):
                return None
            return self._make_site(fn, fn, dec, fn.name)
        return None

    def _make_site(self, node, target, call: Optional[ast.Call],
                   assigned_name) -> JitSite:
        donate, has_kw = (self._donate_of(call) if call is not None
                          else ((), False))
        static = (self._static_of(call, target) if call is not None
                  else frozenset())
        site = JitSite(node, target, donate=donate, has_donate_kw=has_kw,
                       assigned_name=assigned_name, static_names=static)
        if target is not None and static:
            prev = self.static_params.setdefault(id(target), set())
            prev |= static
        return site

    @staticmethod
    def _donate_of(call: ast.Call):
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                if kw.arg == "donate_argnames":
                    return None, True  # names not modeled; presence counts
                return _const_int_tuple(kw.value), True
        return (), False

    @staticmethod
    def _static_of(call: ast.Call, target) -> frozenset:
        """Parameter names the jit treats as compile-time constants —
        host-sync and donation checks must not treat them as traced."""
        names: set = set()
        params = (_params_of(target)
                  if isinstance(target, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) else [])
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                v = kw.value
                elts = (v.elts if isinstance(v, (ast.Tuple, ast.List))
                        else [v])
                for e in elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, str):
                        names.add(e.value)
            elif kw.arg == "static_argnums":
                idxs = _const_int_tuple(kw.value) or ()
                for i in idxs:
                    if 0 <= i < len(params):
                        names.add(params[i])
        return frozenset(names)

    def visit_Assign(self, node):
        prev = self._assign_target
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            self._assign_target = node.targets[0].id
        self.generic_visit(node)
        self._assign_target = prev

    def visit_Call(self, node):
        tail = _callee_tail(node)
        if _is_jax_jit(node.func) and node.args:
            target = self._resolve_fn_arg(node.args[0])
            self.jit_sites.append(self._make_site(
                node, target, node, self._assign_target))
        elif tail in _CALLBACK_CALLEES and node.args:
            t = self._resolve_fn_arg(node.args[0])
            if t is not None:
                self.callback_targets.add(id(t))
        elif tail in _TRACED_ARG_CALLEES:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                t = self._resolve_fn_arg(a)
                if t is not None:
                    self.traced_seeds.add(id(t))
        self.generic_visit(node)

    # -- traced-context resolution

    def traced_defs(self) -> set:
        """ids of defs whose bodies are traced: jit targets and
        control-flow/combinator callees, plus everything lexically nested
        in a traced def — minus host-callback targets."""
        traced = set(self.traced_seeds)
        for site in self.jit_sites:
            if site.target is not None:
                traced.add(id(site.target))
        traced -= self.callback_targets
        changed = True
        while changed:
            changed = False
            for d in self.defs:
                if id(d) in traced or id(d) in self.callback_targets:
                    continue
                p = self.parent_def.get(d)
                if p is not None and id(p) in traced:
                    traced.add(id(d))
                    changed = True
        return traced

    def traced_params(self, node, traced: set) -> set:
        """Parameter names of ``node`` and every TRACED enclosing def —
        the first-order 'this value is traced here' name set.  The walk
        stops at the first non-traced ancestor: a host-side builder's
        params (mesh, params, flags) are trace-time constants, and
        ``float(params.lam)`` in a kernel it builds is legal."""
        names: set = set()
        d = node
        while d is not None:
            a = d.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                names.add(arg.arg)
            names -= self.static_params.get(id(d), set())
            d = self.parent_def.get(d)
            if d is not None and id(d) not in traced:
                break
        return names


def _params_of(fn) -> list:
    a = fn.args
    return [arg.arg for arg in a.posonlyargs + a.args]


_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "sharding"}


def _mentions(expr: ast.AST, names: set) -> bool:
    """Whether ``expr`` reads a traced VALUE from ``names`` — mentions
    under static metadata attributes (``x.shape``, ``x.dtype``, ...) are
    trace-time Python and don't count."""
    def walk(node):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Name) and node.id in names:
            return True
        return any(walk(c) for c in ast.iter_child_nodes(node))

    return walk(expr)


def _nearest_def(node, parents) -> Optional[ast.AST]:
    p = parents.get(node)
    while p is not None and not isinstance(
            p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        p = parents.get(p)
    return p


def _build_parents(tree) -> dict:
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


# --- rule: donation ---------------------------------------------------------


def _at_update_root(expr: ast.AST) -> Optional[str]:
    """The name X when ``expr`` is an ``X.at[...].meth(...)`` chain (with
    any number of trailing method calls), else None."""
    node = expr
    while True:
        if isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            if node.attr == "at" and isinstance(node.value, ast.Name):
                return node.value.id
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            return None


def check_donation(src: SourceFile, index: ModuleIndex) -> list:
    findings = []
    in_solvers = "/solvers/" in f"/{src.path}"

    for site in index.jit_sites:
        fn = site.target
        loc = fn if fn is not None else site.node
        if fn is None:
            continue
        params = _params_of(fn) if not isinstance(fn, ast.Lambda) else \
            [a.arg for a in fn.args.args]
        name = site.assigned_name or getattr(fn, "name", None) or "<lambda>"
        if (in_solvers and not site.has_donate_kw
                and _STEP_NAME_RE.match(name or "")):
            findings.append(Finding(
                rule="donation", severity="error", path=src.path,
                line=loc.lineno, col=loc.col_offset,
                message=(
                    f"jit step `{name}` in solvers/ donates nothing — "
                    f"loop-carried solver state in the drive* ladder must "
                    f"ride donate_argnums (every round otherwise pays a "
                    f"full-state copy in HBM)")))
        if site.donate:
            for idx in site.donate:
                if idx >= len(params) or idx < 0:
                    findings.append(Finding(
                        rule="donation", severity="error", path=src.path,
                        line=loc.lineno, col=loc.col_offset,
                        message=(
                            f"donate_argnums index {idx} is out of range "
                            f"for `{name}` ({len(params)} positional "
                            f"args) — donation silently misses")))
                    continue
                pname = params[idx]
                body = fn.body if isinstance(fn.body, list) else [fn.body]
                used = sum(
                    1 for stmt in body for n in ast.walk(stmt)
                    if isinstance(n, ast.Name) and n.id == pname)
                if used == 0:
                    findings.append(Finding(
                        rule="donation", severity="error", path=src.path,
                        line=loc.lineno, col=loc.col_offset,
                        message=(
                            f"`{name}` donates arg {idx} (`{pname}`) but "
                            f"never reads it — the donated buffer cannot "
                            f"be the one the output aliases, so the "
                            f"donation is a no-op")))

    # the PR-2 shape, anywhere traced: X.at[...].op(...) ± X forces XLA to
    # keep old and new X live at once — the output cannot alias the input
    # buffer, so donation silently degrades to a full copy
    traced = index.traced_defs()
    parents = _build_parents(src.tree)
    for d in index.defs:
        if id(d) not in traced:
            continue
        pnames = index.traced_params(d, traced)
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            for node in ast.walk(stmt):
                nd = _nearest_def(node, parents)
                if nd is not d:
                    continue
                if not isinstance(node, ast.BinOp) or not isinstance(
                        node.op, (ast.Add, ast.Sub)):
                    continue
                for a, b in ((node.left, node.right),
                             (node.right, node.left)):
                    x = _at_update_root(a)
                    if x is not None and x in pnames and _mentions(
                            b, {x}):
                        findings.append(Finding(
                            rule="donation", severity="error",
                            path=src.path, line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{x}.at[...] ± {x}` keeps both the old "
                                f"and new `{x}` live — donation of the "
                                f"buffer silently becomes a full copy "
                                f"(the PR-2 α bug shape); scatter the "
                                f"delta into `jnp.zeros_like({x})` "
                                f"instead")))
                        break
    return findings


# --- rule: host-sync --------------------------------------------------------

_SYNC_METHODS = {"item", "tolist"}


def check_host_sync(src: SourceFile, index: ModuleIndex) -> list:
    findings = []
    traced = index.traced_defs()
    parents = _build_parents(src.tree)

    def flag(node, msg, severity="error"):
        findings.append(Finding(
            rule="host-sync", severity=severity, path=src.path,
            line=node.lineno, col=node.col_offset, message=msg))

    for d in index.defs:
        if id(d) not in traced:
            continue
        pnames = index.traced_params(d, traced)
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if _nearest_def(node, parents) is not d:
                    continue  # nested defs are visited as themselves
                if isinstance(node, ast.Call):
                    chain = _attr_chain(node.func) or ""
                    tail = chain.rsplit(".", 1)[-1]
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _SYNC_METHODS:
                        flag(node,
                             f"`.{node.func.attr}()` inside traced code is "
                             f"a device→host sync per call — fetch once on "
                             f"the host after the dispatch, or route "
                             f"through the io_callback tap")
                    elif chain in ("jax.device_get", "device_get"):
                        flag(node,
                             "`jax.device_get` inside traced code syncs "
                             "the device every call — hoist the fetch to "
                             "the driver")
                    elif tail in ("asarray", "array") and \
                            chain.split(".")[0] in _NP_MODULES and \
                            any(_mentions(a, pnames) for a in node.args):
                        flag(node,
                             f"`{chain}` of a traced value materializes it "
                             f"on the host (silent sync + recompile "
                             f"hazard) — use jnp, or fetch after the "
                             f"dispatch")
                    elif isinstance(node.func, ast.Name) and \
                            node.func.id in ("float", "int", "bool") and \
                            node.args and _mentions(node.args[0], pnames):
                        flag(node,
                             f"`{node.func.id}()` of a traced value blocks "
                             f"on the device (one ~100ms round-trip per "
                             f"call through a tunneled TPU) — keep it as "
                             f"an array, or fetch once after the dispatch")
                    elif isinstance(node.func, ast.Name) and \
                            node.func.id == "print" and \
                            any(_mentions(a, pnames) for a in node.args):
                        flag(node,
                             "`print` of a traced value syncs and runs "
                             "only at trace time — use jax.debug.print "
                             "or the telemetry event stream",
                             severity="warning")
                elif isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    if isinstance(test, ast.UnaryOp) and isinstance(
                            test.op, ast.Not):
                        test = test.operand
                    if isinstance(test, ast.Name) and test.id in pnames:
                        flag(node,
                             f"`if {test.id}:` on a traced value is an "
                             f"implicit bool() sync (TracerBoolConversion "
                             f"at best, a silent host round-trip at "
                             f"worst) — use lax.cond/jnp.where")
    return findings


# --- rule: f64 --------------------------------------------------------------

# float64 is policy-legal only here (DESIGN.md §6): exact certificate
# arithmetic and the parity tests.  tests/ is outside the scan surface.
_F64_ALLOWED_PREFIXES = ("cocoa_tpu/evals/",)


def check_f64(src: SourceFile, index: ModuleIndex) -> list:
    if src.path.startswith(_F64_ALLOWED_PREFIXES):
        return []
    findings = []

    def flag(node, what):
        findings.append(Finding(
            rule="f64", severity="error", path=src.path, line=node.lineno,
            col=node.col_offset,
            message=(
                f"{what} — repo numerics policy keeps float64 in parity "
                f"tests and evals/ certificate math only (DESIGN.md §6); "
                f"fix the dtype or add a justified `jaxlint: allow=f64`")))

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Attribute) and node.attr == "float64":
            root = _attr_chain(node)
            if root:
                flag(node, f"`{root}`")
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func) or ""
            args = list(node.args) + [kw.value for kw in node.keywords]
            if chain.endswith("config.update") and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value == "jax_enable_x64":
                flag(node, "`jax_enable_x64` flipped at runtime")
            elif any(isinstance(a, ast.Constant) and a.value == "float64"
                     for a in args):
                flag(node, '"float64" dtype argument')
    return findings


# --- rule: mesh-api ---------------------------------------------------------

# API surface absent on the pinned jax 0.4.37 -> supported replacement.
# These sites are the tier-1 suite's standing 40 fails + 14 errors and
# the ROADMAP item 4 refactor worklist.
_MESH_ATTRS = {
    "jax.shard_map": (
        "jax.experimental.shard_map.shard_map on jax<0.5 — route through "
        "a versioned adapter (parallel/compat) so both jaxes pass"),
    "lax.pcast": (
        "no pre-0.5 equivalent (VMA types arrived with the new "
        "shard_map) — the adapter must fall back to lax.pvary or a "
        "no-op cast"),
    "jax.lax.pcast": (
        "no pre-0.5 equivalent — see lax.pcast"),
}

_MESH_FALLBACK_ATTRS = {
    # present in the tree as the 'older jax' branch of a hasattr guard,
    # but itself absent on 0.4.37 — the guard still lands on a missing API
    "lax.pvary": (
        "absent on jax 0.4.37 too — the <0.5 branch must drop the VMA "
        "cast entirely (plain identity) under the adapter"),
    "jax.lax.pvary": ("absent on jax 0.4.37 — see lax.pvary"),
}


def check_mesh_api(src: SourceFile, index: ModuleIndex) -> list:
    findings = []
    seen_lines = set()

    def flag(node, api, replacement):
        key = (node.lineno, api)
        if key in seen_lines:
            return
        seen_lines.add(key)
        findings.append(Finding(
            rule="mesh-api", severity="inventory", path=src.path,
            line=node.lineno, col=node.col_offset,
            message=(f"`{api}` does not exist on the pinned jax 0.4.37 "
                     f"(the mesh-suite failure class)"),
            replacement=replacement))

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            if chain in _MESH_ATTRS:
                flag(node, chain, _MESH_ATTRS[chain])
            elif chain in _MESH_FALLBACK_ATTRS:
                flag(node, chain, _MESH_FALLBACK_ATTRS[chain])
            elif chain and chain.startswith("AxisType."):
                flag(node, f"jax.sharding.{chain.split('.')[0]}",
                     "unavailable before jax 0.5 — gate fp meshes (as "
                     "mesh.py does) or build the Mesh from a device "
                     "ndarray without axis_types")
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func) or ""
            if chain in ("jax.make_mesh",) and any(
                    kw.arg == "axis_types" for kw in node.keywords):
                flag(node, "jax.make_mesh(axis_types=...)",
                     "axis_types lands in jax 0.5 — construct "
                     "jax.sharding.Mesh(np.array(devices).reshape(...), "
                     "axis_names) for the <0.5 branch")
    return findings


# --- rule: pallas-budget (AST half) ----------------------------------------


def check_pallas_budget_ast(src: SourceFile, index: ModuleIndex,
                            all_sources: dict) -> list:
    """Every ``pl.pallas_call`` module must declare a VMEM budget constant
    and a ``*_fits`` gate; every gate must be consulted outside its own
    module.  The numeric half (estimates vs budgets vs physical caps)
    lives in pallas_budget.py."""
    calls = [n for n in ast.walk(src.tree)
             if isinstance(n, ast.Call)
             and (_attr_chain(n.func) or "").endswith("pallas_call")]
    if not calls:
        return []
    findings = []
    budget_names = set()
    fits_names = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and "BUDGET" in t.id:
                    budget_names.add(t.id)
        elif isinstance(node, ast.FunctionDef) and (
                # both gate spellings this repo uses: boolean *_fits
                # gates, and pick_* sizers whose 0 return means "does
                # not fit" (pallas_sdca's unroll/interleave pickers)
                node.name.endswith("_fits") or node.name.startswith(
                    "pick_")):
            fits_names.add(node.name)
    if not budget_names:
        findings.append(Finding(
            rule="pallas-budget", severity="error", path=src.path,
            line=calls[0].lineno, col=calls[0].col_offset,
            message=("module calls pl.pallas_call but declares no "
                     "*_BUDGET constant — SMEM/VMEM overflows become "
                     "runtime surprises instead of lint errors")))
    if not fits_names:
        findings.append(Finding(
            rule="pallas-budget", severity="error", path=src.path,
            line=calls[0].lineno, col=calls[0].col_offset,
            message=("module calls pl.pallas_call but exposes no *_fits "
                     "gate — dispatch cannot account the kernel's "
                     "memory before committing to it")))
    # gates must be consulted by the dispatch layer, not just declared
    for gate in sorted(fits_names):
        consulted = False
        for other_path, other in all_sources.items():
            if other_path == src.path:
                continue
            for n in ast.walk(other.tree):
                if isinstance(n, ast.Name) and n.id == gate:
                    consulted = True
                    break
                if isinstance(n, ast.Attribute) and n.attr == gate:
                    consulted = True
                    break
            if consulted:
                break
        if not consulted:
            gate_def = next(
                n for n in ast.walk(src.tree)
                if isinstance(n, ast.FunctionDef) and n.name == gate)
            findings.append(Finding(
                rule="pallas-budget", severity="warning", path=src.path,
                line=gate_def.lineno, col=gate_def.col_offset,
                message=(f"fits gate `{gate}` is never consulted outside "
                         f"{os.path.basename(src.path)} — a gate the "
                         f"dispatch does not call protects nothing")))
    return findings


# --- rule: span-hygiene -----------------------------------------------------

# the tracing surface (telemetry/tracing.py): the context-manager form
# and the decorator form, module-level or on a Tracer instance
_SPAN_CALLEES = {"span", "traced"}

# receiver names that identify the tracing module/object — required for
# the attribute form so ``re.Match.span()`` and other unrelated ``span``
# methods in traced host code are never flagged
_TRACING_RECEIVERS = ("tracing", "tracer")


def _is_span_call(node: ast.Call) -> Optional[str]:
    """'span'/'traced' when ``node`` is a TRACING call, else None.
    Matches ``tracing.span(...)`` / ``_tracing.span(...)`` /
    ``get_tracer().span(...)`` (receiver names the tracing surface), a
    bare imported ``span("phase", ...)``/``traced("phase")`` (string
    phase argument — what distinguishes it from e.g. ``m.span()``)."""
    tail = _callee_tail(node)
    if tail not in _SPAN_CALLEES:
        return None
    phase_is_str = bool(node.args) and isinstance(
        node.args[0], ast.Constant) and isinstance(node.args[0].value, str)
    if isinstance(node.func, ast.Name):
        return tail if phase_is_str else None
    if isinstance(node.func, ast.Attribute):
        recv = node.func.value
        chain = (_attr_chain(recv) or "").lower()
        if any(r in chain for r in _TRACING_RECEIVERS):
            return tail
        # get_tracer().span(...) — receiver is a call to get_tracer
        if isinstance(recv, ast.Call) and \
                _callee_tail(recv) == "get_tracer":
            return tail
        return tail if phase_is_str else None
    return None


def check_span_hygiene(src: SourceFile, index: ModuleIndex) -> list:
    """Span enter/exit must stay on the host (telemetry/tracing.py
    contract): inside jit/lax bodies a span is a trace-time no-op at
    best (it would time the TRACE, not the execution, and emit once per
    compile instead of once per run) and a host sync at worst (a traced
    value in the span attrs materializes on the host at emit).  Reuses
    the host-sync machinery's traced-context resolution: jit targets,
    control-flow/combinator callees, everything lexically nested —
    minus host-callback targets (an io_callback target may span freely;
    it runs on the host by construction)."""
    findings = []
    traced = index.traced_defs()
    parents = _build_parents(src.tree)

    def flag(node, msg):
        findings.append(Finding(
            rule="span-hygiene", severity="error", path=src.path,
            line=node.lineno, col=node.col_offset, message=msg))

    for d in index.defs:
        if id(d) not in traced:
            continue
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if _nearest_def(node, parents) is not d:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                form = _is_span_call(node)
                if form is None:
                    continue
                flag(node,
                     f"tracing `{form}(...)` inside traced code — a span "
                     f"enter/exit in a jit/lax body times the trace, not "
                     f"the execution, and fires once per COMPILE; hoist "
                     f"it to the host boundary (the dispatch/fetch site, "
                     f"solvers/base.py pattern)")
                continue
    # the decorator form on a function that is itself traced: the span
    # would wrap the traced body — same failure, different spelling
    for d in index.defs:
        if id(d) not in traced or not isinstance(
                d, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in d.decorator_list:
            form = (_is_span_call(dec) if isinstance(dec, ast.Call)
                    else None)
            if form == "traced":
                findings.append(Finding(
                    rule="span-hygiene", severity="error", path=src.path,
                    line=dec.lineno, col=dec.col_offset,
                    message=(f"@traced decorator on `{d.name}`, which is "
                             f"jitted/traced — the span would wrap the "
                             f"trace, not the execution; decorate the "
                             f"host-side caller instead")))
    # span attrs that read traced values from an ENCLOSING traced scope:
    # a host-side closure built inside a kernel builder may legally span,
    # but passing a traced array as an attribute materializes it on the
    # host at emit time (a silent device sync on the hot path)
    for d in index.defs:
        if id(d) in traced:
            continue  # already flagged wholesale above
        p = index.parent_def.get(d)
        if p is None or id(p) not in traced:
            continue
        pnames = index.traced_params(p, traced)
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call) or \
                        _is_span_call(node) is None:
                    continue
                args = list(node.args) + [kw.value for kw in node.keywords]
                if any(_mentions(a, pnames) for a in args):
                    flag(node,
                         "span attribute reads a traced value — emitting "
                         "it materializes the array on the host (silent "
                         "device sync); tag scalars the host already "
                         "holds, or fetch after the dispatch")
    return findings


# --- rule: overlap-hygiene ---------------------------------------------------

# the async-exchange surface (parallel/distributed.py)
_EXCHANGE_CALLEES = {"async_host_allgather_bytes", "async_kv_get"}


def check_overlap_hygiene(src: SourceFile, index: ModuleIndex) -> list:
    """The overlapped-exchange contract (see the module docstring):

    1. launching an async exchange inside traced code is an error —
       traced values must not escape into the collector thread (the
       runtime twin is ``distributed._require_host_bytes``, which only
       accepts host bytes; this catches the shape statically, before a
       run ever reaches it);
    2. a handle bound to a local name that is never ``.join()``ed and
       never escapes (returned/yielded, passed to a call — e.g. a
       ``StaleJoinWindow.admit`` — stored into a container/attribute/
       subscript, or re-exported) is an error: the exchange's payload
       is then read by nobody and synchronized with nothing, so any
       super-block dispatch it crosses runs against an un-joined
       exchange."""
    findings = []
    traced = index.traced_defs()
    parents = _build_parents(src.tree)

    def flag(node, msg):
        findings.append(Finding(
            rule="overlap-hygiene", severity="error", path=src.path,
            line=node.lineno, col=node.col_offset, message=msg))

    # (1) async launch inside traced code
    for d in index.defs:
        if id(d) not in traced:
            continue
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if _nearest_def(node, parents) is not d:
                    continue
                if isinstance(node, ast.Call) and \
                        _callee_tail(node) in _EXCHANGE_CALLEES:
                    flag(node,
                         f"`{_callee_tail(node)}` inside traced code — "
                         f"traced values must not escape into the "
                         f"exchange thread (the collector would race the "
                         f"dispatch producing them); launch the exchange "
                         f"at the host boundary and pass host bytes "
                         f"(np.asarray(x).tobytes())")

    # (2) handles that are never joined and never escape, per scope
    scopes = [src.tree] + list(index.defs)
    for scope in scopes:
        if scope is not src.tree and id(scope) in traced:
            continue  # already flagged wholesale by (1)
        body = scope.body if isinstance(getattr(scope, "body", None), list) \
            else [scope.body] if hasattr(scope, "body") else []
        handles: dict = {}   # name -> the Assign node that bound it
        uses: dict = {}      # name -> [non-binding Name mentions]
        joined: set = set()
        for stmt in body:
            for node in ast.walk(stmt):
                nd = _nearest_def(node, parents)
                at_scope = (nd is scope or (scope is src.tree
                                            and nd is None))
                if not at_scope:
                    continue
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and _callee_tail(node.value) in _EXCHANGE_CALLEES:
                    handles[node.targets[0].id] = node
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "join" \
                        and isinstance(node.func.value, ast.Name):
                    joined.add(node.func.value.id)
        if not handles:
            continue
        # any OTHER mention of the name (beyond its binding target and
        # the .join receiver) counts as an escape — conservatively: a
        # handle handed to anyone else is their responsibility to join
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Name) or \
                        not isinstance(node.ctx, ast.Load):
                    continue
                if node.id not in handles:
                    continue
                p = parents.get(node)
                if isinstance(p, ast.Attribute) and p.attr == "join":
                    continue
                uses.setdefault(node.id, []).append(node)
        for name, assign in handles.items():
            if name in joined or uses.get(name):
                continue
            flag(assign,
                 f"exchange handle `{name}` is never joined and never "
                 f"escapes this scope — its payload is read by nobody "
                 f"and any super-block dispatch it crosses runs against "
                 f"an un-joined exchange; call `{name}.join()` at the "
                 f"barrier (or hand it to a StaleJoinWindow)")
    return findings


# --- rule: fleet-hygiene -----------------------------------------------------

# names that identify tenant/fleet iteration (the --fleet surface,
# solvers/fleet.py): matched against a for-loop's target and iterable
_FLEET_NAME_RE = re.compile(r"(^|_)(tenants?|fleet|lanes?)(_|$|\d)",
                            re.IGNORECASE)

# host-side device-fetch callees: each one synchronizes (or stages) a
# device value — paid PER TENANT when it sits inside a tenant loop,
# which is exactly the per-model round-trip cost the fleet path exists
# to amortize away
_FLEET_FETCH_CALLEES = {"asarray", "array", "device_get",
                        "block_until_ready", "item", "tolist"}


def _fleet_named(node: ast.For) -> bool:
    """Whether a for-loop iterates over tenants/the fleet — its target
    or iterable names say so."""
    names = []
    for sub in ast.walk(node.target):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
    for sub in ast.walk(node.iter):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.append(sub.attr)
    return any(_FLEET_NAME_RE.search(n) for n in names)


def check_fleet_hygiene(src: SourceFile, index: ModuleIndex) -> list:
    """The fleet execution contract (solvers/fleet.py, docs/DESIGN.md
    §16): the whole point of the fleet path is ONE dispatch for T
    tenants, so

    1. a Python-level ``for`` loop over tenants inside a jit/lax body is
       an error — it unrolls T copies of the kernel into the graph
       (compile time and code size scale with T, and a manifest change
       retraces everything); the tenant axis rides ``vmap``/``lax.map``
       (parallel/fanout.lane_fanout);
    2. a per-tenant device fetch (``np.asarray`` / ``jax.device_get`` /
       ``.block_until_ready()`` / ``.item()`` / ``.tolist()``) inside a
       HOST-side tenant loop is an error — T host round-trips through
       the device tunnel is the serial-path cost the fleet amortizes;
       fetch the stacked result ONCE before the loop (the
       run_cocoa_fleet pattern).

    Rides the host-sync rule's traced-context machinery."""
    findings = []
    traced = index.traced_defs()
    parents = _build_parents(src.tree)

    def flag(node, msg):
        findings.append(Finding(
            rule="fleet-hygiene", severity="error", path=src.path,
            line=node.lineno, col=node.col_offset, message=msg))

    # (1) tenant loops inside traced code
    for d in index.defs:
        if id(d) not in traced:
            continue
        body = d.body if isinstance(d.body, list) else [d.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if _nearest_def(node, parents) is not d:
                    continue
                if isinstance(node, ast.For) and _fleet_named(node):
                    flag(node,
                         "Python loop over tenants inside traced code — "
                         "this unrolls T kernel copies into the graph "
                         "(one compiled round per tenant is exactly what "
                         "the fleet path exists to avoid); batch the "
                         "tenant axis with vmap/lax.map "
                         "(parallel/fanout.lane_fanout)")

    # (2) per-tenant fetches inside host-side tenant loops
    scopes = [src.tree] + [d for d in index.defs if id(d) not in traced]
    for scope in scopes:
        body = scope.body if isinstance(getattr(scope, "body", None), list) \
            else [scope.body] if hasattr(scope, "body") else []
        for stmt in body:
            for node in ast.walk(stmt):
                nd = _nearest_def(node, parents)
                at_scope = (nd is scope or (scope is src.tree
                                            and nd is None))
                if not at_scope or not isinstance(node, ast.For) \
                        or not _fleet_named(node):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and \
                            _callee_tail(sub) in _FLEET_FETCH_CALLEES:
                        flag(sub,
                             f"per-tenant `{_callee_tail(sub)}` inside a "
                             f"fleet/tenant loop — T device round-trips "
                             f"is the serial-path cost the fleet "
                             f"amortizes away; fetch the stacked result "
                             f"ONCE before the loop (the run_cocoa_fleet "
                             f"pattern)")
    return findings


# --- rule: serve-hygiene -----------------------------------------------------

# the rule applies to the serving subsystem only (and to fixtures that
# put themselves under a serving/ path)
_SERVING_PATH_RE = re.compile(r"(^|/)serving/")

# defs that legitimately BUILD executables / static buffers: module
# level, construction, and explicit build/warmup helpers — everything
# else in a serving module is the hot path
_SERVE_BUILDER_RE = re.compile(r"^(__init__|_?build\w*|make_\w+|warmup)$")

# np/jnp array constructors whose shape argument the rule inspects
_SERVE_ALLOC_CALLEES = {"zeros", "ones", "empty", "full"}

# host clock reads: inside traced code they read the TRACE's wall clock
# once per compile, not the request's
_SERVE_CLOCK_CHAINS = {"time.time", "time.monotonic",
                       "time.perf_counter", "time.perf_counter_ns",
                       "time.monotonic_ns"}

# dtypes whose appearance as an `.astype(...)` target inside a TRACED
# scoring def marks in-graph quantization.  All narrowing happens on
# the host at swap time (serving/quantize.py) where the error
# certificate can see it; the compiled path only ever consumes the
# published form.  Widening casts (float32/int32/uint32/…) and
# bitcast_convert_type (the packed-bf16 reinterpretation) stay legal.
_SERVE_NARROW_DTYPES = {"bfloat16", "float16", "int8", "uint8",
                        "int16", "uint16", "int4", "uint4",
                        "float8_e4m3fn", "float8_e5m2"}

# max/amax spellings that, applied over an abs(), form the symmetric
# quantization scale (max|w|) — the other half of an in-graph quantize
_SERVE_SCALE_REDUCERS = {"max", "amax"}


def _narrow_dtype_name(expr: ast.AST) -> Optional[str]:
    """The narrow dtype an ``.astype(...)`` argument names, else None.
    Recognizes attribute spellings (``jnp.bfloat16``,
    ``ml_dtypes.bfloat16``, ``np.int8``) and string literals."""
    chain = _attr_chain(expr)
    if chain:
        tail = chain.rsplit(".", 1)[-1]
        if tail in _SERVE_NARROW_DTYPES:
            return tail
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
            and expr.value in _SERVE_NARROW_DTYPES:
        return expr.value
    return None


def _contains_abs_call(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and \
                _callee_tail(sub) in ("abs", "absolute"):
            return True
    return False


def _contains_len_call(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Name) and sub.func.id == "len":
            return True
    return False


def check_serve_hygiene(src: SourceFile, index: ModuleIndex) -> list:
    """The serving hot-path contract (cocoa_tpu/serving/, docs/DESIGN.md
    §17): the scoring path must compile once per static bucket and never
    sync per request.

    1. ``jax.jit`` built inside a hot-path def is an error — a jit
       created per call builds a fresh executable per request (the
       compile-per-request leak the one-compile-per-bucket pin exists
       to prevent); build it once at startup (``__init__`` / ``build_*``
       / ``warmup`` are the sanctioned builder scopes).
    2. an array allocation whose shape derives from ``len(...)`` inside
       a hot-path def is an error — a request-dependent shape retraces
       and recompiles on every distinct batch size; pad UP to a static
       bucket (serving/scorer.pick_bucket) instead.
    3. inside TRACED defs (the compiled scoring functions): a host
       clock read (``time.time``/``monotonic``/``perf_counter``) or a
       ``.block_until_ready()`` is an error — it times (or syncs) the
       TRACE, once per compile, not the request; latency accounting
       belongs at the host boundary (the batcher's spans).  Rides the
       host-sync rule's traced-context machinery.
    4. inside TRACED defs: a narrowing ``.astype(...)`` (bf16 / f16 /
       int8 / …) or a max-of-abs scale compute is an error — in-graph
       quantization bypasses the per-swap error certificate and burns
       the cast into every dispatch.  Quantize ONCE on the host at
       swap time (serving/quantize.quantize, DESIGN.md §20); the
       compiled scorer consumes the published form.  Widening casts
       (``astype(jnp.float32)`` on a dequantized gather) and
       ``lax.bitcast_convert_type`` (the packed-bf16 view) stay legal.
    """
    if not _SERVING_PATH_RE.search(src.path.replace(os.sep, "/")):
        return []
    findings = []
    traced = index.traced_defs()
    parents = _build_parents(src.tree)

    def flag(node, msg):
        findings.append(Finding(
            rule="serve-hygiene", severity="error", path=src.path,
            line=node.lineno, col=node.col_offset, message=msg))

    def hot_path(d) -> bool:
        name = getattr(d, "name", "")
        return not _SERVE_BUILDER_RE.match(name or "")

    for d in index.defs:
        body = d.body if isinstance(d.body, list) else [d.body]
        is_traced = id(d) in traced
        is_hot = hot_path(d)
        for stmt in body:
            for node in ast.walk(stmt):
                if _nearest_def(node, parents) is not d:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if is_hot and _is_jax_jit(node.func):
                    flag(node,
                         "jit built in the serving hot path — every "
                         "call builds (and compiles) a fresh "
                         "executable; build the jit once at startup "
                         "(__init__/build_*/warmup) and call the built "
                         "function per batch")
                elif is_hot and _callee_tail(node) in \
                        _SERVE_ALLOC_CALLEES and node.args and \
                        (_attr_chain(node.func) or "").split(".")[0] in \
                        (_NP_MODULES | {"jnp"}) and \
                        _contains_len_call(node.args[0]):
                    flag(node,
                         f"request-dependent shape in the serving hot "
                         f"path — `{_callee_tail(node)}` sized by "
                         f"`len(...)` compiles one executable per "
                         f"distinct batch size; pad UP to a static "
                         f"bucket (serving/scorer.pick_bucket)")
                if is_traced:
                    chain = _attr_chain(node.func) or ""
                    if chain in _SERVE_CLOCK_CHAINS:
                        flag(node,
                             f"`{chain}()` inside the compiled scoring "
                             f"path reads the clock at TRACE time, "
                             f"once per compile — time requests at the "
                             f"host boundary (the batcher's "
                             f"serve_admit/serve_score spans)")
                    elif isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "block_until_ready":
                        flag(node,
                             "`.block_until_ready()` inside the "
                             "compiled scoring path is a device sync "
                             "per call — fetch once on the host after "
                             "the dispatch (the batcher's single "
                             "intended_fetch)")
                    elif isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "astype" and node.args \
                            and _narrow_dtype_name(node.args[0]):
                        flag(node,
                             f"narrowing `.astype("
                             f"{_narrow_dtype_name(node.args[0])})` "
                             f"inside the compiled scoring path — "
                             f"in-graph quantization bypasses the "
                             f"per-swap error certificate and re-casts "
                             f"on every dispatch; quantize ONCE on the "
                             f"host at swap time "
                             f"(serving/quantize.quantize) and publish "
                             f"the narrow form")
                    elif (node.func.attr if isinstance(
                            node.func, ast.Attribute) else
                            _callee_tail(node)) in \
                            _SERVE_SCALE_REDUCERS \
                            and (any(_contains_abs_call(a)
                                     for a in node.args)
                                 or (isinstance(node.func,
                                                ast.Attribute)
                                     and _contains_abs_call(
                                         node.func.value))):
                        flag(node,
                             "max-of-abs inside the compiled scoring "
                             "path — this is the symmetric "
                             "quantization scale (max|w|) being "
                             "derived in-graph, per dispatch; the "
                             "scale is computed once on the host at "
                             "swap time (serving/quantize.quantize) "
                             "and published alongside the model")
    return findings


# --- registry ---------------------------------------------------------------

RULES = ("donation", "host-sync", "f64", "mesh-api", "pallas-budget",
         "span-hygiene", "overlap-hygiene", "fleet-hygiene",
         "serve-hygiene")


def run_static_rules(sources: dict) -> list:
    """Run every AST rule over {path: SourceFile}; returns findings."""
    findings = []
    for path, src in sources.items():
        index = ModuleIndex(src).index()
        findings += check_donation(src, index)
        findings += check_host_sync(src, index)
        findings += check_f64(src, index)
        findings += check_mesh_api(src, index)
        findings += check_pallas_budget_ast(src, index, sources)
        findings += check_span_hygiene(src, index)
        findings += check_overlap_hygiene(src, index)
        findings += check_fleet_hygiene(src, index)
        findings += check_serve_hygiene(src, index)
    return findings
