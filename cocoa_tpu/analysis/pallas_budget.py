"""jaxlint ``pallas-budget`` — the numeric half of the Pallas memory
accounting (rules.py holds the AST half).

The kernels in ``ops/pallas_*.py`` never ask the hardware how much VMEM /
SMEM they may use: each module DECLARES a budget constant and sizes its
blocks with an estimate function that a ``*_fits`` gate compares against
the budget before dispatch commits to the kernel.  That contract has two
statically checkable failure modes:

1. a declared budget exceeding the physical memory (the gate then
   happily admits kernels Mosaic will kill at compile or runtime), and
2. estimate/gate drift — someone widens a scratch buffer or BlockSpec
   and updates the estimate but not the gate (or vice versa), so the
   gate's verdict no longer tracks the bytes the estimate accounts.

This module imports the ops modules (pure Python on CPU; importing does
not build kernels) and checks both: budget constants against the
physical caps from the TPU programming model (~16 MiB VMEM per core,
SMEM far smaller — we cap the repo's scalar-stream budget at 1 MiB), and
gate-vs-estimate agreement swept over a grid of dispatch-realistic
shapes (rcv1 production geometry, the CI synth shapes, and adversarial
corners around each gate's boundary).
"""

from __future__ import annotations

import importlib

from cocoa_tpu.analysis.core import Finding

# physical caps (pallas_guide: VMEM ~16 MB/core; SMEM "small" — the
# repo's scalar streams must stay well under 1 MiB)
PHYS_VMEM = 16 << 20
PHYS_SMEM = 1 << 20

_OPS_MODULES = ("cocoa_tpu.ops.pallas_sdca", "cocoa_tpu.ops.pallas_sparse",
                "cocoa_tpu.ops.pallas_chain")

# dispatch-realistic sweep: (k, n_shard, d, max_nnz, b, n_hot) covering
# rcv1 production geometry (d=47236, ~170k rows over K=4, row width 548
# / residual ~214 after the hot split), the CI synth shapes, and corners
_SHAPES = (
    # k, n_shard,   d, max_nnz,   b, n_hot
    (4, 169350, 47236,     548, 256,     0),   # rcv1, stream path
    (4, 169350, 47236,     214, 256,  2048),   # rcv1, hybrid hot/cold
    (4,   2048,  9947,      64, 128,     0),   # CI small_train shape
    (4,   2048,  9947,      48, 128,   256),
    (8,  65536, 16384,     128, 512,   512),
    (1,    128,   256,       8,  64,     0),   # single-shard corner
    (16, 32768, 47236,    1024, 128,     0),   # fat rows: should NOT fit
)


def _mod_findings(modname):
    findings = []

    def flag(line, message, severity="error"):
        findings.append(Finding(
            rule="pallas-budget", severity=severity,
            path=modname.replace(".", "/") + ".py", line=line, col=0,
            message=message))

    return findings, flag


def check_budget_constants() -> list:
    """Every *_BUDGET constant in the ops modules stays under its
    physical cap — a budget over the hardware turns the fits gates into
    rubber stamps."""
    findings = []
    for modname in _OPS_MODULES:
        mod = importlib.import_module(modname)
        out, flag = _mod_findings(modname)
        for name in dir(mod):
            if not name.endswith("BUDGET"):
                continue
            val = getattr(mod, name)
            if not isinstance(val, int):
                continue
            cap = PHYS_SMEM if "SMEM" in name else PHYS_VMEM
            kind = "SMEM" if "SMEM" in name else "VMEM"
            if val > cap:
                flag(1, f"{name} = {val} bytes exceeds the physical "
                        f"{kind} cap ({cap}) — the fits gates admit "
                        f"kernels the hardware cannot hold")
            elif "SMEM" not in name and val > PHYS_VMEM - (1 << 20):
                flag(1, f"{name} = {val} bytes leaves under 1 MiB of "
                        f"VMEM headroom for Mosaic spills/semaphores",
                     severity="warning")
        findings += out
    return findings


def check_gate_estimate_agreement() -> list:
    """Sweep the fits gates against their own estimates: wherever a gate
    says True, the matching estimate must be within the budget (drift
    in either direction makes overflow a runtime surprise again)."""
    findings = []
    sdca = importlib.import_module("cocoa_tpu.ops.pallas_sdca")
    sparse = importlib.import_module("cocoa_tpu.ops.pallas_sparse")
    chain = importlib.import_module("cocoa_tpu.ops.pallas_chain")
    itemsize = 4  # f32, the TPU compute dtype (DESIGN.md §6)

    def flag(modname, message):
        findings.append(Finding(
            rule="pallas-budget", severity="error",
            path=modname.replace(".", "/") + ".py", line=1, col=0,
            message=message))

    for (k, n_shard, d, max_nnz, b, n_hot) in _SHAPES:
        # sequential sparse kernel: fits ⇒ estimate under budget AND the
        # SMEM segment split leaves at least one step per invocation
        if sparse.sparse_kernel_fits(k, n_shard, d, max_nnz, h=b,
                                     itemsize=itemsize, n_hot=n_hot):
            est = sparse.sparse_vmem_estimate(n_shard, d, max_nnz,
                                              itemsize, k, n_hot)
            if est > sparse.VMEM_BUDGET:
                flag("cocoa_tpu.ops.pallas_sparse",
                     f"sparse_kernel_fits admits shape k={k} "
                     f"n_shard={n_shard} d={d} W={max_nnz} n_hot={n_hot} "
                     f"but sparse_vmem_estimate={est} exceeds "
                     f"VMEM_BUDGET={sparse.VMEM_BUDGET}")
            if sparse.segment_len(k, max_nnz) < 1:
                flag("cocoa_tpu.ops.pallas_sparse",
                     f"sparse_kernel_fits admits k={k} W={max_nnz} but "
                     f"segment_len < 1 — the SMEM stream cannot hold "
                     f"even one step")
        # the SMEM accounting identity: a segment's two (K, S, W) streams
        # (int32 idx + f32 vals = 8 bytes/slot) must fit the SMEM budget
        s = sparse.segment_len(k, max_nnz)
        if s >= 1 and 8 * k * s * max_nnz > sparse.SMEM_IDX_BUDGET:
            flag("cocoa_tpu.ops.pallas_sparse",
                 f"segment_len({k}, {max_nnz}) = {s} overflows "
                 f"SMEM_IDX_BUDGET: {8 * k * s * max_nnz} bytes")
        # block-chain kernels
        if chain.chain_fits(k, b, itemsize):
            est = chain.chain_vmem_estimate(k, b, itemsize)
            if est > chain.CHAIN_VMEM_BUDGET:
                flag("cocoa_tpu.ops.pallas_chain",
                     f"chain_fits admits k={k} B={b} but estimate={est} "
                     f"exceeds CHAIN_VMEM_BUDGET")
        if chain.fused_fits(k, b, d, itemsize):
            est = chain.fused_vmem_estimate(k, b, d, itemsize)
            if est > chain.FUSED_VMEM_BUDGET:
                flag("cocoa_tpu.ops.pallas_chain",
                     f"fused_fits admits k={k} B={b} d={d} but "
                     f"estimate={est} exceeds FUSED_VMEM_BUDGET")
        # dense folded-layout SDCA kernel: the unroll pickers must only
        # ever choose group sizes whose estimates respect their budgets
        s = sdca.pick_unroll(n_shard, d, itemsize, h=b)
        if s > 0 and sdca.vmem_estimate(n_shard, d, itemsize, s) > \
                sdca.VMEM_BUDGET:
            flag("cocoa_tpu.ops.pallas_sdca",
                 f"pick_unroll({n_shard}, {d}) chose S={s} whose "
                 f"estimate exceeds VMEM_BUDGET")
        s = sdca.pick_interleave(k, n_shard, d, itemsize, h=b)
        if s > 0 and sdca.interleave_vmem_estimate(
                k, n_shard, d, itemsize, s) > sdca.INTERLEAVE_BUDGET:
            flag("cocoa_tpu.ops.pallas_sdca",
                 f"pick_interleave(k={k}, {n_shard}, {d}) chose S={s} "
                 f"whose estimate exceeds INTERLEAVE_BUDGET")
        # sparse block-chain Gram/apply path: fits ⇒ the segment pair's
        # SMEM streams and the Gram tile's VMEM stay inside budget
        if sparse.sparse_chain_fits(k, n_shard, d, max_nnz, b, itemsize):
            sb = sparse.seg_rows(b, max_nnz)
            group = min(sparse.GROUP, max(1, max_nnz))
            w_r = -(-max_nnz // group) * group
            if sb < 8 or 16 * sb * w_r > sparse.SMEM_IDX_BUDGET:
                flag("cocoa_tpu.ops.pallas_sparse",
                     f"sparse_chain_fits admits B={b} W={max_nnz} but "
                     f"seg_rows={sb} overflows SMEM_IDX_BUDGET")
            if sparse.sparse_block_vmem(d, b, sb, itemsize) > \
                    sparse.VMEM_BUDGET:
                flag("cocoa_tpu.ops.pallas_sparse",
                     f"sparse_chain_fits admits d={d} B={b} but the "
                     f"Gram tile estimate exceeds VMEM_BUDGET")
        if n_hot > 0 and sparse.hybrid_fits(k, n_shard, d, max_nnz, b,
                                            n_hot, itemsize) and \
                n_hot % 128 != 0:
            flag("cocoa_tpu.ops.pallas_sparse",
                 f"hybrid_fits admits a non-lane-aligned hot panel "
                 f"(n_hot={n_hot})")
    return findings


def run_budget_checks() -> list:
    """The full numeric pallas-budget pass; import failures degrade to a
    lint error rather than a crash (CI must see them either way)."""
    try:
        findings = check_budget_constants()
        findings += check_gate_estimate_agreement()
        return findings
    except Exception as e:  # pragma: no cover - only on API drift
        return [Finding(
            rule="pallas-budget", severity="error",
            path="cocoa_tpu/ops", line=1, col=0,
            message=(f"budget cross-check could not run ({type(e).__name__}:"
                     f" {e}) — the ops accounting API drifted out from "
                     f"under the analyzer; update pallas_budget.py"))]
