"""``python -m cocoa_tpu.analysis`` — the jaxlint CLI / CI gate.

Exit codes: 0 = clean (no findings outside the justified baseline and
inline suppressions), 1 = new findings, 2 = usage error.

Flags:
  --report=PATH       write the full JSONL report (header + one line per
                      finding; ``python -m cocoa_tpu.telemetry.schema``
                      validates it)
  --baseline=PATH     baseline file (default: the committed
                      cocoa_tpu/analysis/baseline.json)
  --update-baseline   rewrite the baseline from the current findings
                      (existing justifications preserved; new entries
                      get a TODO placeholder to fill in)
  --no-budget         skip the numeric Pallas budget cross-check (AST
                      rules only — useful where the ops modules cannot
                      import)
  --all               show baselined/suppressed findings too
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    report_path = None
    baseline_path = None
    update_baseline = False
    with_budget = True
    show_all = False
    targets = []
    for a in argv:
        if a.startswith("--report="):
            report_path = a.split("=", 1)[1]
        elif a.startswith("--baseline="):
            baseline_path = a.split("=", 1)[1]
        elif a == "--update-baseline":
            update_baseline = True
        elif a == "--no-budget":
            with_budget = False
        elif a == "--all":
            show_all = True
        elif a.startswith("-"):
            print(__doc__, file=sys.stderr)
            return 2
        else:
            targets.append(a)

    from cocoa_tpu import analysis
    from cocoa_tpu.analysis import core

    if targets:
        root = core.repo_root()
        missing = [t for t in targets
                   if not os.path.exists(os.path.join(root, t))]
        if missing:
            print(f"error: no such path(s) under {root}: "
                  f"{', '.join(missing)} — targets are repo-relative",
                  file=sys.stderr)
            return 2

    findings, sources, stale = analysis.run_analysis(
        targets=targets or None, baseline_path=baseline_path,
        with_budget_checks=with_budget)

    if update_baseline:
        # a path-scoped update must not wipe baseline entries for files
        # outside the scan — carry them over untouched
        n = core.write_baseline(
            findings, baseline_path or core.BASELINE_PATH,
            scanned_paths=set(sources) if targets else None)
        print(f"baseline updated: {n} entr{'y' if n == 1 else 'ies'} "
              f"(fill in any TODO justifications before committing)")

    if report_path:
        core.write_report(report_path, findings, len(sources),
                          analysis.RULES)
        print(f"report: {report_path}")

    new = [f for f in findings if f.actionable]
    base = [f for f in findings if f.baselined]
    supp = [f for f in findings if f.suppressed]

    shown = findings if show_all else new
    for f in sorted(shown, key=lambda f: (f.path, f.line, f.col, f.rule)):
        tag = ("" if f.actionable
               else " [baselined]" if f.baselined else " [allowed]")
        print(f"{f.location()}: {f.severity}[{f.rule}]{tag} {f.message}")
        if f.replacement:
            print(f"    replacement: {f.replacement}")

    for e in stale:
        print(f"stale baseline entry {e['fingerprint']} "
              f"({e['rule']} at {e.get('path', '?')}) — finding no longer "
              f"produced; run --update-baseline to drop it")

    print(f"jaxlint: {len(sources)} files, {len(findings)} finding(s): "
          f"{len(new)} new, {len(base)} baselined, {len(supp)} allowed "
          f"inline" + (f", {len(stale)} stale baseline" if stale else ""))
    if new and not update_baseline:
        print("new findings — fix them, add a justified "
              "`# jaxlint: allow=<rule> -- reason`, or (for a worklist "
              "item) baseline with --update-baseline + a justification")
    return 1 if (new and not update_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
