"""JSONL schema checker for the telemetry artifacts.

One dependency-free validator shared by tests/test_telemetry.py and the CI
telemetry step, covering the six JSONL dialects this repo emits:

- **event streams** (``--events``, telemetry/events.py): every line has
  ``event``/``seq``/``ts``, per-type required fields, and ``seq`` is
  strictly increasing — the ordering guarantee the ordered io_callback
  bridge provides;
- **trajectory dumps** (``--trajOut``, utils/logging.Trajectory): a
  manifest header line followed by per-round records, ``stopped`` carried
  on the final record;
- **benchmark results** (benchmarks/results.jsonl): one config row per
  line.
- **analysis reports** (``python -m cocoa_tpu.analysis --report=...``):
  an ``analysis_manifest`` header plus one finding per line, unique
  fingerprints (what the jaxlint baseline keys on).
- **flight-recorder dumps** (``<events>.flightrec``,
  telemetry/recorder.py): a ``flightrec_manifest`` header (dump reason,
  victim pid, ring size) followed by the last-N event records the ring
  held when the dump fired.
- **fleet manifests** (``--fleet``, data/fleet.py): a ``fleet_manifest``
  header followed by one tenant per line (dataset ref, λ, gap target) —
  the loader validates through this checker before building anything.

Usage: ``python -m cocoa_tpu.telemetry.schema FILE...`` — the dialect is
sniffed per file from its first line; exit code 1 on any violation.
"""

from __future__ import annotations

import json
import sys

_NUM = (int, float)
_OPT_NUM = (int, float, type(None))

# the run_start manifest's layout-split record (--hotCols provenance,
# data/hybrid.resolve_hot_cols): present on sparse-layout svm runs so
# benchmark provenance is machine-readable — which panel the run trained
# on, what it covered, and what the residual streams still pay
LAYOUT_SPLIT_FIELDS = {
    "spec": (str,),
    "hot_cols": (int,),
    "coverage": _NUM,
    "residual_mean_nnz": _NUM,
    "residual_max_nnz": (int,),
    "panel_bytes": (int,),
}

# the run_start manifest's ingest record (--ingest provenance,
# data/ingest.IngestReport): how the training data reached the device —
# which mode, what this process parsed, and what it cost (the same fields
# ride the typed ``ingest`` event)
INGEST_FIELDS = {
    "mode": (str,),
    "path": (str,),
    "file_bytes": (int,),
    "processes": (int,),
    "parse_seconds": _NUM,
    "bytes_read": (int,),
    "rows": (int,),
    "nnz": (int,),
    "n": (int,),
    "total_nnz": (int,),
    "peak_rss_bytes": (int,),
    "cache": (str,),     # --ingestCache outcome: off|hit|partial|miss
}

# event type -> {field: allowed types}; every event also needs seq/ts
EVENT_FIELDS = {
    "run_start": {"manifest": (dict,)},
    "round_eval": {"algorithm": (str,), "t": (int,), "primal": _NUM,
                   "gap": _OPT_NUM, "test_error": _OPT_NUM,
                   "sigma": _OPT_NUM, "stall": _OPT_NUM},
    "sigma_backoff": {"algorithm": (str,), "t": (int,), "sigma": _NUM,
                      "from_sigma": _NUM},
    "checkpoint_write": {"algorithm": (str,), "round": (int,),
                         "path": (str,)},
    "restart": {"reason": (str,)},
    "divergence": {"algorithm": (str,), "t": (int,), "n_evals": (int,)},
    "run_end": {"algorithm": (str,), "stopped": (str, type(None))},
    # the sanitizer bridge (analysis/sanitize.py): one per finished XLA
    # compile / per sanctioned device→host fetch — what feeds the
    # cocoa_compiles_total / cocoa_host_transfers_total counters
    "compile": {"name": (str,), "seconds": _NUM},
    "host_transfer": {"label": (str,)},
    # the accelerated outer loop (--accel, solvers/base.py): a
    # gap-monitored momentum restart / a Θ local-accuracy ladder step —
    # emitted identically by the live io_callback stream and the fetch
    # replay (DeviceTap) and by the host-stepped drivers' twin
    "momentum_restart": {"algorithm": (str,), "t": (int,),
                         "restarts_total": (int,)},
    "theta_stage": {"algorithm": (str,), "t": (int,), "stage": (int,),
                    "h": (int, type(None))},
    # streaming/whole ingest of one LIBSVM file (data/ingest.py): what
    # feeds cocoa_ingest_seconds / cocoa_ingest_bytes in --metrics
    "ingest": INGEST_FIELDS,
    # one file's --ingestCache outcome (data/slab_cache.py, DESIGN.md
    # §18): what feeds cocoa_ingest_cache_hits_total /
    # cocoa_ingest_cache_bytes in --metrics
    "ingest_cache": {"path": (str,), "status": (str,),
                     "shards_cached": (int,), "shards_total": (int,),
                     "bytes_mapped": (int,), "seconds_saved": _NUM},
    # a cache artifact failed validation on load and was evicted; the
    # shard fell back to a cold parse (the torn/truncated-file recovery
    # path, pinned with the tests/_faults.py truncate fault)
    "ingest_cache_corrupt": {"path": (str,), "artifact": (str,),
                             "reason": (str,)},
    # the elastic supervisor reformed the gang at P′ < P survivors
    # (cocoa_tpu/elastic.py shrink-to-survivors): what feeds the
    # cocoa_gang_size gauge.  ``restart`` events additionally carry
    # gang_size / backoff_s (not required here: the σ′ trial rerun emits
    # restarts too, without a gang)
    "gang_resize": {"reason": (str,), "old_size": (int,),
                    "new_size": (int,), "generation": (int,)},
    # a checkpoint generation failed validation on load and the reader
    # fell back (checkpoint.latest) — the torn/corrupt-file recovery path
    "checkpoint_corrupt": {"algorithm": (str,), "path": (str,),
                           "reason": (str,)},
    # one closed tracing span (telemetry/tracing.py): the per-phase,
    # per-worker timing record trace_report.py assembles into the gang
    # timeline / per-round critical path / straggler table.  parent_id
    # None = a top-level span; worker None = tracer configured without a
    # process index (single-process runs)
    "span": {"phase": (str,), "span_id": (int,),
             "parent_id": (int, type(None)),
             "worker": (int, type(None)),
             "start_ts": _NUM, "dur_s": _NUM},
    # the JSONL sink hit its --eventsMaxMB cap and rolled to `.1`
    # (events.EventBus._rotate) — always the first event of a fresh file
    "events_rotate": {"path": (str,), "rotated_to": (str,),
                      "bytes": (int,)},
    # one joined overlapped exchange (--overlapComm,
    # parallel/distributed.ExchangeHandle): what feeds the
    # cocoa_overlap_hidden_seconds gauge
    "comm_overlap": {"tag": (str,), "hidden_s": _NUM, "wait_s": _NUM},
    # a bounded-staleness contribution joined rounds_late rounds after
    # its own round (--staleRounds, solvers/cocoa.StaleJoinWindow):
    # what feeds cocoa_stale_joins_total{rounds_late=}
    "stale_join": {"algorithm": (str,), "t": (int,), "round": (int,),
                   "rounds_late": (int,),
                   "workers": (int, type(None))},
    # one fleet eval boundary (--fleet, solvers/fleet.py): how many
    # tenant lanes are still live and how many have certified — what
    # feeds cocoa_fleet_tenants_active / cocoa_fleet_models_per_second
    # (models_per_second rides only the final event, once the wall-clock
    # denominator exists)
    "fleet_progress": {"algorithm": (str,), "t": (int,),
                       "active": (int,), "certified_total": (int,),
                       "models_per_second": _OPT_NUM},
    # one tenant crossed its duality-gap target inside the fleet loop —
    # what feeds cocoa_tenants_certified_total
    "tenant_certified": {"algorithm": (str,), "tenant": (str,),
                         "t": (int,), "gap": _OPT_NUM},
    # one scored serving batch (--serve, serving/batcher.py): what feeds
    # cocoa_serve_qps / cocoa_serve_latency_seconds /
    # cocoa_serve_batch_fill_ratio.  model_round is None only before the
    # first checkpoint carried a round (never in practice — the server
    # refuses to start without a validated generation)
    "serve_request": {"algorithm": (str,), "n": (int,), "bucket": (int,),
                      "fill_ratio": _NUM, "queue_s": _NUM,
                      "device_s": _NUM, "latency_max_s": _NUM,
                      "latency_mean_s": _NUM, "model_round": _OPT_NUM},
    # the serving watcher hot-swapped a new validated generation into
    # the live slot (serving/watcher.py): what anchors
    # cocoa_model_gap_age_seconds (birth_ts = the checkpoint's mtime =
    # when its certificate was produced); gap is the certified duality
    # gap the checkpoint meta recorded (None on pre-gap metas)
    # tenant_gaps / tenant_cert_ts ride the stacked catalogue's
    # per-tenant certification metadata (checkpoint meta, docs/DESIGN.md
    # §21-22): one certified gap and one certification wall-clock per
    # tenant row, None on single-model checkpoints — what feeds the
    # tenant-labeled cocoa_model_gap_age_seconds series
    "model_swap": {"algorithm": (str,), "round": (int, type(None)),
                   "path": (str,), "birth_ts": _NUM, "gap": _OPT_NUM,
                   "gap_age_s": _NUM, "swap_seq": (int,),
                   "tenant_gaps": (list, type(None)),
                   "tenant_cert_ts": (list, type(None))},
    # one --serveDtype publish decision (serving/scorer.ModelSlots):
    # served == serve_dtype when the generation certified, "f32" on a
    # certificate fallback (fallback=1); bound is the measured
    # f32-vs-quantized margin-error bound over calib_n calibration
    # queries (None when no calibration source is wired), flips how
    # many calibration margins actually changed sign, scale the int8
    # symmetric per-model scale (None for bf16).  swap_seq mirrors
    # model_swap ("seq" would collide with the record envelope)
    "model_quantize": {"algorithm": (str,), "serve_dtype": (str,),
                       "served": (str,), "round": (int, type(None)),
                       "swap_seq": (int,), "bound": _OPT_NUM,
                       "calib_n": (int,), "flips": (int,),
                       "fallback": (int,), "scale": _OPT_NUM},
    # the fleet router refused one request line at admission
    # (serving/router.py): the best live replica still projected past
    # the shed budget, so the line was refused instead of queued into
    # an SLA violation.  tenant None = an untagged line; inflight /
    # est_s describe the BEST live replica at the decision — what feeds
    # cocoa_serve_shed_total
    # trace_id: the exemplar — when the refused line carried a trace
    # context, the shed counter names a concrete query to go look at
    "serve_shed": {"algorithm": (str,), "route": (str,),
                   "tenant": (int, type(None)), "inflight": (int,),
                   "est_s": _NUM, "sla_s": _NUM,
                   "trace_id": (str, type(None))},
    # one fleet replica liveness transition (serving/router.py /
    # fleet.py): state "dead" (connection or process died), "requeue"
    # (a request line replayed off the dead replica, requeued=1), or
    # "live" (the monitor respawned it).  replicas_live is the live
    # count AFTER the transition — what feeds
    # cocoa_serve_replicas_live / cocoa_serve_requeue_total
    # trace_id: the requeue exemplar — the trace context of the line
    # that was replayed off the dead replica (None on live/dead
    # transitions and untraced requeues)
    "replica_state": {"algorithm": (str,), "replica": (str,),
                      "state": (str,), "replicas_live": (int,),
                      "requeued": (int,),
                      "trace_id": (str, type(None))},
    # one sampled end-to-end query trace (--traceSample, docs/DESIGN.md
    # §22).  Hop seconds are None where the hop does not exist: a solo
    # server has no router_queue/forward hop, a line the replica
    # rejected at parse has no replica-side hops.  requeues counts how
    # many dead replicas the line replayed past before answering;
    # replica names the answerer (None solo).  The model stamp (round,
    # gap age, dtype, bucket) is the generation that ANSWERED — how a
    # trace correlates a slow query with a stale or quantized model
    "query_trace": {"algorithm": (str,), "trace_id": (str,),
                    "tenant": (int, type(None)),
                    "replica": (str, type(None)),
                    "router_queue_s": _OPT_NUM,
                    "forward_s": _OPT_NUM,
                    "replica_queue_s": _OPT_NUM,
                    "device_s": _OPT_NUM,
                    "serialize_s": _OPT_NUM,
                    "total_s": _NUM,
                    "bucket": (int, type(None)),
                    "model_round": (int, type(None)),
                    "gap_age_s": _OPT_NUM,
                    "dtype": (str, type(None)),
                    "requeues": (int,)},
    # one /slo evaluation (telemetry/aggregate.py): attainment = the
    # fraction of served lines inside the SLA over the rolling window
    # (None until the histogram has data); burn_fast / burn_slow = the
    # multi-window error-budget burn rates ((1 - attainment) / (1 -
    # objective)) over the fast and slow windows — a burn > 1 on BOTH
    # is the page-worthy signal (fast-only = a blip, slow-only = an
    # old incident draining out)
    "slo_status": {"algorithm": (str,), "sla_ms": _NUM,
                   "objective": _NUM, "window_fast_s": _NUM,
                   "window_slow_s": _NUM, "attainment": _OPT_NUM,
                   "burn_fast": _OPT_NUM, "burn_slow": _OPT_NUM,
                   "served_total": (int,), "over_sla_total": (int,),
                   "replicas_live": (int, type(None))},
}

# --fleet manifest dialect (data/fleet.py): a ``fleet_manifest`` header
# line, then one tenant per line.  tenant/dataset/lam are required; the
# optional columns are type-checked when present (file-backed datasets
# carry num_features, non-hinge fleets a loss/smoothing pair)
FLEET_TENANT_REQUIRED = {
    "tenant": (str,),
    "dataset": (str,),
    "lam": _NUM,
}
FLEET_TENANT_OPTIONAL = {
    "gap_target": _OPT_NUM,
    "num_features": (int,),
    "loss": (str,),
    "smoothing": _NUM,
}

TRAJ_RECORD_FIELDS = {
    "algorithm": (str,),
    "round": (int,),
    "wall_time": _OPT_NUM,
    "primal": _OPT_NUM,
    "gap": _OPT_NUM,
    "test_error": _OPT_NUM,
    "sigma": _OPT_NUM,
}

# jaxlint JSONL reports (python -m cocoa_tpu.analysis --report=...):
# one analysis_manifest header line, then one line per finding
ANALYSIS_FINDING_FIELDS = {
    "rule": (str,),
    "severity": (str,),
    "path": (str,),
    "line": (int,),
    "col": (int,),
    "message": (str,),
    "fingerprint": (str,),
}

ANALYSIS_SEVERITIES = ("error", "warning", "inventory")


# benchmarks/results.jsonl: "config" identifies the row; every OTHER known
# key is type-checked when present (rows carry different column subsets —
# svm vs lasso vs perf-accounting)
RESULTS_FIELDS = {
    "config": (str,), "n": (int,), "d": (int,), "k": (int,),
    "lam": _NUM, "rounds": (int,), "gap": _NUM, "primal": _NUM,
    "wallclock_s": _NUM, "fixed_s": _NUM, "l2": _NUM,
    "vs_oracle": _NUM, "vs_oracle_same_gap": _NUM, "oracle_basis": (str,),
    "type": (str,), "device": (str,), "ms_per_round": _NUM,
    "us_per_step": _NUM, "useful_gflops": _NUM, "physical_gflops": _NUM,
    "mfu_pct": _NUM, "physical_mfu_pct": _NUM, "hbm_floor_ms": _NUM,
    "hbm_bound_pct": _NUM, "bound": (str,),
    # h / gap_target are numeric but legacy rows carry e.g. "n/a"
    "h": (int, str), "gap_target": (int, float, str),
    # the accelerated outer loop A/B row (--accel, benchmarks/run.py):
    # control rounds, measured ratio, and the theoretical Nesterov floor
    # (perf.predict_accel_rounds)
    "control_rounds": (int,), "rounds_ratio": _NUM,
    "accel_floor_rounds": (int,), "stopped": (str, type(None)),
    "sigma_ladder": (str,),
    # the fleet rows (--fleet / benchmarks/fleet_bench.py): tenants
    # certified per second through the one compiled vmapped round, with
    # the serial solo control and the measured speedup alongside
    "tenants": (int,), "certified": (int,), "models_per_second": _NUM,
    "serial_models_per_second": _NUM, "speedup": _NUM, "compiles": (int,),
    "lam_lo": _NUM, "lam_hi": _NUM, "drive_mode": (str,),
    "lane_exec": (str,),
    # the ingest A/B rows (benchmarks/run.py bench_ingest): per-process
    # parse wallclock / bytes / peak host RSS, stream vs whole, with the
    # perf.ingest_model predictions alongside
    "mode": (str,), "processes": (int,), "file_mb": _NUM,
    "parse_s": _NUM, "bytes_read_mb": _NUM, "peak_rss_mb": _NUM,
    "rss_delta_mb": _NUM, "rss_vs_whole": _NUM,
    "predicted_parse_s": _NUM, "predicted_csr_mb": _NUM,
    # the warm-ingest rows (--ingestCache, benchmarks/run.py
    # bench_ingest "warm" mode): zero-parse slab mapping vs the streamed
    # cold parse of the same file/geometry
    "warm_speedup": _NUM, "bytes_mapped_mb": _NUM,
    # the serving rows (--serve / benchmarks/serve_bench.py): queries/s
    # under a pinned p99 SLA plus the model-freshness (gap age) the run
    # observed; buckets is the static bucket ladder ("64/256"), compiles
    # the measured XLA compile count (== bucket count, the
    # one-compile-per-bucket pin), swaps the hot-swaps served through
    "qps": _NUM, "p50_ms": _NUM, "p99_ms": _NUM, "sla_ms": _NUM,
    "gap_age_s": _NUM, "buckets": (str,), "queries": (int,),
    "swaps": (int,), "fill": _NUM, "threads": (int,),
    # the low-precision serving A/B rows (--serveDtype,
    # benchmarks/serve_bench.py): compiled-path throughput of the
    # packed bf16/int8 model vs the SAME-harness f32 control at a
    # geometry where the f32 model spills the cache level the packed
    # form fits (the honest mechanism: the gather stream halves);
    # margin_err_bound is the per-swap certificate, flips the sign
    # flips observed beyond it (gated == 0), calib_n the calibration
    # batch size the bound was measured over
    "serve_dtype": (str,), "f32_qps": _NUM, "qps_ratio": _NUM,
    "margin_err_bound": _NUM, "flips": (int,), "flip_checked": (int,),
    "calib_n": (int,),
    # the fleet-serving rows (--serveReplicas,
    # benchmarks/serve_bench.py): aggregate open-loop qps of R replicas
    # behind the router vs the SAME-harness 1-replica control
    # (control_qps), scaling_eff = qps / (replicas × control_qps);
    # shed / requeued / failed are the router's admission + recovery
    # accounting (failed is pinned 0 — a SIGKILLed replica requeues,
    # never fails), rate_qps the open-loop offered rate
    "replicas": (int,), "route": (str,), "rate_qps": _NUM,
    "control_qps": _NUM, "scaling_eff": _NUM, "shed": (int,),
    "requeued": (int,), "failed": (int,), "killed": (int,),
    # the per-query tracing A/B riding the fleet row (--serveReplicas,
    # docs/DESIGN.md §22): closed-loop qps with every line
    # trace=-prefixed (1-in-N sampled into query_trace events) vs the
    # same-shape untraced window, the measured overhead percentage
    # (gated ≤5% on the committed row), the sampled-trace count, the
    # trace stream's schema-violation count (gated 0), and the
    # waterfall's dominant hop over the run's sampled traces
    "traced_qps": _NUM, "trace_overhead_pct": _NUM,
    "trace_sampled": (int,), "trace_schema_errors": (int,),
    "dominant_hop": (str, type(None)),
}


def _typecheck(obj, fields, where, errors, required=True):
    for name, types in fields.items():
        if name not in obj:
            if required:
                errors.append(f"{where}: missing field {name!r}")
            continue
        v = obj[name]
        if isinstance(v, bool) or not isinstance(v, types):
            errors.append(f"{where}: field {name!r} has type "
                          f"{type(v).__name__}, expected "
                          f"{'/'.join(t.__name__ for t in types)}")


def check_event_lines(objs) -> list:
    """Validate an event stream; returns a list of error strings.

    ``seq`` must be strictly increasing PER EMITTER (``pid``): a
    supervised run interleaves several processes' whole-line appends in
    one file — the elastic supervisor's restart events between worker
    generations, each generation's fresh EventBus — and each emitter
    counts its own seq from 1.  The ordering guarantee (the ordered
    io_callback bridge) is per run, which is per emitter."""
    errors = []
    prev_seq = {}
    for ln, obj in objs:
        where = f"line {ln}"
        ev = obj.get("event")
        if ev not in EVENT_FIELDS:
            errors.append(f"{where}: unknown event type {ev!r}")
            continue
        seq = obj.get("seq")
        if not isinstance(seq, int) or isinstance(seq, bool):
            errors.append(f"{where}: missing/invalid seq")
        else:
            # pre-pid streams validate as one emitter (pid None); a
            # restarted worker generation is a NEW process with a new
            # pid, so per-pid strict ordering covers supervised runs too
            pid = obj.get("pid")
            prev = prev_seq.get(pid, 0)
            if seq <= prev:
                errors.append(f"{where}: seq {seq} not increasing "
                              f"(prev {prev} for pid {pid}) — event order "
                              f"violated")
            prev_seq[pid] = seq
        if not isinstance(obj.get("ts"), _NUM):
            errors.append(f"{where}: missing/invalid ts")
        _typecheck(obj, EVENT_FIELDS[ev], where, errors)
        if ev == "run_start":
            man = obj.get("manifest")
            split = man.get("layout_split") if isinstance(man, dict) else None
            if split is not None:
                if not isinstance(split, dict):
                    errors.append(f"{where}: layout_split must be an object")
                else:
                    _typecheck(split, LAYOUT_SPLIT_FIELDS,
                               f"{where}: layout_split", errors)
            ing = man.get("ingest") if isinstance(man, dict) else None
            if ing is not None:
                if not isinstance(ing, dict):
                    errors.append(f"{where}: ingest must be an object")
                else:
                    _typecheck(ing, INGEST_FIELDS,
                               f"{where}: ingest", errors)
    return errors


def check_trajectory_lines(objs) -> list:
    """Validate a --trajOut dump: manifest header, per-round records,
    ``stopped`` on the final record."""
    errors = []
    if not objs:
        return ["empty trajectory file"]
    ln0, head = objs[0]
    man = head.get("manifest")
    if not isinstance(man, dict):
        errors.append(f"line {ln0}: first line must carry the run manifest")
    else:
        for name in ("algorithm", "config_hash", "jax_version", "backend"):
            if name not in man:
                errors.append(f"line {ln0}: manifest missing {name!r}")
    for j, (ln, obj) in enumerate(objs[1:]):
        _typecheck(obj, TRAJ_RECORD_FIELDS, f"line {ln}", errors)
    if len(objs) > 1:
        ln, last = objs[-1]
        if "stopped" not in last:
            errors.append(f"line {ln}: final record must carry 'stopped' "
                          f"(null = ran its full round budget)")
        elif not isinstance(last["stopped"], (str, type(None))):
            errors.append(f"line {ln}: 'stopped' must be a string or null")
    return errors


def check_results_lines(objs) -> list:
    """Validate benchmarks/results.jsonl rows."""
    errors = []
    for ln, obj in objs:
        where = f"line {ln}"
        if not isinstance(obj.get("config"), str):
            errors.append(f"{where}: missing/invalid 'config'")
        _typecheck(obj, RESULTS_FIELDS, where, errors, required=False)
    return errors


def check_analysis_lines(objs) -> list:
    """Validate a jaxlint JSONL report: the manifest header, per-finding
    required fields, legal severities, and fingerprint uniqueness (the
    baseline keys on fingerprints — a collision would silently merge two
    findings)."""
    errors = []
    if not objs:
        return ["empty analysis report"]
    ln0, head = objs[0]
    man = head.get("analysis_manifest")
    if not isinstance(man, dict):
        errors.append(f"line {ln0}: first line must carry the "
                      f"analysis_manifest header")
    else:
        for name in ("tool", "version", "files_scanned", "rules"):
            if name not in man:
                errors.append(f"line {ln0}: analysis_manifest missing "
                              f"{name!r}")
    seen = {}
    for ln, obj in objs[1:]:
        where = f"line {ln}"
        _typecheck(obj, ANALYSIS_FINDING_FIELDS, where, errors)
        sev = obj.get("severity")
        if isinstance(sev, str) and sev not in ANALYSIS_SEVERITIES:
            errors.append(f"{where}: severity {sev!r} not in "
                          f"{ANALYSIS_SEVERITIES}")
        fp = obj.get("fingerprint")
        if isinstance(fp, str):
            if fp in seen:
                errors.append(f"{where}: fingerprint {fp} duplicates "
                              f"line {seen[fp]}")
            seen[fp] = ln
    return errors


def check_flightrec_lines(objs) -> list:
    """Validate a flight-recorder dump (``<events>.flightrec``,
    telemetry/recorder.py — the 5th dialect): a ``flightrec_manifest``
    header naming the dump reason, then the ring's last-N event records,
    each a valid typed event (per-emitter seq ordering holds — the ring
    preserves emission order, and a victim-tail dump is one emitter)."""
    errors = []
    if not objs:
        return ["empty flight-recorder dump"]
    ln0, head = objs[0]
    man = head.get("flightrec_manifest")
    if not isinstance(man, dict):
        errors.append(f"line {ln0}: first line must carry the "
                      f"flightrec_manifest header")
    else:
        for name in ("reason", "ts", "n_events"):
            if name not in man:
                errors.append(f"line {ln0}: flightrec_manifest missing "
                              f"{name!r}")
        n = man.get("n_events")
        if isinstance(n, int) and n != len(objs) - 1:
            errors.append(f"line {ln0}: manifest says n_events={n} but "
                          f"the dump carries {len(objs) - 1} records "
                          f"(torn dump?)")
    return errors + check_event_lines(objs[1:])


def check_fleet_lines(objs) -> list:
    """Validate a --fleet manifest (the 6th dialect, data/fleet.py): a
    ``fleet_manifest`` header naming the dialect version, then one tenant
    object per line — required tenant/dataset/lam, optional columns
    type-checked when present, tenant ids unique (the fleet's per-tenant
    events and metrics key on them)."""
    errors = []
    if not objs:
        return ["empty fleet manifest"]
    ln0, head = objs[0]
    man = head.get("fleet_manifest")
    if not isinstance(man, dict):
        errors.append(f"line {ln0}: first line must carry the "
                      f"fleet_manifest header")
    elif "version" not in man:
        errors.append(f"line {ln0}: fleet_manifest missing 'version'")
    seen = {}
    known = set(FLEET_TENANT_REQUIRED) | set(FLEET_TENANT_OPTIONAL)
    for ln, obj in objs[1:]:
        where = f"line {ln}"
        _typecheck(obj, FLEET_TENANT_REQUIRED, where, errors)
        _typecheck(obj, FLEET_TENANT_OPTIONAL, where, errors,
                   required=False)
        # manifests are USER-authored input (unlike the machine-emitted
        # dialects): a typoed optional column ('gap_taget') must fail
        # here, not silently train a different fleet
        for key in sorted(set(obj) - known):
            errors.append(f"{where}: unknown field {key!r} (known tenant "
                          f"columns: {sorted(known)})")
        tid = obj.get("tenant")
        if isinstance(tid, str):
            if tid in seen:
                errors.append(f"{where}: tenant {tid!r} duplicates "
                              f"line {seen[tid]}")
            seen[tid] = ln
    if len(objs) == 1:
        errors.append("fleet manifest names no tenants")
    return errors


def sniff(objs) -> str:
    """Dialect from the first line: 'events' | 'trajectory' | 'results'
    | 'analysis' | 'flightrec' | 'fleet'."""
    if not objs:
        return "events"
    head = objs[0][1]
    if "event" in head:
        return "events"
    if "analysis_manifest" in head:
        return "analysis"
    if "flightrec_manifest" in head:
        return "flightrec"
    if "fleet_manifest" in head:
        return "fleet"
    if "manifest" in head:
        return "trajectory"
    return "results"


_CHECKERS = {"events": check_event_lines,
             "trajectory": check_trajectory_lines,
             "results": check_results_lines,
             "analysis": check_analysis_lines,
             "flightrec": check_flightrec_lines,
             "fleet": check_fleet_lines}


def check_file(path: str, kind: str = "auto") -> list:
    """Parse + validate one JSONL file; returns a list of error strings."""
    objs = []
    errors = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                errors.append(f"line {ln}: invalid JSON ({e})")
                continue
            if not isinstance(obj, dict):
                errors.append(f"line {ln}: expected a JSON object")
                continue
            objs.append((ln, obj))
    if kind == "auto":
        kind = sniff(objs)
    if kind not in _CHECKERS:
        raise ValueError(f"unknown dialect {kind!r}")
    return errors + _CHECKERS[kind](objs)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m cocoa_tpu.telemetry.schema FILE...",
              file=sys.stderr)
        return 2
    bad = 0
    for path in argv:
        errs = check_file(path)
        if errs:
            bad += 1
            print(f"{path}: {len(errs)} schema violation(s)")
            for e in errs[:20]:
                print(f"  {e}")
        else:
            print(f"{path}: ok")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
