"""Gang-wide span tracing: what phase, on which worker, burned the time.

The event bus (telemetry/events.py) answers *what happened* — evals,
backoffs, resizes.  This module answers *where the wall-clock went*: a
span is one timed phase execution (an ingest pass, a KV exchange, a
local-solve super-block, an eval window, a checkpoint save, a supervisor
generation), emitted through the bus as a typed ``span`` event when it
CLOSES.  The offline assembler (telemetry/trace_report.py) merges the
per-process span streams of a gang run into one timeline, exports a
Perfetto/Chrome trace, and attributes stragglers per worker × phase.

Design constraints, in order:

- **Zero perturbation.**  Spans are host-side bookkeeping around code
  that is already host-side (dispatch boundaries, file IO, KV waits);
  nothing a span does reads or writes device values, so a traced run's
  ``(w, α)`` and sched leaf are bit-identical to an untraced run — the
  same contract the PR-4 telemetry bridge carries, pinned the same way
  (tests/test_tracing.py).  The jaxlint ``span-hygiene`` rule
  (cocoa_tpu/analysis) enforces the corollary statically: a span
  enter/exit must never appear inside jit/lax bodies, where it would be
  a trace-time no-op at best and a host sync at worst.
- **Inert by default.**  ``span()`` on a disabled tracer yields a shared
  null context — one attribute read and no allocation beyond the
  contextmanager frame — so the instrumented call sites cost nothing on
  untraced runs.
- **Clock model** (docs/DESIGN.md "Observability"): durations come from
  ``time.monotonic()`` (immune to NTP steps mid-span); the placement of
  a span on the merged timeline comes from its wall-clock ``start_ts``
  (``time.time()`` at enter).  Cross-process alignment is therefore
  wall-clock-grade (NTP skew bounds it); per-span durations — what the
  critical path and straggler slack are computed from — are exact per
  process.  Within one process, nesting is tracked by a thread-local
  stack, so a span's ``parent_id`` names the span it ran inside (the
  KV gets inside an allgather inside a round).

Span event fields: ``phase`` (the instrument point's name), ``span_id``
/ ``parent_id`` (per-process, thread-safe counter), ``worker`` (the
process index the tracer was configured with), ``start_ts`` (wall),
``dur_s`` (monotonic), plus free-form attributes (``round``, ``path``,
``key``, ``generation``, ...) the call site tags on.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import threading
import time


class Tracer:
    """Process-global span source.  ``configure(enabled=True, worker=i)``
    arms it (the CLI does this under ``--trace``); ``span``/``traced``
    are the two instrumentation forms.  Spans are emitted through the
    process-global EventBus, so they ride the same JSONL sink, metrics
    writer, and flight-recorder ring as every other event — and an
    armed tracer with an inert bus emits nothing (one more cheap guard).
    """

    def __init__(self):
        self.enabled = False
        self.worker = None
        self._ids = itertools.count(1)
        self._local = threading.local()

    def configure(self, enabled: bool = True, worker=None) -> "Tracer":
        self.enabled = bool(enabled)
        if worker is not None:
            self.worker = int(worker)
        return self

    def reset(self):
        """Disarm and forget the worker tag + id counter (tests)."""
        self.enabled = False
        self.worker = None
        self._ids = itertools.count(1)
        self._local = threading.local()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, phase: str, **attrs):
        """Time one phase execution; emits the ``span`` event at exit.

        Yields the span id (or None when disabled).  The event is
        emitted even when the body raises — a phase that died mid-way
        is exactly what the flight recorder wants on its ring — with
        an ``error`` attribute naming the exception type.
        """
        if not self.enabled:
            yield None
            return
        from cocoa_tpu.telemetry import events as _events

        bus = _events.get_bus()
        if not bus.active():
            yield None
            return
        sid = next(self._ids)
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(sid)
        start_ts = time.time()
        t0 = time.monotonic()
        err = None
        try:
            yield sid
        except BaseException as e:
            err = type(e).__name__
            raise
        finally:
            dur = time.monotonic() - t0
            stack.pop()
            fields = dict(phase=str(phase), span_id=sid, parent_id=parent,
                          worker=self.worker, start_ts=start_ts,
                          dur_s=dur, **attrs)
            if err is not None:
                fields["error"] = err
            bus.emit("span", **fields)

    def traced(self, phase: str, **attrs):
        """Decorator form: ``@tracer.traced("checkpoint_save")``."""
        def deco(fn):
            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                with self.span(phase, **attrs):
                    return fn(*args, **kwargs)
            return wrapper
        return deco


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer every instrument point shares."""
    return _TRACER


def configure(enabled: bool = True, worker=None) -> Tracer:
    return _TRACER.configure(enabled=enabled, worker=worker)


def span(phase: str, **attrs):
    """Module-level convenience: ``with tracing.span("eval", round=t):``"""
    return _TRACER.span(phase, **attrs)


def traced(phase: str, **attrs):
    """Module-level convenience decorator."""
    return _TRACER.traced(phase, **attrs)


def reset():
    """Disarm the process-global tracer (tests)."""
    _TRACER.reset()
