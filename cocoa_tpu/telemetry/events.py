"""The host-side event bus and the device→host event bridge.

One process-global :class:`EventBus` carries every run's structured
telemetry: typed records appended to a JSONL sink (one JSON object per
line, ``seq``-ordered) and fanned out synchronously to subscribers (the
metrics writer, the round-windowed profiler, tests).  The bus is inert
until configured — ``emit`` on an inactive bus is a no-op costing one
attribute read, so the training hot paths carry no telemetry tax by
default.

The device bridge: the device-resident driver (solvers/base.py
``drive_on_device``) computes one ``[primal, gap, test_err, sigma_stage,
stall]`` row per eval inside its ``lax.while_loop``.  With the bus
active, an **ordered** ``jax.experimental.io_callback`` posts each row to
:func:`_device_sink` WHILE THE LOOP IS STILL ON DEVICE — the host sees
``round_eval`` (and decoded ``sigma_backoff``) events live, in eval
order.  Where ordered callbacks are unavailable (probed once per process
by :func:`io_callback_supported`), the driver replays the SAME rows
through the SAME :class:`DeviceTap` from its end-of-run fetch — the
fallback emits bit-identical events, just late.  Either way the callback
only reads values the loop already computes: the loop-carried state is
untouched, so telemetry cannot perturb the run.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import math
import os
import threading
import time

import numpy as np

EVENT_TYPES = (
    "run_start",        # manifest: full config + config hash + jax/device info
    "round_eval",       # one debugIter-cadence evaluation
    "sigma_backoff",    # the σ′ anneal schedule backed off a stage
    "checkpoint_write", # a round-stamped checkpoint landed on disk
    "restart",          # sigma=auto trial rerun, or an elastic gang restart
    "divergence",       # the stall watch bailed the run out
    "run_end",          # final summary (primal, gap, stopped reason)
    "compile",          # one finished XLA compile (analysis/sanitize.py
                        # bridge) — the compile-once invariant, observable
    "host_transfer",    # one sanctioned device→host fetch (intended_fetch)
    "momentum_restart", # --accel: a gap rise reset the outer momentum
    "theta_stage",      # --accel: the Θ local-accuracy ladder stepped up
    "ingest",           # one loaded LIBSVM file (data/ingest.IngestReport:
                        # mode, parse seconds, bytes read, rows/nnz this
                        # process materialized, peak host RSS, and the
                        # --ingestCache outcome: off|hit|partial|miss)
    "ingest_cache",     # one file's --ingestCache outcome in detail
                        # (data/slab_cache.py, docs/DESIGN.md §18):
                        # shards served warm vs total, bytes mapped,
                        # seconds the cache saved — what feeds
                        # cocoa_ingest_cache_hits_total /
                        # cocoa_ingest_cache_bytes
    "ingest_cache_corrupt",  # a cache artifact failed validation on
                        # load (torn/truncated/drifted file): the
                        # artifact is evicted and the shard falls back
                        # to a cold parse — never a crash, never a
                        # silently wrong slab
    "gang_resize",      # the elastic supervisor reformed the gang at
                        # P′ < P survivors (shrink-to-survivors,
                        # cocoa_tpu/elastic.py, docs/DESIGN.md §13)
    "checkpoint_corrupt",  # a checkpoint generation failed validation on
                        # load; the reader fell back to the previous one
                        # (checkpoint.latest)
    "span",             # one closed tracing span (telemetry/tracing.py):
                        # phase + worker + wall start + monotonic
                        # duration + call-site attributes — what
                        # trace_report.py assembles into the gang
                        # timeline / critical path / straggler table
    "events_rotate",    # the JSONL sink hit its size cap and rolled the
                        # full file to `<path>.1` (first event of the
                        # fresh file, so the rotation itself is in the
                        # machine-readable record)
    "comm_overlap",     # one joined overlapped exchange (--overlapComm,
                        # parallel/distributed.ExchangeHandle): hidden_s
                        # = exchange wall-clock that ran concurrently
                        # with the caller's compute, wait_s = the
                        # residual blocking wait at the join barrier
    "stale_join",       # a bounded-staleness contribution joined late
                        # (--staleRounds, solvers/cocoa.StaleJoinWindow):
                        # round r's Δw applied at round t = r +
                        # rounds_late, rounds_late <= S by construction
    "fleet_progress",   # one fleet eval boundary (--fleet,
                        # solvers/fleet.py): live tenant lanes +
                        # cumulative certifications; the final event of a
                        # fleet run also carries models_per_second —
                        # what feeds cocoa_fleet_tenants_active /
                        # cocoa_fleet_models_per_second
    "tenant_certified", # one tenant crossed its duality-gap target
                        # inside the fleet's vmapped loop — what feeds
                        # cocoa_tenants_certified_total
    "serve_request",    # one scored serving batch (--serve,
                        # serving/batcher.py): n real requests, the
                        # static bucket they padded into, fill ratio,
                        # queue vs device seconds, per-request latency
                        # max/mean, and the model round that answered —
                        # what feeds cocoa_serve_qps /
                        # cocoa_serve_latency_seconds /
                        # cocoa_serve_batch_fill_ratio
    "model_swap",       # the serving watcher published a new validated
                        # checkpoint generation into the live model slot
                        # (serving/watcher.py): round, path, certified
                        # gap, and the certificate's birth timestamp —
                        # what anchors cocoa_model_gap_age_seconds
    "model_quantize",   # one --serveDtype publish decision
                        # (serving/scorer.ModelSlots._publish): the
                        # configured serve dtype, the form actually
                        # published (== serve dtype, or f32 on a
                        # certificate fallback), the measured
                        # f32-vs-quantized margin-error bound over the
                        # calibration batch, its size, and the int8
                        # scale — what feeds
                        # cocoa_serve_margin_error_bound /
                        # cocoa_serve_dtype_fallbacks_total
    "serve_shed",       # the fleet router refused one request line at
                        # admission (serving/router.py): routing
                        # policy, the tenant (None when untagged), the
                        # best live replica's inflight depth and
                        # projected wait vs the SLA — what feeds
                        # cocoa_serve_shed_total
    "replica_state",    # one fleet replica liveness transition
                        # (serving/router.py / fleet.py): replica name,
                        # state (live / dead / requeue), live count
                        # after the transition, and whether a request
                        # line was requeued by it — what feeds
                        # cocoa_serve_replicas_live /
                        # cocoa_serve_requeue_total
    "query_trace",      # one sampled end-to-end query trace
                        # (--traceSample, docs/DESIGN.md §22): the
                        # client-chosen trace id plus per-hop seconds —
                        # router queue, forward (network + relay),
                        # replica admission queue, device dispatch,
                        # protocol parse/serialize — stamped with the
                        # answering model generation, its gap age, the
                        # serving dtype, the bucket, and how many times
                        # the line requeued.  Emitted by the router in
                        # fleet mode (it sees the whole lifecycle) and
                        # by the solo server otherwise — what feeds
                        # cocoa_query_traces_total and what
                        # trace_report --queries assembles into the
                        # per-hop waterfall
    "slo_status",       # one /slo evaluation (telemetry/aggregate.py):
                        # rolling SLA attainment over the fleet-wide
                        # latency histogram plus the fast/slow
                        # multi-window burn rates against the
                        # attainment objective — the ops plane's
                        # machine-readable answer to "is the fleet
                        # inside its SLA right now"
)


def _clean(v):
    """JSON-safe scalars: numpy numerics → python, NaN → None (JSON has no
    NaN; a NaN metric means 'not applicable' everywhere in this codebase)."""
    if isinstance(v, np.ndarray) and v.ndim == 0:
        v = v.item()
    if isinstance(v, np.floating):
        v = float(v)
    if isinstance(v, np.integer):
        v = int(v)
    if isinstance(v, float) and math.isnan(v):
        return None
    if isinstance(v, dict):
        return {k: _clean(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_clean(x) for x in v]
    return v


class EventBus:
    """Ordered, typed event stream with a JSONL sink and subscribers.

    ``emit`` is thread-safe: the device bridge fires from the runtime's
    callback thread while the main thread blocks on the run's host fetch.
    Subscriber callbacks run inline under the lock — they must be cheap
    (the metrics writer's atomic rewrite is ~µs at these event rates).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self.jsonl_path = None
        self.metrics_path = None
        self.metrics_writer = None   # the MetricsWriter configure()
        # attached (None otherwise) — owners that need more than the
        # subscriber protocol (the serving loop's gap-age heartbeat)
        # reach it here instead of poking _subscribers
        self.max_bytes = None
        self._subscribers = []
        self._seq = 0

    def configure(self, jsonl_path=None, metrics_path=None,
                  max_bytes=None, metrics_interval_s=0.0):
        """Attach sinks; either may be None.  The metrics path attaches a
        :class:`cocoa_tpu.telemetry.metrics.MetricsWriter` subscriber
        (``metrics_interval_s`` is its write-debounce window).  Any
        active sink also installs the compile→event bridge, so
        ``compiles_total``/``compile`` events come for free on telemetry
        runs (the sanitizer invariants, observable in production).

        ``max_bytes`` (``--eventsMaxMB``): size cap on the JSONL sink —
        when an append pushes the file past it, the full file atomically
        rolls to ``<path>.1`` (replacing any previous rollover) and the
        fresh file opens with a typed ``events_rotate`` event, so a
        long serving/elastic run holds at most ~2× the cap on disk
        instead of growing without bound."""
        with self._lock:
            self.jsonl_path = jsonl_path or None
            if max_bytes is not None:
                self.max_bytes = int(max_bytes) or None
            if metrics_path and metrics_path != self.metrics_path:
                from cocoa_tpu.telemetry.metrics import MetricsWriter

                self.metrics_writer = MetricsWriter(
                    metrics_path, flush_interval_s=metrics_interval_s)
                self.subscribe(self.metrics_writer)
                self.metrics_path = metrics_path
        if self.active():
            from cocoa_tpu.analysis import sanitize

            sanitize.install_compile_events(self)
        return self

    def active(self) -> bool:
        return bool(self.jsonl_path or self._subscribers)

    def subscribe(self, fn):
        with self._lock:
            self._subscribers.append(fn)
        return fn

    def unsubscribe(self, fn):
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    def reset(self):
        """Detach every sink and zero the sequence (tests)."""
        with self._lock:
            self.jsonl_path = None
            self.metrics_path = None
            if self.metrics_writer is not None:
                self.metrics_writer.stop_heartbeat()
            self.metrics_writer = None
            self.max_bytes = None
            self._subscribers = []
            self._seq = 0

    def emit(self, event: str, **fields):
        """Append one typed record; returns it (or None when inactive).

        The record is sanitized ONCE (numpy scalars → python, NaN → None)
        so the JSONL line and every subscriber see identical values — the
        io_callback-path vs fetch-fallback parity the tests pin rests on
        this single normalization point."""
        if not self.active():
            return None
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}; "
                             f"expected one of {EVENT_TYPES}")
        reserved = {"event", "seq", "pid", "ts"} & fields.keys()
        if reserved:
            # a payload field named like the envelope would silently
            # overwrite it — the model_swap 'seq' collision class of bug
            raise ValueError(f"event field(s) {sorted(reserved)} collide "
                             f"with the record envelope; rename them")
        with self._lock:
            self._seq += 1
            # pid identifies the EMITTER: a supervised run interleaves
            # several processes' appends (elastic supervisor + worker
            # generations, each with its own seq counter) in one JSONL,
            # and the schema checker orders per emitter
            rec = {"event": event, "seq": self._seq, "pid": os.getpid(),
                   "ts": time.time(),
                   **{k: _clean(v) for k, v in fields.items()}}
            rotated = None
            if self.jsonl_path:
                # open-append per event: whole-line writes interleave
                # safely with other emitters of the same file (the elastic
                # supervisor appends restart events between generations)
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    size = f.tell()
                if (self.max_bytes and size >= self.max_bytes
                        and event != "events_rotate"):
                    rotated = self._rotate(size)
            for fn in list(self._subscribers):
                fn(rec)
            if rotated is not None:
                for fn in list(self._subscribers):
                    fn(rotated)
        return rec

    def _rotate(self, size: int):
        """Roll the full JSONL sink to ``<path>.1`` (atomic rename,
        replacing any previous rollover — the cap bounds disk at ~2×,
        it does not archive history) and open the fresh file with a
        typed ``events_rotate`` record.  Caller holds the lock.

        Concurrent emitters: each shared file has exactly ONE rotating
        owner (cli.py arms ``max_bytes`` on the workers only — the
        supervisor appends to worker 0's file uncapped), so the re-stat
        below is a belt-and-suspenders guard, not the coordination
        mechanism: if the file on disk is already below the cap, some
        other process rotated between our append and now — renaming
        again would clobber the just-archived ``.1`` with a near-empty
        fresh file."""
        rolled = self.jsonl_path + ".1"
        try:
            if os.path.getsize(self.jsonl_path) < self.max_bytes:
                return None
            os.replace(self.jsonl_path, rolled)
        except OSError:
            return None  # the file vanished under us — nothing to roll
        self._seq += 1
        rec = {"event": "events_rotate", "seq": self._seq,
               "pid": os.getpid(), "ts": time.time(),
               "path": self.jsonl_path, "rotated_to": rolled,
               "bytes": int(size)}
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec


_BUS = EventBus()


def get_bus() -> EventBus:
    """The process-global bus every emitter and sink shares."""
    return _BUS


# --- run manifest -----------------------------------------------------------


def config_hash(config: dict) -> str:
    """Stable short hash of a config mapping (the run's identity in the
    manifest and the trajectory header)."""
    blob = json.dumps(_clean(config), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def environment_manifest() -> dict:
    """jax/device provenance for the run manifest.  Requires the backend
    to be selected already (callers emit after CLI setup)."""
    import jax

    dev = jax.devices()[0]
    return {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "process_count": jax.process_count(),
    }


def run_manifest(config: dict, dataset=None) -> dict:
    """The ``run_start`` payload: the full config, its hash, and the
    jax/device environment."""
    return {
        "dataset": dataset,
        "config": _clean(config),
        "config_hash": config_hash(config),
        **environment_manifest(),
    }


# --- the device bridge ------------------------------------------------------

_IO_CALLBACK_OK = None


def io_callback_supported() -> bool:
    """Whether ordered ``io_callback`` works inside a jitted
    ``lax.while_loop`` on this jax/backend (probed once per process with a
    trivial three-iteration loop).  When False, the device driver falls
    back to replaying events from its end-of-run fetch — same events,
    same values, just not live."""
    global _IO_CALLBACK_OK
    if _IO_CALLBACK_OK is None:
        try:
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.experimental import io_callback

            seen = []

            def probe(x):
                def body(s):
                    i, x = s
                    io_callback(lambda i, v: seen.append(int(i)), None,
                                i, x, ordered=True)
                    return i + 1, x + 1.0
                return lax.while_loop(lambda s: s[0] < 3, body,
                                      (jnp.int32(0), x))

            jax.jit(probe)(jnp.float32(0.0))[0].block_until_ready()
            jax.effects_barrier()
            _IO_CALLBACK_OK = seen == [0, 1, 2]
        except Exception:
            _IO_CALLBACK_OK = False
    return _IO_CALLBACK_OK


_DEVICE_TAP = None


def _device_sink(i, row):
    """The io_callback target: forward one eval row to the installed tap.
    A row arriving with no tap installed (e.g. a cached executable rerun
    outside a telemetry context) is dropped — side-effect-only either way."""
    tap = _DEVICE_TAP
    if tap is not None:
        tap(i, row)


@contextlib.contextmanager
def device_tap(tap):
    """Install ``tap`` as the destination for in-flight device events for
    the duration of one dispatch+fetch.  Runs are sequential within a
    process (the driver's fetch joins the loop before returning), so a
    single slot suffices."""
    global _DEVICE_TAP
    prev = _DEVICE_TAP
    _DEVICE_TAP = tap
    try:
        yield tap
    finally:
        _DEVICE_TAP = prev


class DeviceTap:
    """Decode device eval rows into bus events.

    One instance serves BOTH bridge paths — the live io_callback stream
    and the end-of-run fetch replay feed rows through the same
    ``__call__`` — so the two paths emit identical events by construction
    (the parity the tests pin).

    Row layout (solvers/base.py ``_build_device_run``):
    ``[primal, gap, test_err, sigma_stage, stall, theta_stage,
    restarts]`` — gap/test_err NaN when not applicable, sigma_stage NaN
    outside σ′-anneal runs, theta_stage/restarts NaN outside ``--accel``
    runs (and absent entirely on pre-widening 5-col rows, which decode
    unchanged).

    ``init_stage`` / ``init_theta_stage`` / ``init_restarts`` seed
    transition detection with the values the state ENTERED this dispatch
    at (the sched leaf rides super-block boundaries), so a resumed or
    multi-block run never fabricates a backoff / Θ-step / restart event
    for its first eval.
    """

    def __init__(self, bus, algorithm: str, start_round: int, cadence: int,
                 sigma_levels=None, init_stage=None, theta_hs=None,
                 init_theta_stage=None, init_restarts=None):
        self.bus = bus
        self.algorithm = algorithm
        self.start_round = start_round
        self.cadence = cadence
        self.levels = sigma_levels
        self._prev_stage = init_stage
        self.theta_hs = theta_hs
        self._prev_theta = init_theta_stage
        self._prev_restarts = init_restarts
        self.count = 0

    def __call__(self, i, row):
        # jaxlint: allow=f64 -- host-side decode of an already-fetched f32
        # row; never enters device compute
        r = np.asarray(row, dtype=np.float64)
        t = self.start_round - 1 + (int(i) + 1) * self.cadence
        primal, gap, test_err, stage_f, stall = (float(v) for v in r[:5])
        stage = None if math.isnan(stage_f) else int(stage_f)
        sigma = (self.levels[stage]
                 if self.levels is not None and stage is not None else None)
        self.bus.emit(
            "round_eval", algorithm=self.algorithm, t=t, primal=primal,
            gap=gap, test_error=test_err, sigma=sigma, sigma_stage=stage,
            stall=None if math.isnan(stall) else int(stall),
        )
        if (stage is not None and self._prev_stage is not None
                and stage != self._prev_stage):
            self.bus.emit(
                "sigma_backoff", algorithm=self.algorithm, t=t,
                sigma=sigma, from_sigma=self.levels[self._prev_stage],
                stage=stage,
            )
        if stage is not None:
            self._prev_stage = stage
        if r.shape[0] >= 7:
            theta_f, restarts_f = float(r[5]), float(r[6])
            theta = None if math.isnan(theta_f) else int(theta_f)
            restarts = None if math.isnan(restarts_f) else int(restarts_f)
            if (restarts is not None and self._prev_restarts is not None
                    and restarts > self._prev_restarts):
                self.bus.emit("momentum_restart", algorithm=self.algorithm,
                              t=t, restarts_total=restarts)
            if restarts is not None:
                self._prev_restarts = restarts
            if (theta is not None and self._prev_theta is not None
                    and theta != self._prev_theta):
                self.bus.emit(
                    "theta_stage", algorithm=self.algorithm, t=t,
                    stage=theta,
                    h=(self.theta_hs[theta]
                       if self.theta_hs is not None else None))
            if theta is not None:
                self._prev_theta = theta
        self.count += 1
