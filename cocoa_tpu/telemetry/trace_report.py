"""Offline gang-trace assembly: merge per-process span streams, export a
Perfetto/Chrome trace, compute the per-round critical path, attribute
stragglers.

A gang run leaves one event JSONL per process (worker 0 owns
``<events>``, worker p owns ``<events>.p<p>`` — cli.py), each carrying
that process's ``span`` events (telemetry/tracing.py).  This module is
the postmortem/analysis half:

    python -m cocoa_tpu.telemetry.trace_report run/events.jsonl \\
        run/events.jsonl.p1 --trace=run/trace.json \\
        --metrics=run/straggler.prom

- **merge** — spans from every stream on one wall-clock timeline (the
  clock model: placement by wall ``start_ts``, duration by per-process
  monotonic ``dur_s`` — see tracing.py and docs/DESIGN.md).
- **Perfetto export** (``--trace``) — Chrome trace-event JSON, one
  process track per worker, loadable at https://ui.perfetto.dev (or
  ``chrome://tracing``).  :func:`check_chrome_trace` validates the
  structure — the same check the tests and CI run on the artifact.
- **per-round critical path** — spans inherit their round from the
  nearest enclosing span that carries a ``round`` attribute; per round
  and phase the gang can only advance at the SLOWEST worker, so the
  round's critical path is, for each phase, the max-across-workers
  duration (and which worker set it).  Under an elastic resize the
  worker set simply changes between rounds — each round's path is
  computed over the workers that actually reported it.
- **straggler attribution** — for each (round, phase), the time the
  gang lost waiting on worker w is ``max(0, dur_w - max(others))``:
  nonzero only for the slowest worker, and exactly the wall-clock the
  phase would have saved had w kept pace.  Summed over rounds and
  ranked, worker × phase: the table's top row IS the straggler.  The
  same numbers render as ``cocoa_phase_seconds{worker,phase}`` and
  ``cocoa_straggler_slack_seconds{worker,phase}`` gauges (``--metrics``)
  for dashboards that already scrape the run's textfiles.
- **query waterfall** (``--queries``) — assemble the sampled
  ``query_trace`` events (--traceSample, docs/DESIGN.md §22) into a
  per-hop p50/p99 waterfall over the serving pipeline — router queue /
  forward / replica queue / device / serialize — and name the DOMINANT
  hop (largest p99): the one answer a latency incident needs first.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

_NUM = (int, float)


# --- loading + round attribution -------------------------------------------


def load_spans(paths) -> list:
    """Every ``span`` record from the given JSONL files (event streams,
    rotated ``.1`` files, flight-recorder dumps — any dialect whose
    lines are event records).  Unparseable lines are skipped: a stream
    torn by a SIGKILL is exactly the kind of input a postmortem tool
    must accept."""
    spans = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and obj.get("event") == "span":
                    spans.append(obj)
    attribute_rounds(spans)
    spans.sort(key=lambda s: (s.get("start_ts") or 0.0,
                              s.get("pid") or 0,
                              s.get("span_id") or 0))
    return spans


def worker_of(span: dict):
    """The worker identity a span is attributed to: the tracer's
    configured process index, falling back to the emitter pid (spans
    from a tracer configured without a worker tag)."""
    w = span.get("worker")
    return w if w is not None else span.get("pid")


def attribute_rounds(spans) -> None:
    """Set ``_round`` on every span — its own ``round`` attribute, else
    the nearest ancestor's (the KV gets inside an allgather inside a
    ``round`` span all belong to that round) — and ``_bg``, whether the
    span ran on an overlapped BACKGROUND collector (its own or an
    ancestor's ``overlapped`` attribute — what
    ``async_host_allgather_bytes`` tags its collector spans with; the
    charged-seconds accounting makes background spans yield the
    wall-clock to concurrent foreground work).  Parent chains are
    per-process (span ids restart per process/generation), so the walk
    keys on (pid, span_id)."""
    by_id = {(s.get("pid"), s.get("span_id")): s for s in spans}
    for s in spans:
        node, r, bg, hops = s, None, False, 0
        while node is not None and hops < 64:
            if r is None and node.get("round") is not None:
                r = int(node["round"])
            if node.get("overlapped") or node.get("background"):
                bg = True
            node = by_id.get((node.get("pid"), node.get("parent_id")))
            hops += 1
        s["_round"] = r
        s["_bg"] = bg


# --- Perfetto / Chrome trace export ----------------------------------------

_RESERVED = frozenset((
    "event", "seq", "pid", "ts", "phase", "span_id", "parent_id",
    "worker", "start_ts", "dur_s", "_round", "_bg",
))


def chrome_trace(spans) -> dict:
    """Chrome trace-event JSON: complete ('X') events on one process
    track per worker, one thread track per OS process (so the
    generations of an elastic run appear as successive threads of the
    same worker).  Timestamps are microseconds of wall clock."""
    events = []
    named = set()
    for s in spans:
        w = worker_of(s)
        if w is None or s.get("start_ts") is None:
            continue
        if w not in named:
            named.add(w)
            events.append({"ph": "M", "name": "process_name", "pid": int(w),
                           "tid": 0, "args": {"name": f"worker {w}"}})
        args = {k: v for k, v in s.items()
                if k not in _RESERVED and v is not None}
        if s.get("_round") is not None:
            args["round"] = s["_round"]
        events.append({
            "name": str(s.get("phase")),
            "cat": "cocoa",
            "ph": "X",
            "ts": float(s["start_ts"]) * 1e6,
            "dur": max(float(s.get("dur_s") or 0.0), 0.0) * 1e6,
            "pid": int(w),
            "tid": int(s.get("pid") or 0),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def check_chrome_trace(obj) -> list:
    """Structural validation of an exported trace (what the tests and CI
    assert on the artifact); returns error strings."""
    errors = []
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        return ["trace must be an object with a traceEvents list"]
    for i, e in enumerate(obj["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        if e.get("ph") not in ("X", "M"):
            errors.append(f"{where}: unsupported phase {e.get('ph')!r}")
            continue
        for field in ("pid", "tid"):
            if not isinstance(e.get(field), int):
                errors.append(f"{where}: missing/invalid {field}")
        if not isinstance(e.get("name"), str):
            errors.append(f"{where}: missing/invalid name")
        if e["ph"] == "X":
            for field in ("ts", "dur"):
                v = e.get(field)
                if not isinstance(v, _NUM) or isinstance(v, bool):
                    errors.append(f"{where}: missing/invalid {field}")
            if isinstance(e.get("dur"), _NUM) and e["dur"] < 0:
                errors.append(f"{where}: negative dur")
    return errors


# --- critical path + stragglers --------------------------------------------


def _charge_spans(intervals, background) -> list:
    """Partition one worker's wall-clock among its (possibly
    overlapping) leaf spans.  Each second of the intervals' union is
    charged to exactly one covering span: FOREGROUND spans (the
    worker's main thread) beat BACKGROUND ones (an ``--overlapComm``
    collector daemon, ``_bg``), and within a class the latest-started
    covering span owns the second (ties broken by list order).
    Returns per-interval charged seconds.

    Rationale (docs/DESIGN.md §15): a collector's KV gets run
    CONCURRENTLY with the main thread's next local-solve — a worker
    owns at most wall-clock seconds of wall-clock, so summing
    overlapped leaves would double-count hidden exchange time straight
    into the critical path and the slack table.  Foreground-beats-
    background charges the compute (or the ``exchange_join`` wait, once
    the thread actually blocks) and shadows the hidden exchange to ~0 —
    which is exactly what "hidden" means; latest-started-owns within a
    class makes same-phase re-entries charge their union.  Disjoint
    spans (every pre-overlap run) are charged their full durations —
    bit-identical to the old per-phase sums."""
    n = len(intervals)
    events = []
    for i, (s0, s1) in enumerate(intervals):
        events.append((s0, 0, i))
        events.append((max(s0, s1), 1, i))
    events.sort(key=lambda e: (e[0], e[1]))
    charged = [0.0] * n
    active: dict = {}
    prev = None
    for t, kind, i in events:
        if prev is not None and active and t > prev:
            fg = [j for j in active if not background[j]]
            pool = fg if fg else list(active)
            owner = max(pool, key=lambda j: (intervals[j][0], j))
            charged[owner] += t - prev
        prev = t
        if kind == 0:
            active[i] = True
        else:
            active.pop(i, None)
    return charged


def _per_round_phase_durs(spans) -> dict:
    """{round: {phase: {worker: seconds}}} over round-attributed LEAF
    spans.  Container spans — those with recorded children, like the
    ``round`` wrapper or an allgather whose gets were traced — are
    excluded: counting both a parent and its children would double
    every nested second in the critical path and the slack totals.  The
    Perfetto export keeps the full hierarchy.

    Per worker, concurrent leaf spans (a phase re-entered several times
    per round is fine; ``--overlapComm`` collector gets riding
    alongside the main thread are the interesting case) share the
    wall-clock via :func:`_charge_spans` — each second charged to the
    latest-started covering span — and the charged seconds then
    aggregate into (round, phase) cells.  Spans missing a ``start_ts``
    (torn streams) fall back to their full duration."""
    containers = {(s.get("pid"), s.get("parent_id"))
                  for s in spans if s.get("parent_id") is not None}
    leaves: dict = {}   # worker -> [span, ...]
    for s in spans:
        if (s.get("pid"), s.get("span_id")) in containers:
            continue
        r, w = s.get("_round"), worker_of(s)
        if r is None or w is None or s.get("dur_s") is None:
            continue
        leaves.setdefault(w, []).append(s)
    table: dict = {}
    for w, ss in leaves.items():
        timed = [s for s in ss if s.get("start_ts") is not None]
        charged = _charge_spans(
            [(float(s["start_ts"]),
              float(s["start_ts"]) + max(0.0, float(s["dur_s"])))
             for s in timed],
            [bool(s.get("_bg")) for s in timed])
        pairs = list(zip(timed, charged)) + [
            (s, max(0.0, float(s["dur_s"])))
            for s in ss if s.get("start_ts") is None]
        for s, d in pairs:
            ph = str(s.get("phase"))
            cell = table.setdefault(s["_round"], {}).setdefault(ph, {})
            cell[w] = cell.get(w, 0.0) + d
    return table


def critical_path(spans) -> list:
    """One entry per round: which (phase, worker) durations bound the
    gang.  ``entries`` lists every phase's slowest worker and duration;
    ``critical_s`` is their sum — the floor on the round's wall-clock
    no matter how fast the other workers run."""
    out = []
    table = _per_round_phase_durs(spans)
    for r in sorted(table):
        phases = table[r]
        entries = []
        for ph in sorted(phases):
            durs = phases[ph]
            worker = max(durs, key=lambda w: (durs[w], str(w)))
            entries.append({"phase": ph, "worker": worker,
                            "dur_s": durs[worker],
                            "workers": len(durs)})
        out.append({"round": r, "entries": entries,
                    "critical_s": sum(e["dur_s"] for e in entries)})
    return out


def stragglers(spans) -> list:
    """Worker × phase rows ranked by cumulative slack — the wall-clock
    the gang lost waiting on that worker in that phase (see module
    docstring for the definition).  Rows exist for every participating
    (worker, phase) pair, so a balanced gang still yields a table (with
    ~zero slack) and the top row always names the straggler."""
    slack: dict = {}
    seconds: dict = {}
    rounds: dict = {}
    for r, phases in _per_round_phase_durs(spans).items():
        for ph, durs in phases.items():
            for w, d in durs.items():
                key = (w, ph)
                seconds[key] = seconds.get(key, 0.0) + d
                rounds[key] = rounds.get(key, 0) + 1
                others = [v for ow, v in durs.items() if ow != w]
                lost = max(0.0, d - max(others)) if others else 0.0
                slack[key] = slack.get(key, 0.0) + lost
    rows = [{"worker": w, "phase": ph, "slack_s": slack[(w, ph)],
             "phase_s": seconds[(w, ph)], "rounds": rounds[(w, ph)]}
            for (w, ph) in slack]
    rows.sort(key=lambda row: (-row["slack_s"], -row["phase_s"],
                               str(row["worker"]), row["phase"]))
    return rows


def metrics_text(spans) -> str:
    """The straggler numbers in the Prometheus textfile format, labeled
    worker × phase — droppable next to the run's ``--metrics`` files."""
    rows = stragglers(spans)
    lines = ["# TYPE cocoa_phase_seconds gauge"]
    for row in sorted(rows, key=lambda r: (str(r["worker"]), r["phase"])):
        lines.append(
            f'cocoa_phase_seconds{{worker="{row["worker"]}",'
            f'phase="{row["phase"]}"}} {row["phase_s"]!r}')
    lines.append("# TYPE cocoa_straggler_slack_seconds gauge")
    for row in sorted(rows, key=lambda r: (str(r["worker"]), r["phase"])):
        lines.append(
            f'cocoa_straggler_slack_seconds{{worker="{row["worker"]}",'
            f'phase="{row["phase"]}"}} {row["slack_s"]!r}')
    return "\n".join(lines) + "\n"


# --- query waterfall (--queries) --------------------------------------------

# the serving pipeline's hops, in traversal order (query_trace fields);
# solo-server traces carry None for the router-side hops and simply
# contribute nothing to those rows
QUERY_HOPS = ("router_queue_s", "forward_s", "replica_queue_s",
              "device_s", "serialize_s")


def load_query_traces(paths) -> list:
    """Every ``query_trace`` record from the given JSONL streams (same
    torn-stream tolerance as :func:`load_spans`)."""
    traces = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) \
                        and obj.get("event") == "query_trace":
                    traces.append(obj)
    traces.sort(key=lambda t: (t.get("ts") or 0.0, t.get("pid") or 0,
                               t.get("seq") or 0))
    return traces


def _percentile(values, q: float) -> float:
    """Nearest-rank percentile over a non-empty list."""
    vs = sorted(values)
    k = max(0, min(len(vs) - 1, int(round(q * (len(vs) - 1)))))
    return vs[k]


def query_waterfall(traces) -> dict:
    """The per-hop latency waterfall over sampled query traces:
    ``{"traces", "hops": {hop: {n, p50_s, p99_s, mean_s}}, "total":
    {...}, "dominant_hop", "requeued", "replicas"}``.  The dominant hop
    is the largest p99 — the tail is what an SLA pages on, so the hop
    that owns the tail is the hop to fix."""
    hops = {}
    for hop in QUERY_HOPS:
        vals = [float(t[hop]) for t in traces
                if t.get(hop) is not None]
        if vals:
            hops[hop] = {"n": len(vals),
                         "p50_s": _percentile(vals, 0.50),
                         "p99_s": _percentile(vals, 0.99),
                         "mean_s": sum(vals) / len(vals)}
    totals = [float(t["total_s"]) for t in traces
              if t.get("total_s") is not None]
    dominant = (max(hops, key=lambda h: hops[h]["p99_s"])
                if hops else None)
    replicas = {}
    for t in traces:
        rep = t.get("replica")
        if rep is not None:
            replicas[rep] = replicas.get(rep, 0) + 1
    return {
        "traces": len(traces),
        "hops": hops,
        "total": ({"n": len(totals),
                   "p50_s": _percentile(totals, 0.50),
                   "p99_s": _percentile(totals, 0.99),
                   "mean_s": sum(totals) / len(totals)}
                  if totals else None),
        "dominant_hop": dominant,
        "requeued": sum(int(t.get("requeues") or 0) for t in traces),
        "replicas": replicas,
    }


def render_queries(wf: dict) -> str:
    """The waterfall as a fixed-width table plus the dominant-hop
    verdict — the human half of ``--queries`` (the dict itself is the
    machine half serve_bench reads)."""
    lines = [f"query traces: {wf['traces']} sampled"
             + (f", {wf['requeued']} requeue(s) survived"
                if wf["requeued"] else "")
             + (", replicas " + ", ".join(
                 f"{r}={n}" for r, n in sorted(wf["replicas"].items()))
                if wf["replicas"] else "")]
    header = f"  {'hop':<16} {'n':>6} {'p50':>10} {'p99':>10} {'mean':>10}"
    lines.append(header)
    for hop in QUERY_HOPS:
        st = wf["hops"].get(hop)
        if st is None:
            continue
        mark = "  <- dominant" if hop == wf["dominant_hop"] else ""
        lines.append(
            f"  {hop[:-2]:<16} {st['n']:>6} {st['p50_s']*1e3:>8.3f}ms "
            f"{st['p99_s']*1e3:>8.3f}ms {st['mean_s']*1e3:>8.3f}ms"
            f"{mark}")
    if wf["total"]:
        st = wf["total"]
        lines.append(
            f"  {'total':<16} {st['n']:>6} {st['p50_s']*1e3:>8.3f}ms "
            f"{st['p99_s']*1e3:>8.3f}ms {st['mean_s']*1e3:>8.3f}ms")
    if wf["dominant_hop"]:
        lines.append(f"dominant hop: {wf['dominant_hop'][:-2]} "
                     f"(p99 {wf['hops'][wf['dominant_hop']]['p99_s']*1e3:.3f}ms)")
    return "\n".join(lines)


# --- CLI --------------------------------------------------------------------


def render_report(spans, top: int = 10) -> str:
    path = critical_path(spans)
    rows = stragglers(spans)
    workers = sorted({worker_of(s) for s in spans
                      if worker_of(s) is not None}, key=str)
    lines = [f"spans: {len(spans)} from {len(workers)} worker(s) "
             f"{workers}, {len(path)} attributed round(s)"]
    if path:
        total = sum(p["critical_s"] for p in path)
        lines.append(f"critical path: {total:.6f}s over "
                     f"{len(path)} round(s)")
        slowest = max(path, key=lambda p: p["critical_s"])
        lines.append(
            f"  slowest round {slowest['round']}: "
            f"{slowest['critical_s']:.6f}s — "
            + ", ".join(f"{e['phase']}={e['dur_s']:.6f}s(w{e['worker']})"
                        for e in slowest["entries"]))
    if rows:
        lines.append(f"stragglers (top {min(top, len(rows))} of "
                     f"{len(rows)} worker x phase rows, by slack):")
        for row in rows[:top]:
            lines.append(
                f"  worker {row['worker']} x {row['phase']}: "
                f"slack {row['slack_s']:.6f}s over {row['rounds']} "
                f"round(s) (own time {row['phase_s']:.6f}s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    inputs, trace_out, metrics_out, top = [], None, None, 10
    queries = False
    for a in argv:
        if a.startswith("--trace="):
            trace_out = a.split("=", 1)[1]
        elif a.startswith("--metrics="):
            metrics_out = a.split("=", 1)[1]
        elif a.startswith("--top="):
            top = int(a.split("=", 1)[1])
        elif a == "--queries":
            queries = True
        elif a.startswith("-"):
            print(f"unknown flag {a!r}", file=sys.stderr)
            return 2
        else:
            inputs.append(a)
    if not inputs:
        print("usage: python -m cocoa_tpu.telemetry.trace_report "
              "EVENTS.jsonl [EVENTS.jsonl.p1 ...] [--trace=OUT.json] "
              "[--metrics=OUT.prom] [--top=N] [--queries]",
              file=sys.stderr)
        return 2
    missing = [p for p in inputs if not os.path.exists(p)]
    if missing:
        print(f"no such file(s): {missing}", file=sys.stderr)
        return 2
    if queries:
        traces = load_query_traces(inputs)
        if not traces:
            print("no query_trace events in the given streams (was the "
                  "server run with --traceSample and trace=-prefixed "
                  "queries?)", file=sys.stderr)
            return 1
        print(render_queries(query_waterfall(traces)))
        return 0
    spans = load_spans(inputs)
    if not spans:
        print("no span events in the given streams (was the run traced? "
              "pass --trace to the CLI)", file=sys.stderr)
        return 1
    if trace_out:
        trace = chrome_trace(spans)
        errs = check_chrome_trace(trace)
        if errs:  # self-check: never ship an artifact Perfetto rejects
            print(f"internal error: exported trace failed validation: "
                  f"{errs[:5]}", file=sys.stderr)
            return 1
        with open(trace_out, "w") as f:
            json.dump(trace, f)
        print(f"wrote {trace_out} ({len(trace['traceEvents'])} events) — "
              f"open at https://ui.perfetto.dev")
    if metrics_out:
        with open(metrics_out, "w") as f:
            f.write(metrics_text(spans))
        print(f"wrote {metrics_out}")
    print(render_report(spans, top=top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
