"""Crash flight recorder: the last N events, saved when a run dies.

The event JSONL answers postmortems only when the events that explain a
death actually reached disk — and the most interesting deaths are
exactly the ones that interrupt the story: an unhandled exception mid
phase, a SIGTERM from a preempting scheduler, a SIGKILL the process
never sees.  This module closes that gap from both sides:

- **In-process** (:class:`FlightRecorder`): a bounded ring of the most
  recent events + spans, kept as a plain bus subscriber.  On a
  ``divergence`` event, an unhandled exception (``sys.excepthook``), or
  SIGTERM, the ring is dumped ATOMICALLY (temp + rename) to
  ``<events>.flightrec`` — one ``flightrec_manifest`` header line
  (reason, pid, ring size) plus the ring's records, validated by
  ``telemetry/schema.py`` as its own dialect.  The ring works even when
  no JSONL sink is configured (subscribing it activates the bus), so
  every worker of a gang has a recorder regardless of which worker owns
  the shared event file.
- **Supervisor-side** (:func:`dump_victim`): a SIGKILLed worker cannot
  dump anything — but its events were streaming to its per-process
  JSONL the whole time.  When the elastic supervisor observes a worker
  exit nonzero (cocoa_tpu/elastic.py), it reads the tail of the
  victim's stream and writes the same ``.flightrec`` artifact on the
  victim's behalf: a chaos kill yields an explanation (which phase,
  which round, what the last exchanges were), not just a ``gang_resize``
  event.

The dump path convention: ``<stream>.flightrec`` next to the stream it
explains.  Dumps overwrite (atomic replace): the recorder keeps the
LATEST explanation, it does not archive history — the events JSONL is
the archive.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import time
from typing import Optional

# ring capacity: enough to hold several rounds of a traced gang run
# (round span + KV exchanges + checkpoint writes per round) while keeping
# the dump a glance-sized artifact
DEFAULT_CAPACITY = 256


def _atomic_write_jsonl(path: str, records) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    os.replace(tmp, path)
    return path


class FlightRecorder:
    """Bounded ring of recent bus events; ``dump()`` writes the
    postmortem artifact.  Subscribe it to the bus (``install`` does, and
    wires the dump triggers)."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY):
        self.path = path
        self.capacity = int(capacity)
        self.ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.dumps: list = []          # (reason, path) of every dump fired

    def __call__(self, rec: dict):
        """Bus subscriber: every event rides the ring; a ``divergence``
        event triggers an immediate dump (the run is about to bail out
        with the console possibly silenced — the ring IS the context)."""
        self.ring.append(dict(rec))
        if rec.get("event") == "divergence":
            self.dump("divergence")

    def dump(self, reason: str, **extra) -> Optional[str]:
        """Write the ring to ``self.path`` (atomic; overwrites the
        previous dump — latest explanation wins).  Never raises: the
        recorder must not turn a crash into a different crash."""
        try:
            records = list(self.ring)
            head = {"flightrec_manifest": {
                "reason": str(reason), "pid": os.getpid(),
                "ts": time.time(), "n_events": len(records),
                "capacity": self.capacity, **extra,
            }}
            _atomic_write_jsonl(self.path, [head] + records)
            self.dumps.append((reason, self.path))
            return self.path
        except Exception:
            return None


def install(bus, events_path: str,
            capacity: int = DEFAULT_CAPACITY,
            signals: bool = True) -> FlightRecorder:
    """Wire a :class:`FlightRecorder` into ``bus`` and the process:

    - subscribes the ring (activating the bus if it was inert);
    - chains ``sys.excepthook`` so an unhandled exception dumps
      (reason ``unhandled_exception``, the exception named) before the
      original hook prints the traceback;
    - installs a SIGTERM handler (``signals=True``, main thread only)
      that dumps and then re-delivers the signal to the previous
      disposition, so the process still dies with the termination
      status its supervisor expects.

    Returns the recorder (callers keep it to ``dump()`` on their own
    triggers).  The dump lands at ``<events_path>.flightrec``.
    """
    rec = FlightRecorder(flightrec_path(events_path), capacity=capacity)
    bus.subscribe(rec)

    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        rec.dump("unhandled_exception", error=exc_type.__name__)
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook

    if signals:
        try:
            prev_term = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                rec.dump("sigterm")
                if callable(prev_term):
                    prev_term(signum, frame)
                elif prev_term is signal.SIG_IGN:
                    # the process deliberately ignored SIGTERM before the
                    # recorder installed; keep ignoring — dump and live
                    return
                else:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_term)
        except ValueError:
            pass  # not the main thread — the excepthook path still works
    return rec


def flightrec_path(stream_path: str) -> str:
    """``<stream>.flightrec`` — the dump next to the stream it explains."""
    return stream_path + ".flightrec"


def worker_stream_path(events_path: str, worker: int) -> str:
    """The per-process event stream convention (cli.py): worker 0 (and
    single-process runs) own ``<events>``; worker p > 0 streams to
    ``<events>.p<p>`` — distinct from the rotation suffix ``.1``."""
    return events_path if worker == 0 else f"{events_path}.p{worker}"


def _tail_events(stream_path: str, last_n: int, pid=None) -> list:
    """The last ``last_n`` parseable event records of a stream (and, if
    the stream was rotated, of its ``.1`` predecessor), optionally
    filtered to one emitter pid."""
    records = []
    for path in (stream_path + ".1", stream_path):
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue  # a torn final line is expected on kills
                    if isinstance(obj, dict) and "event" in obj and (
                            pid is None or obj.get("pid") == pid):
                        records.append(obj)
        except OSError:
            continue
    return records[-last_n:]


def dump_victim(events_path: str, victim_index: int, reason: str,
                exit_code=None, generation=None, victim_pid=None,
                last_n: int = DEFAULT_CAPACITY) -> Optional[str]:
    """Supervisor-side dump for a worker that died without the chance to
    dump itself (SIGKILL, OOM): read the tail of the victim's
    per-process stream (``worker_stream_path``) and write
    ``<victim stream>.flightrec`` naming the reason and exit code.

    ``victim_pid`` (the dead Popen's pid) scopes the tail to the victim
    generation's own records: worker 0's stream is shared with the
    supervisor's appends, and every stream accumulates earlier
    generations' records (different pids) — without the filter the
    "victim's last-N events" would misattribute those.  The filter
    falls back to the unscoped tail when the victim pid left no records
    (killed before its first event) so the dump still carries the
    stream's last-known state, labeled accordingly.

    Returns the dump path, or None when the victim left no stream (the
    run was launched without ``--events`` — nothing to explain from).
    Never raises (supervisor teardown must proceed regardless).
    """
    try:
        stream = worker_stream_path(events_path, victim_index)
        scoped = victim_pid is not None
        records = _tail_events(stream, last_n,
                               pid=victim_pid if scoped else None)
        if not records and scoped:
            scoped = False
            records = _tail_events(stream, last_n)
        if not records:
            return None
        head = {"flightrec_manifest": {
            "reason": str(reason), "pid": os.getpid(),
            "ts": time.time(), "n_events": len(records),
            "source": "supervisor", "victim_index": int(victim_index),
            "victim_stream": stream,
            # scope="victim": every record below is the dead process's
            # own; scope="stream": the victim left nothing (or its pid
            # is unknown) and this is the stream's last-known state,
            # possibly multi-emitter
            "scope": "victim" if scoped else "stream",
            **({"victim_pid": int(victim_pid)} if victim_pid is not None
               else {}),
            **({"exit_code": int(exit_code)} if exit_code is not None
               else {}),
            **({"generation": int(generation)} if generation is not None
               else {}),
        }}
        return _atomic_write_jsonl(flightrec_path(stream), [head] + records)
    except Exception:
        return None
