"""Prometheus-style textfile metrics, refreshed on every bus event.

The contract (docs/DESIGN.md §Observability): a single plain-text file in
the Prometheus exposition format, rewritten ATOMICALLY (temp + rename, the
node-exporter textfile-collector convention) on every event — or, with a
``flush_interval_s`` debounce, at most once per interval plus a trailing
timer flush (``--metricsInterval``; run boundaries and recovery
transitions always write immediately) — so the elastic supervisor's
stall watchdog and any external scraper can watch a run that is
otherwise one opaque device dispatch:

- ``cocoa_rounds_total``        counter — training rounds advanced
- ``cocoa_evals_total``         counter — debugIter-cadence evaluations
- ``cocoa_sigma_backoffs_total``counter — σ′ anneal backoffs
- ``cocoa_restarts_total``      counter — trial reruns + gang restarts
- ``cocoa_momentum_restarts_total`` counter — --accel gap-monitored
  momentum restarts (the extrapolation reset to the certified iterate)
- ``cocoa_theta_stage``         gauge   — --accel Θ local-accuracy ladder
  stage currently in effect (inner-step count rises with it)
- ``cocoa_compiles_total``      counter — finished XLA compiles (the
  analysis/sanitize.py bridge).  The sanitizer invariant made
  observable: after warmup this must flatline — growth mid-run means a
  shape or config is silently retracing every super-block
- ``cocoa_host_transfers_total``counter — sanctioned device→host fetch
  points (``intended_fetch``).  The drive loop's contract is ~1 per
  super-block; per-ROUND growth means a host sync leaked into the loop
- ``cocoa_ingest_seconds``      gauge   — cumulative data-ingest parse
  seconds this process spent (train + test files; the ``ingest`` event)
- ``cocoa_ingest_bytes``        gauge   — cumulative bytes this process
  read to ingest data (streamed runs read ~2/P of the file vs the whole
  of it — the streaming win, observable)
- ``cocoa_ingest_cache_hits_total`` counter — shards served warm from
  the ``--ingestCache`` slab cache (the ``ingest_cache`` event;
  rendered only once a cache-armed run reported).
  ``cocoa_ingest_cache_bytes`` gauge (cumulative artifact bytes mapped)
  and ``cocoa_ingest_cache_corrupt_total`` counter (artifacts evicted
  by load validation — any nonzero value deserves a disk look) ride
  alongside
- ``cocoa_gang_size``           gauge   — current elastic gang size after
  a shrink-to-survivors resize (the ``gang_resize`` event; absent until
  the first resize — the configured size is in the run manifest)
- ``cocoa_gang_generations_total`` counter — elastic gang generations
  launched (initial + every restart/resize; from the ``generation`` field
  the supervisor stamps on restart/resize events)
- ``cocoa_restart_backoff_seconds`` gauge — the backoff the supervisor
  slept before the most recent relaunch (exponential with jitter, reset
  on progress — a rising value means a crash loop, a reset means the run
  advanced)
- ``cocoa_checkpoint_corrupt_total`` counter — checkpoint generations
  rejected by validation on load (the reader fell back to the previous
  generation; any nonzero value deserves a disk/preemption look)
- ``cocoa_phase_seconds{phase=...}`` gauge — cumulative seconds this
  process spent in each traced phase (the ``span`` events of
  telemetry/tracing.py; present only on ``--trace`` runs).  The
  cross-worker straggler gauges (``cocoa_straggler_slack_seconds``)
  come from telemetry/trace_report.py, which merges every process's
  stream
- ``cocoa_overlap_hidden_seconds`` gauge — cumulative exchange
  wall-clock hidden behind the caller's compute by ``--overlapComm``
  (the ``comm_overlap`` events; present only once an overlapped
  exchange has joined).  ``cocoa_overlap_wait_seconds`` alongside it is
  the residual blocking wait the overlap did NOT hide — the pair is
  the overlap's measured win
- ``cocoa_stale_joins_total{rounds_late=...}`` counter — bounded-
  staleness contributions joined late, labeled by how many rounds late
  (``--staleRounds``; the ``stale_join`` events — never exceeds S by
  construction, which makes the label set finite)
- ``cocoa_fleet_tenants_active`` gauge — tenant lanes still training in
  the current ``--fleet`` run (the ``fleet_progress`` events; certified
  tenants mask out of the update, so this is the live-lane count)
- ``cocoa_tenants_certified_total`` counter — tenants whose duality gap
  crossed their target (the ``tenant_certified`` events)
- ``cocoa_fleet_models_per_second`` gauge — the fleet run's headline
  throughput: tenants certified per wall-clock second through the ONE
  compiled vmapped round (carried by the final ``fleet_progress``)
- ``cocoa_serve_qps``           gauge — serving throughput: requests
  answered per second, averaged over the lifetime of the serving run
  (the ``serve_request`` events; 1 s floor on the denominator so a
  single burst cannot render an absurd rate).  Present only once a
  serve run has answered.  ``cocoa_serve_requests_total`` /
  ``cocoa_serve_batches_total`` counters ride alongside
- ``cocoa_serve_latency_seconds`` histogram — per-batch WORST request
  latency (admission to answer).  Charging every batch its max is the
  conservative SLA accounting: the rendered p99 upper-bounds the true
  per-request p99
- ``cocoa_serve_batch_fill_ratio`` gauge — real requests / padded
  bucket slots, cumulative: how much of the compiled dispatch work is
  real.  Low fill under load means the bucket ladder or the admission
  window is mis-tuned
- ``cocoa_model_swaps_total``   counter — validated checkpoint
  generations hot-swapped into the live serving slot (``model_swap``)
- ``cocoa_serve_margin_error_bound`` gauge — the live ``--serveDtype``
  certificate: the measured f32-vs-quantized margin-error bound of the
  most recent publish over its calibration batch (the
  ``model_quantize`` events; present only once a quantized serve run
  published).  ``cocoa_serve_dtype_fallbacks_total`` counter rides
  alongside — publishes whose bound could flip the weakest calibrated
  margin's sign, so the swap served f32 instead; a steadily climbing
  value means the trained models stopped surviving quantization and
  the serve dtype should be revisited
- ``cocoa_serve_replicas_live`` gauge — fleet replicas currently
  routable (the ``replica_state`` events, serving/router.py); present
  only once a fleet router ran.  ``cocoa_serve_shed_total`` counter —
  request lines refused at admission because every live replica
  projected past the shed budget; ``cocoa_serve_requeue_total``
  counter — request lines replayed off a dead replica onto a live one
  (the requeue-never-fail recovery path, docs/DESIGN.md §21)
- ``cocoa_model_gap_age_seconds`` gauge — freshness of the SERVING
  model: seconds (at render time) since the live model's certificate —
  its checkpoint — was produced.  A healthy background trainer keeps
  this bounded by its checkpoint cadence; a climbing value is a dead or
  wedged trainer, visible long before anyone reads a stale margin.
  Because the value is computed at write time, the serving loop arms
  :meth:`MetricsWriter.start_heartbeat` — a periodic unconditional
  rewrite — so the gauge keeps climbing even when no events arrive
  (a dead trainer + an idle server is exactly the alert scenario)
- ``cocoa_last_gap``            gauge   — most recent duality gap
- ``cocoa_round_seconds``       histogram — observed per-round wall time
  (host-clock deltas between consecutive evals divided by the rounds
  between them; on the device-resident path these are the io_callback
  arrival times — the only per-round timing that path can observe)

Counters are process-lifetime (a CLI invocation runs several algorithms;
their rounds accumulate).  The writer is a plain bus subscriber —
``EventBus.configure(metrics_path=...)`` attaches it.
"""

from __future__ import annotations

import os
import threading
import time

BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
           0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# events whose state change must be visible immediately regardless of the
# write debounce: run boundaries, bail-outs, and every supervisor-visible
# recovery transition (the stall watchdog reads this file as a progress
# token — a debounced restart/resize would blind it exactly when it
# matters)
_FLUSH_EVENTS = frozenset((
    "run_start", "run_end", "divergence", "restart", "gang_resize",
    "checkpoint_corrupt", "events_rotate",
))


class MetricsWriter:
    def __init__(self, path: str, families: str = "all",
                 flush_interval_s: float = 0.0):
        # families="gang": render ONLY the supervisor-owned gang families
        # (cocoa_gang_size / cocoa_gang_generations_total /
        # cocoa_restart_backoff_seconds) — the elastic supervisor's
        # sibling `<metrics>.gang` textfile must not duplicate worker
        # 0's series (a textfile collector globbing the directory
        # rejects duplicate families, and the counters would mean
        # different things in each file).  "all" (workers, single
        # process) renders everything, with the gang families gated on
        # having actually seen gang data for the same reason.
        # flush_interval_s > 0: coalesce textfile rewrites to at most one
        # per interval (plus a trailing timer flush, so the file always
        # converges to the final state within one interval even when the
        # event stream stops).  The default 0.0 keeps the original
        # behavior — one atomic rewrite per event — which is already
        # right at eval cadence; span-heavy or tight-cadence runs pass
        # --metricsInterval so a µs-scale event burst costs one rename,
        # not hundreds.  _FLUSH_EVENTS bypass the debounce either way.
        if families not in ("all", "gang"):
            raise ValueError(f"families must be all|gang, got {families!r}")
        self.families = families
        self.path = path
        self.flush_interval_s = float(flush_interval_s)
        self._lock = threading.RLock()
        self._last_write = 0.0
        self._dirty = False
        self._timer = None
        self._hb_timer = None       # start_heartbeat's repeating timer
        self._hb_interval = None
        self.rounds_total = 0
        self.evals_total = 0
        self.sigma_backoffs_total = 0
        self.restarts_total = 0
        self.momentum_restarts_total = 0
        self.gang_size = None
        self.gang_generations_total = 0
        self.restart_backoff_seconds = None
        self.checkpoint_corrupt_total = 0
        self.theta_stage = None
        self.compiles_total = 0
        self.host_transfers_total = 0
        self.ingest_seconds = 0.0
        self.ingest_bytes = 0
        self.ingest_cache_seen = False
        self.ingest_cache_hits_total = 0
        self.ingest_cache_bytes = 0
        self.ingest_cache_corrupt_total = 0
        self.phase_seconds: dict = {}   # span phase -> cumulative seconds
        self.overlap_hidden_seconds = 0.0
        self.overlap_wait_seconds = 0.0
        self.overlap_joins_total = 0
        self.stale_joins: dict = {}     # rounds_late -> count
        self.fleet_tenants_active = None
        self.tenants_certified_total = 0
        self.fleet_models_per_second = None
        self.serve_requests_total = 0
        self.serve_batches_total = 0
        self.serve_slots_total = 0      # Σ bucket — the fill denominator
        self.serve_first_ts = None
        self.serve_last_ts = None
        self.serve_lat_buckets = [0] * (len(BUCKETS) + 1)
        self.serve_lat_sum = 0.0
        self.serve_lat_count = 0
        self.model_swaps_total = 0
        self.model_birth_ts = None      # live model's certificate birth
        self.model_round = None         # live model's training round
        # per-tenant certification wall-clocks of a served catalogue
        # (model_swap tenant_cert_ts): the tenant-labeled gap-age series
        # renders from these at render time, like the unlabeled gauge
        self.tenant_cert_ts = None
        self.tenant_gaps = None
        self.query_traces_total = 0     # sampled query_trace events
        self.serve_quantize_seen = False
        self.serve_margin_error_bound = None
        self.serve_dtype_fallbacks_total = 0
        self.fleet_serve_seen = False   # any router event arrived
        self.serve_replicas_live = None
        self.serve_shed_total = 0
        self.serve_requeue_total = 0
        self.last_gap = None
        self.bucket_counts = [0] * (len(BUCKETS) + 1)  # +Inf tail
        self.hist_sum = 0.0
        self.hist_count = 0
        # per-algorithm (last round, last event ts) — the round_seconds
        # denominators; cleared on run_start so a restarted run's first
        # eval never spans the gap across generations
        self._prev = {}
        self.write()

    def _observe(self, seconds_per_round: float):
        self.hist_sum += seconds_per_round
        self.hist_count += 1
        for j, b in enumerate(BUCKETS):
            if seconds_per_round <= b:
                self.bucket_counts[j] += 1
                return
        self.bucket_counts[-1] += 1

    def __call__(self, rec: dict):
        with self._lock:
            self._update(rec)
            self._maybe_write(rec.get("event"))

    def _update(self, rec: dict):
        ev = rec.get("event")
        if ev == "run_start":
            self._prev.clear()
        elif ev == "round_eval":
            self.evals_total += 1
            if rec.get("gap") is not None:
                self.last_gap = float(rec["gap"])
            t = rec.get("t")
            alg = rec.get("algorithm")
            if isinstance(t, int):
                prev = self._prev.get(alg)
                if prev is not None and t > prev[0]:
                    dt_rounds = t - prev[0]
                    self.rounds_total += dt_rounds
                    self._observe((rec["ts"] - prev[1]) / dt_rounds)
                # no prev: the first observed eval anchors the counter but
                # adds nothing — a resumed run's t includes rounds a
                # PREVIOUS process (or generation) executed, and crediting
                # them here would re-count the whole history on every
                # elastic restart.  Cost: up to one eval cadence of rounds
                # per run goes uncounted — resume-safe beats exact-once.
                self._prev[alg] = (t, rec["ts"])
        elif ev == "sigma_backoff":
            self.sigma_backoffs_total += 1
        elif ev == "restart":
            self.restarts_total += 1
            # elastic supervisor restarts carry the gang bookkeeping the
            # σ′ trial rerun (same event type) does not
            if rec.get("gang_size") is not None:
                self.gang_size = int(rec["gang_size"])
            if rec.get("backoff_s") is not None:
                self.restart_backoff_seconds = float(rec["backoff_s"])
            if rec.get("generation") is not None:
                # generation = gangs spawned so far; the restart event
                # precedes the relaunch that makes it generation+1
                self.gang_generations_total = max(
                    self.gang_generations_total, int(rec["generation"]) + 1)
        elif ev == "gang_resize":
            self.gang_size = int(rec["new_size"])
            self.gang_generations_total = max(
                self.gang_generations_total, int(rec["generation"]) + 1)
        elif ev == "checkpoint_corrupt":
            self.checkpoint_corrupt_total += 1
        elif ev == "momentum_restart":
            self.momentum_restarts_total += 1
        elif ev == "theta_stage":
            self.theta_stage = rec.get("stage")
        elif ev == "compile":
            self.compiles_total += 1
        elif ev == "host_transfer":
            self.host_transfers_total += 1
        elif ev == "ingest":
            if rec.get("parse_seconds") is not None:
                self.ingest_seconds += float(rec["parse_seconds"])
            if rec.get("bytes_read") is not None:
                self.ingest_bytes += int(rec["bytes_read"])
        elif ev == "ingest_cache":
            self.ingest_cache_seen = True
            if rec.get("shards_cached") is not None:
                self.ingest_cache_hits_total += int(rec["shards_cached"])
            if rec.get("bytes_mapped") is not None:
                self.ingest_cache_bytes += int(rec["bytes_mapped"])
        elif ev == "ingest_cache_corrupt":
            self.ingest_cache_seen = True
            self.ingest_cache_corrupt_total += 1
        elif ev == "span":
            # per-phase wall-clock gauge (tracing.py spans): cumulative
            # seconds this process spent in each instrumented phase —
            # the single-process half of the straggler story (the
            # cross-worker slack gauges come from trace_report.py,
            # which sees every process's stream)
            phase = rec.get("phase")
            if phase is not None and rec.get("dur_s") is not None:
                self.phase_seconds[str(phase)] = (
                    self.phase_seconds.get(str(phase), 0.0)
                    + float(rec["dur_s"]))
        elif ev == "comm_overlap":
            self.overlap_joins_total += 1
            if rec.get("hidden_s") is not None:
                self.overlap_hidden_seconds += float(rec["hidden_s"])
            if rec.get("wait_s") is not None:
                self.overlap_wait_seconds += float(rec["wait_s"])
        elif ev == "stale_join":
            late = rec.get("rounds_late")
            if late is not None:
                self.stale_joins[int(late)] = (
                    self.stale_joins.get(int(late), 0) + 1)
        elif ev == "fleet_progress":
            if rec.get("active") is not None:
                self.fleet_tenants_active = int(rec["active"])
            if rec.get("models_per_second") is not None:
                self.fleet_models_per_second = float(
                    rec["models_per_second"])
        elif ev == "tenant_certified":
            self.tenants_certified_total += 1
        elif ev == "serve_request":
            n = int(rec.get("n") or 0)
            self.serve_requests_total += n
            self.serve_batches_total += 1
            self.serve_slots_total += int(rec.get("bucket") or 0)
            ts = rec.get("ts")
            if ts is not None:
                if self.serve_first_ts is None:
                    self.serve_first_ts = float(ts)
                self.serve_last_ts = float(ts)
            lat = rec.get("latency_max_s")
            if lat is not None:
                # per-batch WORST latency: conservative SLA accounting
                # (the rendered p99 upper-bounds the per-request p99)
                lat = float(lat)
                self.serve_lat_sum += lat
                self.serve_lat_count += 1
                for j, b in enumerate(BUCKETS):
                    if lat <= b:
                        self.serve_lat_buckets[j] += 1
                        break
                else:
                    self.serve_lat_buckets[-1] += 1
        elif ev == "model_swap":
            # swap_seq 0 is the server's INITIAL load (it anchors gap
            # age but is not a hot-swap) — counting it would disagree by
            # one with the watcher's swaps_total and the bench row
            if rec.get("swap_seq"):
                self.model_swaps_total += 1
            if rec.get("birth_ts") is not None:
                self.model_birth_ts = float(rec["birth_ts"])
            if rec.get("round") is not None:
                self.model_round = int(rec["round"])
            if rec.get("tenant_cert_ts") is not None:
                self.tenant_cert_ts = [float(t) for t
                                       in rec["tenant_cert_ts"]]
            if rec.get("tenant_gaps") is not None:
                self.tenant_gaps = [float(g) if g is not None else None
                                    for g in rec["tenant_gaps"]]
        elif ev == "model_quantize":
            self.serve_quantize_seen = True
            if rec.get("bound") is not None:
                # the LIVE certificate: the most recent publish's bound
                # (kept even on a fallback — it is why the fallback
                # happened, and the one number to look at when the
                # fallbacks counter climbs)
                self.serve_margin_error_bound = float(rec["bound"])
            if rec.get("fallback"):
                self.serve_dtype_fallbacks_total += 1
        elif ev == "serve_shed":
            self.fleet_serve_seen = True
            self.serve_shed_total += 1
        elif ev == "query_trace":
            self.query_traces_total += 1
        elif ev == "replica_state":
            self.fleet_serve_seen = True
            if rec.get("replicas_live") is not None:
                self.serve_replicas_live = int(rec["replicas_live"])
            self.serve_requeue_total += int(rec.get("requeued") or 0)

    def _maybe_write(self, ev):
        """The write debounce (caller holds the lock): flush-now events
        and elapsed intervals write; everything else marks dirty and arms
        a one-shot trailing timer for the remainder of the window."""
        self._dirty = True
        now = time.monotonic()
        if (self.flush_interval_s <= 0 or ev in _FLUSH_EVENTS
                or now - self._last_write >= self.flush_interval_s):
            self.write()
            return
        if self._timer is None:
            delay = self.flush_interval_s - (now - self._last_write)
            self._timer = threading.Timer(max(delay, 0.001), self.flush)
            self._timer.daemon = True
            self._timer.start()

    def flush(self):
        """Write the current state if anything changed since the last
        write (the trailing-timer target; also callable by owners at
        shutdown).  Best-effort on the timer path: the target directory
        may already be gone at process teardown — a late flush must not
        turn that into a thread-crash traceback."""
        with self._lock:
            if self._dirty:
                try:
                    self.write()
                except OSError:
                    pass

    def start_heartbeat(self, interval_s: float = 5.0):
        """Periodic UNCONDITIONAL rewrite, independent of events — the
        serving loop arms this because its render-time gauges
        (``cocoa_model_gap_age_seconds``) must keep moving when no
        events arrive: a dead trainer plus an idle server is exactly
        the scenario the climbing gauge exists to alert on, and an
        event-driven-only writer would freeze the textfile there.
        Best-effort like :meth:`flush`; idempotent; daemon timers."""
        with self._lock:
            self._hb_interval = float(interval_s)
            if self._hb_timer is None:
                self._arm_heartbeat()

    def stop_heartbeat(self):
        with self._lock:
            self._hb_interval = None
            if self._hb_timer is not None:
                self._hb_timer.cancel()
                self._hb_timer = None

    def _arm_heartbeat(self):
        t = threading.Timer(self._hb_interval, self._heartbeat)
        t.daemon = True
        t.start()
        self._hb_timer = t

    def _heartbeat(self):
        with self._lock:
            if self._hb_interval is None:
                return
            try:
                self.write()
            except OSError:
                pass
            self._arm_heartbeat()

    def _gang_lines(self) -> list:
        lines = ["# TYPE cocoa_gang_generations_total counter",
                 f"cocoa_gang_generations_total "
                 f"{self.gang_generations_total}"]
        if self.gang_size is not None:
            lines += ["# TYPE cocoa_gang_size gauge",
                      f"cocoa_gang_size {self.gang_size}"]
        if self.restart_backoff_seconds is not None:
            lines += ["# TYPE cocoa_restart_backoff_seconds gauge",
                      f"cocoa_restart_backoff_seconds "
                      f"{self.restart_backoff_seconds!r}"]
        return lines

    def render(self) -> str:
        if self.families == "gang":
            return "\n".join(self._gang_lines()) + "\n"
        lines = [
            "# TYPE cocoa_rounds_total counter",
            f"cocoa_rounds_total {self.rounds_total}",
            "# TYPE cocoa_evals_total counter",
            f"cocoa_evals_total {self.evals_total}",
            "# TYPE cocoa_sigma_backoffs_total counter",
            f"cocoa_sigma_backoffs_total {self.sigma_backoffs_total}",
            "# TYPE cocoa_restarts_total counter",
            f"cocoa_restarts_total {self.restarts_total}",
            "# TYPE cocoa_momentum_restarts_total counter",
            f"cocoa_momentum_restarts_total {self.momentum_restarts_total}",
            "# TYPE cocoa_compiles_total counter",
            f"cocoa_compiles_total {self.compiles_total}",
            "# TYPE cocoa_host_transfers_total counter",
            f"cocoa_host_transfers_total {self.host_transfers_total}",
            "# TYPE cocoa_ingest_seconds gauge",
            f"cocoa_ingest_seconds {self.ingest_seconds!r}",
            "# TYPE cocoa_ingest_bytes gauge",
            f"cocoa_ingest_bytes {self.ingest_bytes}",
            "# TYPE cocoa_checkpoint_corrupt_total counter",
            f"cocoa_checkpoint_corrupt_total {self.checkpoint_corrupt_total}",
        ]
        if self.ingest_cache_seen:
            # cache families render only once a --ingestCache run has
            # reported (uncached runs must not carry zero-valued series)
            lines += ["# TYPE cocoa_ingest_cache_hits_total counter",
                      f"cocoa_ingest_cache_hits_total "
                      f"{self.ingest_cache_hits_total}",
                      "# TYPE cocoa_ingest_cache_bytes gauge",
                      f"cocoa_ingest_cache_bytes "
                      f"{self.ingest_cache_bytes}",
                      "# TYPE cocoa_ingest_cache_corrupt_total counter",
                      f"cocoa_ingest_cache_corrupt_total "
                      f"{self.ingest_cache_corrupt_total}"]
        if self.gang_generations_total:
            # gang families appear in an "all" file only when this
            # process actually saw gang events (a worker never does —
            # its file must not shadow the supervisor's .gang series)
            lines += self._gang_lines()
        if self.phase_seconds:
            lines.append("# TYPE cocoa_phase_seconds gauge")
            lines += [f'cocoa_phase_seconds{{phase="{p}"}} '
                      f"{self.phase_seconds[p]!r}"
                      for p in sorted(self.phase_seconds)]
        if self.overlap_joins_total:
            lines += ["# TYPE cocoa_overlap_hidden_seconds gauge",
                      f"cocoa_overlap_hidden_seconds "
                      f"{self.overlap_hidden_seconds!r}",
                      "# TYPE cocoa_overlap_wait_seconds gauge",
                      f"cocoa_overlap_wait_seconds "
                      f"{self.overlap_wait_seconds!r}"]
        if self.stale_joins:
            lines.append("# TYPE cocoa_stale_joins_total counter")
            lines += [f'cocoa_stale_joins_total{{rounds_late="{late}"}} '
                      f"{self.stale_joins[late]}"
                      for late in sorted(self.stale_joins)]
        if self.fleet_tenants_active is not None:
            # fleet families appear only once a --fleet run has reported
            # (solo runs must not render zero-valued fleet series)
            lines += ["# TYPE cocoa_fleet_tenants_active gauge",
                      f"cocoa_fleet_tenants_active "
                      f"{self.fleet_tenants_active}",
                      "# TYPE cocoa_tenants_certified_total counter",
                      f"cocoa_tenants_certified_total "
                      f"{self.tenants_certified_total}"]
            if self.fleet_models_per_second is not None:
                lines += ["# TYPE cocoa_fleet_models_per_second gauge",
                          f"cocoa_fleet_models_per_second "
                          f"{self.fleet_models_per_second!r}"]
        if self.serve_batches_total:
            # serving families render only once a --serve run answered
            # (training runs must not carry zero-valued serve series)
            qps = self.serve_requests_total / max(
                (self.serve_last_ts or 0.0) - (self.serve_first_ts
                                               or 0.0), 1.0)
            fill = self.serve_requests_total / max(self.serve_slots_total,
                                                   1)
            lines += ["# TYPE cocoa_serve_requests_total counter",
                      f"cocoa_serve_requests_total "
                      f"{self.serve_requests_total}",
                      "# TYPE cocoa_serve_batches_total counter",
                      f"cocoa_serve_batches_total "
                      f"{self.serve_batches_total}",
                      "# TYPE cocoa_serve_qps gauge",
                      f"cocoa_serve_qps {qps!r}",
                      "# TYPE cocoa_serve_batch_fill_ratio gauge",
                      f"cocoa_serve_batch_fill_ratio {fill!r}",
                      "# TYPE cocoa_serve_latency_seconds histogram"]
            cum = 0
            for b, c in zip(BUCKETS, self.serve_lat_buckets):
                cum += c
                lines.append(
                    f'cocoa_serve_latency_seconds_bucket{{le="{b}"}} '
                    f"{cum}")
            lines.append(f'cocoa_serve_latency_seconds_bucket'
                         f'{{le="+Inf"}} '
                         f"{cum + self.serve_lat_buckets[-1]}")
            lines.append(f"cocoa_serve_latency_seconds_sum "
                         f"{self.serve_lat_sum!r}")
            lines.append(f"cocoa_serve_latency_seconds_count "
                         f"{self.serve_lat_count}")
        if self.model_birth_ts is not None:
            now = time.time()
            age = max(0.0, now - self.model_birth_ts)
            lines += ["# TYPE cocoa_model_swaps_total counter",
                      f"cocoa_model_swaps_total {self.model_swaps_total}",
                      "# TYPE cocoa_model_gap_age_seconds gauge",
                      f"cocoa_model_gap_age_seconds {age!r}"]
            if self.tenant_cert_ts:
                # the catalogue's per-tenant freshness (docs/DESIGN.md
                # §22): seconds since EACH tenant row's certificate was
                # produced — the labeled series sits under the same
                # family as the whole-catalogue gauge above
                lines += [f'cocoa_model_gap_age_seconds{{tenant="{t}"}} '
                          f"{max(0.0, now - ts)!r}"
                          for t, ts in enumerate(self.tenant_cert_ts)]
            if self.model_round is not None:
                lines += ["# TYPE cocoa_model_round gauge",
                          f"cocoa_model_round {self.model_round}"]
        if self.serve_quantize_seen:
            # quantized-serving families render only once a --serveDtype
            # run published (f32 serves must not carry zero-valued
            # quantization series)
            lines += ["# TYPE cocoa_serve_dtype_fallbacks_total counter",
                      f"cocoa_serve_dtype_fallbacks_total "
                      f"{self.serve_dtype_fallbacks_total}"]
            if self.serve_margin_error_bound is not None:
                lines += ["# TYPE cocoa_serve_margin_error_bound gauge",
                          f"cocoa_serve_margin_error_bound "
                          f"{self.serve_margin_error_bound!r}"]
        if self.fleet_serve_seen:
            # fleet-serving families render only once a router event
            # arrived (single-process serves must not carry zero-valued
            # fleet series)
            lines += ["# TYPE cocoa_serve_shed_total counter",
                      f"cocoa_serve_shed_total {self.serve_shed_total}",
                      "# TYPE cocoa_serve_requeue_total counter",
                      f"cocoa_serve_requeue_total "
                      f"{self.serve_requeue_total}"]
            if self.serve_replicas_live is not None:
                lines += ["# TYPE cocoa_serve_replicas_live gauge",
                          f"cocoa_serve_replicas_live "
                          f"{self.serve_replicas_live}"]
        if self.query_traces_total:
            # sampled tracing families render only once a --traceSample
            # run emitted (untraced serves must not carry zero series)
            lines += ["# TYPE cocoa_query_traces_total counter",
                      f"cocoa_query_traces_total "
                      f"{self.query_traces_total}"]
        if self.theta_stage is not None:
            lines += ["# TYPE cocoa_theta_stage gauge",
                      f"cocoa_theta_stage {self.theta_stage}"]
        if self.last_gap is not None:
            lines += ["# TYPE cocoa_last_gap gauge",
                      f"cocoa_last_gap {self.last_gap!r}"]
        lines.append("# TYPE cocoa_round_seconds histogram")
        cum = 0
        for b, c in zip(BUCKETS, self.bucket_counts):
            cum += c
            lines.append(f'cocoa_round_seconds_bucket{{le="{b}"}} {cum}')
        lines.append(f'cocoa_round_seconds_bucket{{le="+Inf"}} '
                     f"{cum + self.bucket_counts[-1]}")
        lines.append(f"cocoa_round_seconds_sum {self.hist_sum!r}")
        lines.append(f"cocoa_round_seconds_count {self.hist_count}")
        return "\n".join(lines) + "\n"

    def write(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._dirty = False
            self._last_write = time.monotonic()
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(self.render())
            os.replace(tmp, self.path)
