"""Prometheus-style textfile metrics, refreshed on every bus event.

The contract (docs/DESIGN.md §Observability): a single plain-text file in
the Prometheus exposition format, rewritten ATOMICALLY (temp + rename, the
node-exporter textfile-collector convention) on every event, so the
elastic supervisor's stall watchdog and any external scraper can watch a
run that is otherwise one opaque device dispatch:

- ``cocoa_rounds_total``        counter — training rounds advanced
- ``cocoa_evals_total``         counter — debugIter-cadence evaluations
- ``cocoa_sigma_backoffs_total``counter — σ′ anneal backoffs
- ``cocoa_restarts_total``      counter — trial reruns + gang restarts
- ``cocoa_momentum_restarts_total`` counter — --accel gap-monitored
  momentum restarts (the extrapolation reset to the certified iterate)
- ``cocoa_theta_stage``         gauge   — --accel Θ local-accuracy ladder
  stage currently in effect (inner-step count rises with it)
- ``cocoa_compiles_total``      counter — finished XLA compiles (the
  analysis/sanitize.py bridge).  The sanitizer invariant made
  observable: after warmup this must flatline — growth mid-run means a
  shape or config is silently retracing every super-block
- ``cocoa_host_transfers_total``counter — sanctioned device→host fetch
  points (``intended_fetch``).  The drive loop's contract is ~1 per
  super-block; per-ROUND growth means a host sync leaked into the loop
- ``cocoa_ingest_seconds``      gauge   — cumulative data-ingest parse
  seconds this process spent (train + test files; the ``ingest`` event)
- ``cocoa_ingest_bytes``        gauge   — cumulative bytes this process
  read to ingest data (streamed runs read ~2/P of the file vs the whole
  of it — the streaming win, observable)
- ``cocoa_last_gap``            gauge   — most recent duality gap
- ``cocoa_round_seconds``       histogram — observed per-round wall time
  (host-clock deltas between consecutive evals divided by the rounds
  between them; on the device-resident path these are the io_callback
  arrival times — the only per-round timing that path can observe)

Counters are process-lifetime (a CLI invocation runs several algorithms;
their rounds accumulate).  The writer is a plain bus subscriber —
``EventBus.configure(metrics_path=...)`` attaches it.
"""

from __future__ import annotations

import os

BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
           0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class MetricsWriter:
    def __init__(self, path: str):
        self.path = path
        self.rounds_total = 0
        self.evals_total = 0
        self.sigma_backoffs_total = 0
        self.restarts_total = 0
        self.momentum_restarts_total = 0
        self.theta_stage = None
        self.compiles_total = 0
        self.host_transfers_total = 0
        self.ingest_seconds = 0.0
        self.ingest_bytes = 0
        self.last_gap = None
        self.bucket_counts = [0] * (len(BUCKETS) + 1)  # +Inf tail
        self.hist_sum = 0.0
        self.hist_count = 0
        # per-algorithm (last round, last event ts) — the round_seconds
        # denominators; cleared on run_start so a restarted run's first
        # eval never spans the gap across generations
        self._prev = {}
        self.write()

    def _observe(self, seconds_per_round: float):
        self.hist_sum += seconds_per_round
        self.hist_count += 1
        for j, b in enumerate(BUCKETS):
            if seconds_per_round <= b:
                self.bucket_counts[j] += 1
                return
        self.bucket_counts[-1] += 1

    def __call__(self, rec: dict):
        ev = rec.get("event")
        if ev == "run_start":
            self._prev.clear()
        elif ev == "round_eval":
            self.evals_total += 1
            if rec.get("gap") is not None:
                self.last_gap = float(rec["gap"])
            t = rec.get("t")
            alg = rec.get("algorithm")
            if isinstance(t, int):
                prev = self._prev.get(alg)
                if prev is not None and t > prev[0]:
                    dt_rounds = t - prev[0]
                    self.rounds_total += dt_rounds
                    self._observe((rec["ts"] - prev[1]) / dt_rounds)
                # no prev: the first observed eval anchors the counter but
                # adds nothing — a resumed run's t includes rounds a
                # PREVIOUS process (or generation) executed, and crediting
                # them here would re-count the whole history on every
                # elastic restart.  Cost: up to one eval cadence of rounds
                # per run goes uncounted — resume-safe beats exact-once.
                self._prev[alg] = (t, rec["ts"])
        elif ev == "sigma_backoff":
            self.sigma_backoffs_total += 1
        elif ev == "restart":
            self.restarts_total += 1
        elif ev == "momentum_restart":
            self.momentum_restarts_total += 1
        elif ev == "theta_stage":
            self.theta_stage = rec.get("stage")
        elif ev == "compile":
            self.compiles_total += 1
        elif ev == "host_transfer":
            self.host_transfers_total += 1
        elif ev == "ingest":
            if rec.get("parse_seconds") is not None:
                self.ingest_seconds += float(rec["parse_seconds"])
            if rec.get("bytes_read") is not None:
                self.ingest_bytes += int(rec["bytes_read"])
        self.write()

    def render(self) -> str:
        lines = [
            "# TYPE cocoa_rounds_total counter",
            f"cocoa_rounds_total {self.rounds_total}",
            "# TYPE cocoa_evals_total counter",
            f"cocoa_evals_total {self.evals_total}",
            "# TYPE cocoa_sigma_backoffs_total counter",
            f"cocoa_sigma_backoffs_total {self.sigma_backoffs_total}",
            "# TYPE cocoa_restarts_total counter",
            f"cocoa_restarts_total {self.restarts_total}",
            "# TYPE cocoa_momentum_restarts_total counter",
            f"cocoa_momentum_restarts_total {self.momentum_restarts_total}",
            "# TYPE cocoa_compiles_total counter",
            f"cocoa_compiles_total {self.compiles_total}",
            "# TYPE cocoa_host_transfers_total counter",
            f"cocoa_host_transfers_total {self.host_transfers_total}",
            "# TYPE cocoa_ingest_seconds gauge",
            f"cocoa_ingest_seconds {self.ingest_seconds!r}",
            "# TYPE cocoa_ingest_bytes gauge",
            f"cocoa_ingest_bytes {self.ingest_bytes}",
        ]
        if self.theta_stage is not None:
            lines += ["# TYPE cocoa_theta_stage gauge",
                      f"cocoa_theta_stage {self.theta_stage}"]
        if self.last_gap is not None:
            lines += ["# TYPE cocoa_last_gap gauge",
                      f"cocoa_last_gap {self.last_gap!r}"]
        lines.append("# TYPE cocoa_round_seconds histogram")
        cum = 0
        for b, c in zip(BUCKETS, self.bucket_counts):
            cum += c
            lines.append(f'cocoa_round_seconds_bucket{{le="{b}"}} {cum}')
        lines.append(f'cocoa_round_seconds_bucket{{le="+Inf"}} '
                     f"{cum + self.bucket_counts[-1]}")
        lines.append(f"cocoa_round_seconds_sum {self.hist_sum!r}")
        lines.append(f"cocoa_round_seconds_count {self.hist_count}")
        return "\n".join(lines) + "\n"

    def write(self):
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.render())
        os.replace(tmp, self.path)
