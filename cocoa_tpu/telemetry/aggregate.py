"""The live fleet ops plane: merge per-process metrics textfiles and
serve ``/metrics``, ``/healthz``, ``/slo`` over HTTP (``--statusPort``,
docs/DESIGN.md §22).

A serving fleet already writes N+1 Prometheus textfiles — the front
door's own plus one ``<metrics>.r<N>`` per replica (cli.py wires the
suffix, the same ``.rN`` slot convention as the event streams).  Nothing
aggregated them while the system ran: answering "is the fleet inside
its SLA right now" meant hand-merging files.  This module is that
aggregation, deliberately built ON the textfiles rather than on a new
IPC channel: the files are the crash-safe, atomically-renamed artifacts
every process already owns, a scrape is a handful of reads, and a dead
replica keeps its last file on disk — visible as a frozen round and a
climbing gap age rather than a hole in the data.

Endpoints (stdlib ``http.server``, no new dependencies):

- ``/metrics`` — one merged Prometheus exposition: every sample from
  every source file re-labeled with ``replica="<label>"``, families
  grouped under one ``# TYPE`` line each, so a single scrape target
  covers the whole fleet with per-replica attribution.
- ``/healthz`` — JSON liveness + freshness: per replica the router's
  live bit, the newest generation it serves (``cocoa_model_round``)
  and its certificate age (``cocoa_model_gap_age_seconds``), plus the
  fleet-wide live count and newest round.  ``status`` is "ok" only
  when every replica is live — the SIGKILL drill shows "degraded" with
  the victim's live=false, then "ok" again after the respawn.
- ``/slo`` — rolling SLA attainment and multi-window burn rate over
  the fleet-wide ``cocoa_serve_latency_seconds`` histogram: each
  evaluation snapshots the cumulative (served, over-SLA) totals, and
  attainment/burn are computed from deltas inside the fast/slow
  windows — cumulative counters make the rolling math exact across
  scrapes, no per-request state needed.  Each evaluation also emits a
  typed ``slo_status`` event, so the SLO verdicts land in the same
  machine-readable stream as everything else.

The latency histogram's per-batch observations are worst-of-batch
(metrics.py), so the attainment reported here lower-bounds the true
per-request attainment — conservative in the direction an SLO should
be.  Burn rate is the standard error-budget form: ``(1 - attainment) /
(1 - objective)`` over a window; > 1 on both the fast and slow windows
means the budget is burning faster than it refills — the page-worthy
signal — while fast-only is a blip and slow-only an old incident
draining out.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

# SLA attainment objective: the p99 budget — 1% of lines may breach
DEFAULT_OBJECTIVE = 0.99
# burn-rate windows (seconds): the fast window catches a live incident,
# the slow window filters blips — the classic multi-window pair scaled
# to a serving loop's cadence rather than a month-long budget
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 300.0


# --- exposition parsing ------------------------------------------------------


def split_sample(line: str):
    """One textfile sample line -> ``(name, labels, value)`` strings
    (labels without braces, "" when unlabeled); (None, None, None) on
    comments/blank/garbage — a scraper never throws on a torn file."""
    rest = line.strip()
    if not rest or rest.startswith("#"):
        return None, None, None
    brace = rest.find("{")
    if brace >= 0:
        end = rest.rfind("}")
        if end < brace:
            return None, None, None
        name = rest[:brace]
        labels = rest[brace + 1:end]
        value = rest[end + 1:].strip()
    else:
        name, _, value = rest.partition(" ")
        labels = ""
    if not name or not value:
        return None, None, None
    try:
        float(value)
    except ValueError:
        return None, None, None
    return name, labels, value


def family(name: str) -> str:
    """The family a sample belongs to: histogram member suffixes fold
    into their base name, everything else is its own family."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[:-len(suffix)]
    return name


def merge_expositions(sources: Dict[str, str]) -> str:
    """Merge per-process textfiles into ONE exposition: every sample
    re-labeled with ``replica="<label>"`` (prepended, existing labels
    kept), families grouped under a single ``# TYPE`` line each (first
    seen wins), sources in sorted-label order so the merge is
    deterministic."""
    fam_order, fam_type, fam_samples = [], {}, {}

    def _fam(f, type_line=None):
        if f not in fam_type:
            fam_type[f] = type_line or f"# TYPE {f} untyped"
            fam_order.append(f)
        elif type_line and fam_type[f].endswith(" untyped"):
            fam_type[f] = type_line

    for label in sorted(sources):
        for ln in sources[label].splitlines():
            if ln.startswith("# TYPE "):
                parts = ln.split()
                if len(parts) >= 3:
                    _fam(parts[2], ln)
                continue
            name, labels, value = split_sample(ln)
            if name is None:
                continue
            f = family(name)
            _fam(f)
            merged = f'replica="{label}"' + (
                "," + labels if labels else "")
            fam_samples.setdefault(f, []).append(
                f"{name}{{{merged}}} {value}")
    lines = []
    for f in fam_order:
        lines.append(fam_type[f])
        lines += fam_samples.get(f, [])
    return "\n".join(lines) + "\n"


def read_sources(paths: Dict[str, str]) -> Dict[str, str]:
    """label -> textfile content for every source that exists; missing
    or unreadable files are skipped (a replica that never wrote is not
    an aggregator crash)."""
    out = {}
    for label, path in paths.items():
        try:
            with open(path) as f:
                out[label] = f.read()
        except OSError:
            continue
    return out


def scrape_gauge(text: str, name: str) -> Optional[float]:
    """The UNLABELED sample of one family (the whole-process gauge);
    None when absent."""
    for ln in text.splitlines():
        n, labels, value = split_sample(ln)
        if n == name and not labels:
            return float(value)
    return None


def latency_totals(sources: Dict[str, str], sla_s: float):
    """Fleet-wide ``(served_total, over_sla_total)`` from the
    cumulative ``cocoa_serve_latency_seconds`` histogram: within-SLA is
    the cumulative bucket at the largest edge <= sla_s, so latencies in
    (edge, sla] count as over — conservative, never optimistic."""
    total = over = 0
    for text in sources.values():
        count, best_edge, best_cum = 0, -1.0, 0.0
        for ln in text.splitlines():
            name, labels, value = split_sample(ln)
            if name == "cocoa_serve_latency_seconds_count" \
                    and not labels.startswith("replica="):
                count = int(float(value))
            elif name == "cocoa_serve_latency_seconds_bucket":
                le = dict(
                    kv.split("=", 1) for kv in labels.split(",")
                    if "=" in kv).get("le", "").strip('"')
                if le in ("", "+Inf"):
                    continue
                edge = float(le)
                if best_edge < edge <= sla_s:
                    best_edge, best_cum = edge, float(value)
        total += count
        over += count - min(int(best_cum), count)
    return total, over


# --- the rolling SLO math ----------------------------------------------------


class SloTracker:
    """Cumulative-counter snapshots -> rolling attainment + burn.

    Pure bookkeeping (no IO, injectable clock): ``observe`` appends one
    ``(ts, served_total, over_sla_total)`` snapshot, ``status`` computes
    attainment over the slow window (lifetime until the window has two
    snapshots) and the fast/slow burn rates from in-window deltas.
    Counters are monotone (the histogram is cumulative), so a delta is
    exactly the traffic inside the window."""

    def __init__(self, sla_s: float, objective: float = DEFAULT_OBJECTIVE,
                 fast_s: float = FAST_WINDOW_S,
                 slow_s: float = SLOW_WINDOW_S):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got "
                             f"{objective!r}")
        self.sla_s = float(sla_s)
        self.objective = float(objective)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self._snaps = []   # (ts, served_total, over_sla_total)
        self._lock = threading.Lock()

    def observe(self, served_total: int, over_sla_total: int,
                now: Optional[float] = None):
        now = time.time() if now is None else now
        with self._lock:
            self._snaps.append((now, int(served_total),
                                int(over_sla_total)))
            horizon = now - 2 * self.slow_s
            while len(self._snaps) > 2 and self._snaps[1][0] < horizon:
                self._snaps.pop(0)

    def _window(self, now: float, window_s: float):
        """Attainment over ``[now - window_s, now]`` from the earliest
        in-window snapshot to the latest; None until the window holds a
        delta with traffic in it."""
        last = self._snaps[-1]
        base = None
        for snap in self._snaps:
            if snap[0] >= now - window_s:
                base = snap
                break
        if base is None or base is last:
            return None
        served = last[1] - base[1]
        over = last[2] - base[2]
        if served <= 0:
            return None
        return 1.0 - over / served

    def status(self, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            if not self._snaps:
                served = over = 0
                attain = burn_fast = burn_slow = None
            else:
                _, served, over = self._snaps[-1]
                attain = self._window(now, self.slow_s)
                if attain is None and served > 0:
                    attain = 1.0 - over / served   # lifetime fallback
                budget = 1.0 - self.objective
                af = self._window(now, self.fast_s)
                aslow = self._window(now, self.slow_s)
                burn_fast = (None if af is None
                             else (1.0 - af) / budget)
                burn_slow = (None if aslow is None
                             else (1.0 - aslow) / budget)
        return {"sla_ms": self.sla_s * 1e3,
                "objective": self.objective,
                "window_fast_s": self.fast_s,
                "window_slow_s": self.slow_s,
                "attainment": attain,
                "burn_fast": burn_fast, "burn_slow": burn_slow,
                "served_total": served, "over_sla_total": over}


# --- the HTTP plane ----------------------------------------------------------


class StatusServer:
    """``/metrics`` + ``/healthz`` + ``/slo`` over the per-process
    textfiles the fleet already writes.

    ``sources_fn`` returns the label -> path map to scrape (called per
    request, so a respawned replica's slot file is always current);
    ``liveness_fn`` (optional) returns the router's name -> live map —
    without it every scraped source counts as live (the solo server
    case).  Pure stdlib, daemon-threaded, port 0 = ephemeral."""

    def __init__(self, sources_fn: Callable[[], Dict[str, str]],
                 sla_s: float, host: str = "127.0.0.1", port: int = 0,
                 algorithm: str = "serve",
                 liveness_fn: Optional[Callable[[], Dict[str, bool]]]
                 = None,
                 objective: float = DEFAULT_OBJECTIVE):
        self.sources_fn = sources_fn
        self.liveness_fn = liveness_fn
        self.algorithm = algorithm
        self.tracker = SloTracker(sla_s, objective=objective)
        plane = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # the ops plane must not spam
                pass                     # the serving console

            def do_GET(self):
                try:
                    route = self.path.split("?")[0].rstrip("/") or "/"
                    if route == "/metrics":
                        body, ctype = plane.render_metrics(), \
                            "text/plain; version=0.0.4"
                    elif route == "/healthz":
                        body, ctype = plane.render_healthz(), \
                            "application/json"
                    elif route == "/slo":
                        body, ctype = plane.render_slo(), \
                            "application/json"
                    else:
                        self.send_error(404, "unknown endpoint "
                                        "(have /metrics /healthz /slo)")
                        return
                except Exception as e:   # a torn scrape must answer 500,
                    self.send_error(500, f"{type(e).__name__}: {e}")
                    return               # never kill the plane
                raw = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        class _HTTP(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._http = _HTTP((host, port), _Handler)
        self._thread = threading.Thread(
            target=self._http.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="cocoa-status-plane")

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves here."""
        return self._http.server_address

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._http.shutdown()
        self._thread.join(timeout)
        self._http.server_close()

    # --- renderers (also the direct test surface — no sockets needed) ---

    def _scrape(self):
        return read_sources(self.sources_fn())

    def render_metrics(self) -> str:
        return merge_expositions(self._scrape())

    def render_healthz(self) -> str:
        sources = self._scrape()
        live_map = (self.liveness_fn() if self.liveness_fn is not None
                    else {label: True for label in sources})
        replicas = {}
        newest = None
        for name in sorted(set(live_map) | set(sources)):
            text = sources.get(name, "")
            rnd = scrape_gauge(text, "cocoa_model_round")
            age = scrape_gauge(text, "cocoa_model_gap_age_seconds")
            if rnd is not None:
                newest = rnd if newest is None else max(newest, rnd)
            # a scraped source the liveness map does not track (the
            # router's own file) gets live=null, not a false alarm
            replicas[name] = {
                "live": (bool(live_map[name]) if name in live_map
                         else None),
                "round": None if rnd is None else int(rnd),
                "gap_age_s": age}
        n_live = sum(1 for r in live_map.values() if r)
        return json.dumps(
            {"status": ("ok" if live_map
                        and n_live == len(live_map) else "degraded"),
             "replicas_live": n_live,
             "replicas_total": len(live_map),
             "round": None if newest is None else int(newest),
             "replicas": replicas}, sort_keys=True) + "\n"

    def render_slo(self) -> str:
        sources = self._scrape()
        served, over = latency_totals(sources, self.tracker.sla_s)
        self.tracker.observe(served, over)
        status = self.tracker.status()
        live = (sum(1 for v in self.liveness_fn().values() if v)
                if self.liveness_fn is not None else None)
        status["replicas_live"] = live
        self._emit(status)
        return json.dumps(status, sort_keys=True) + "\n"

    def _emit(self, status: dict):
        from cocoa_tpu.telemetry import events as tele_events

        bus = tele_events.get_bus()
        if bus.active():
            bus.emit("slo_status", algorithm=self.algorithm, **status)
