"""Profiler capture, trace summarization, and round-windowed capture.

The capture/summarize core lived in benchmarks/trace.py (VERDICT r3 item
8: record what the hardware actually did, not just the analytic roofline).
It is promoted here so production runs and benchmarks share ONE
implementation: benchmarks/trace.py now imports :func:`capture`,
:func:`parse_trace` and :func:`device_table` from this module, and the CLI
exposes the same machinery as ``--profile=<dir>[,<start>,<stop>]``.

The round window: a whole-run trace of a production run is dominated by
compile + warmup and can reach GBs; what a perf question usually needs is
a few steady-state rounds.  :class:`RoundWindowProfiler` subscribes to the
telemetry event bus and starts/stops ``jax.profiler`` when the
``round_eval`` stream crosses the requested round bounds — which works on
the device-resident driver precisely BECAUSE the io_callback bridge emits
evals while the ``lax.while_loop`` is still running (a post-hoc trigger
would fire after the loop already finished).  On the fallback (replayed)
bridge the events arrive at the end-of-run fetch, so the window degrades
to a no-op capture — live streaming is what makes windowed capture real.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from collections import defaultdict


def capture(tag, run_fn, out_root):
    """Run ``run_fn`` under the profiler; return the capture directory."""
    import shutil

    import jax

    tdir = os.path.join(out_root, tag)
    # start clean: the profiler appends new session dirs, and parse_trace
    # globs recursively — stale captures would silently mix into the
    # aggregation (observed: a re-capture summed two generations of ops).
    # A rmtree failure must be LOUD for the same reason.
    if os.path.exists(tdir):
        shutil.rmtree(tdir)
    os.makedirs(tdir, exist_ok=True)
    jax.profiler.start_trace(tdir)
    try:
        run_fn()
    finally:
        jax.profiler.stop_trace()
    return tdir


def parse_trace(tdir):
    """Aggregate complete events from the Perfetto trace.json.gz files:
    {track_name: {op_name: total_us}}."""
    out = defaultdict(lambda: defaultdict(float))
    for path in glob.glob(os.path.join(
            tdir, "**", "*.trace.json.gz"), recursive=True):
        with gzip.open(path, "rt") as f:
            data = json.load(f)
        events = data.get("traceEvents", [])
        # map (pid, tid) -> track name from metadata events
        pids = {}
        tids = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pids[e.get("pid")] = e["args"].get("name", "")
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                tids[(e.get("pid"), e.get("tid"))] = e["args"].get("name", "")
        for e in events:
            if e.get("ph") != "X":
                continue
            pname = pids.get(e.get("pid"), "")
            tname = tids.get((e.get("pid"), e.get("tid")), "")
            track = f"{pname}/{tname}".strip("/")
            out[track][e.get("name", "?")] += float(e.get("dur", 0.0))
    return {k: dict(v) for k, v in out.items()}


def device_table(tracks, top=18):
    """The device-side op table: the track(s) that look like TPU op
    streams (XLA ops land on '/device:TPU... XLA Ops'-style threads).
    Control-flow container events (while/cond shells) are excluded — their
    durations INCLUDE their children and would double-count every loop
    body op."""
    rows = []
    for track, ops in tracks.items():
        low = track.lower()
        if not ("tpu" in low or "device" in low):
            continue
        if "xla op" not in low and "step" not in low and "ops" not in low:
            continue
        for name, us in ops.items():
            if name.split(".")[0] in ("while", "cond", "conditional"):
                continue
            rows.append((track, name, us))
    rows.sort(key=lambda r: -r[2])
    return rows[:top], sum(r[2] for r in rows)


def parse_profile_flag(value: str):
    """``--profile=DIR`` or ``--profile=DIR,START,STOP`` →
    (dir, start_round|None, stop_round|None)."""
    parts = str(value).split(",")
    if len(parts) == 1:
        return parts[0], None, None
    if len(parts) != 3:
        raise ValueError(
            f"--profile takes DIR or DIR,START,STOP (round window), got "
            f"{value!r}")
    try:
        start, stop = int(parts[1]), int(parts[2])
    except ValueError:
        raise ValueError(
            f"--profile window bounds must be round numbers, got {value!r}")
    if start < 1 or stop <= start:
        raise ValueError(
            f"--profile window needs 1 <= START < STOP, got {value!r}")
    return parts[0], start, stop


class RoundWindowProfiler:
    """Bus subscriber that traces the rounds in ``[start, stop)``.

    The trace starts at the first ``round_eval`` with t >= start and stops
    at the first with t >= stop (round numbers are only observable at the
    ``debugIter`` eval cadence, so the window snaps to it).  One window
    per process: the first algorithm whose trajectory crosses it wins —
    production runs profile one algorithm, and a second overlapping trace
    session would make jax.profiler raise.
    """

    def __init__(self, outdir: str, start_round: int, stop_round: int):
        self.outdir = outdir
        self.start_round = start_round
        self.stop_round = stop_round
        self.active = False
        self.done = False

    def __call__(self, rec: dict):
        ev = rec.get("event")
        if ev == "round_eval" and not self.done:
            t = rec.get("t")
            if not isinstance(t, int):
                return
            if not self.active and t >= self.start_round:
                import jax

                os.makedirs(self.outdir, exist_ok=True)
                jax.profiler.start_trace(self.outdir)
                self.active = True
            if self.active and t >= self.stop_round:
                self.close()
        elif ev in ("run_end", "divergence"):
            # a run ending inside the window must still flush the capture
            self.close()

    def close(self):
        if self.active:
            import jax

            jax.profiler.stop_trace()
            self.active = False
            self.done = True
