"""In-loop telemetry: structured event stream, run manifest, metrics
textfile, and round-windowed profiler capture.

The paper's certificate story (duality gap per comm-round) is only as
credible as the ability to OBSERVE it while a run is in flight — and since
the drive* ladder went device-resident, the fast path surfaces nothing
until its final host sync.  This package closes that gap:

- :mod:`cocoa_tpu.telemetry.events` — the host-side event bus: typed,
  ordered records (``run_start`` with a full config manifest,
  ``round_eval``, ``sigma_backoff``, ``checkpoint_write``, ``restart``,
  ``divergence``, ``run_end``) appended to a JSONL sink and fanned out to
  subscribers; plus the device bridge glue (``DeviceTap`` /
  ``io_callback_supported``) that streams each eval out of the
  device-resident ``lax.while_loop`` (solvers/base.py).
- :mod:`cocoa_tpu.telemetry.metrics` — a Prometheus-style textfile
  refreshed on every event (rounds_total, evals_total,
  sigma_backoffs_total, restarts_total, last_gap, round_seconds
  histogram) — what elastic.py's supervisor and external scrapers watch.
- :mod:`cocoa_tpu.telemetry.schema` — the JSONL schema checker shared by
  the tests and CI (event streams, trajectory dumps, benchmark results).
- :mod:`cocoa_tpu.telemetry.profiling` — the profiler capture/summarize
  core (promoted from benchmarks/trace.py so production runs and
  benchmarks share one implementation) and the round-windowed
  ``--profile=<dir>,<start>,<stop>`` capture riding the event stream.
- :mod:`cocoa_tpu.telemetry.tracing` — gang-wide span tracing
  (``--trace``): per-phase, per-worker timed spans emitted through the
  bus as typed ``span`` events (ingest passes, KV exchanges, local-solve
  super-blocks, eval windows, checkpoints, supervisor generations).
- :mod:`cocoa_tpu.telemetry.trace_report` — the offline assembler:
  merges a gang's per-process span streams, exports Perfetto/Chrome
  trace JSON, computes the per-round critical path, and attributes
  stragglers worker × phase by slack.
- :mod:`cocoa_tpu.telemetry.recorder` — the crash flight recorder: a
  bounded ring of recent events dumped to ``<events>.flightrec`` on
  divergence/exception/SIGTERM, plus the supervisor-side dump of a
  SIGKILLed worker's stream tail.

Soundness: telemetry is side-effect-only.  The device bridge adds an
ordered ``io_callback`` that READS the eval row the loop already
computes; the loop-carried compute state (w, alpha, sched) is untouched,
so a telemetry-on run is bit-identical to a telemetry-off run
(tests/test_telemetry.py pins this).
"""

from cocoa_tpu.telemetry import events  # noqa: F401
from cocoa_tpu.telemetry.events import get_bus  # noqa: F401
