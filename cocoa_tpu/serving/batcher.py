"""Adaptive micro-batching: admit under a latency budget, pad to a
static bucket, dispatch once.

The policy (docs/DESIGN.md §17): a request's latency is
``admission wait + device time``, and throughput is real rows per
compiled dispatch.  The batcher therefore

- **waits only while the SLA can afford it** — the admission window for
  a batch closes at ``oldest.t_enq + (sla - device_est - margin)``,
  where ``device_est`` is a per-bucket EWMA of measured dispatch+fetch
  time.  Traffic bursts fill big buckets; a lone request ships almost
  immediately.
- **picks the tightest bucket** — the smallest static bucket that holds
  the admitted requests maximizes fill ratio (real/padded rows), which
  is the throughput maximizer under one-compile-per-bucket.

Instrumentation: the admission wait and the device dispatch are
separate spans (``serve_admit`` / ``serve_score``), so
``trace_report`` attributes queueing vs device time per batch; every
batch emits one typed ``serve_request`` event (n, bucket, fill ratio,
queue/device seconds, per-request latency max/mean, the model round it
was answered by).

Swap interaction: the batcher reads ``slots.current()`` ONCE per batch
— the whole bucket is answered by exactly one model generation, and a
swap that lands mid-admission simply takes effect at the next batch
boundary.  Nothing blocks, nothing drops.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from cocoa_tpu.serving.scorer import pick_bucket

# fraction of the SLA reserved against estimate error + fetch jitter:
# the admission window never spends the whole budget on waiting
_SLA_SAFETY = 0.25
_EWMA = 0.3
# early-ship rule: once the queue has been idle this long, stop waiting
# for stragglers — under light traffic latency collapses to roughly
# device time + one idle gap, while a burst (requests arriving
# back-to-back) keeps admitting until the bucket or the SLA window
# closes.  This is what makes the batcher ADAPTIVE rather than a fixed
# timer: the wait is bounded by the SLA but paid only while it buys fill
_IDLE_GAP_S = 0.002


class PendingQuery:
    """One in-flight request: parsed arrays in, margin (or error) out."""

    __slots__ = ("idx", "val", "tenant", "t_enq", "done", "margin",
                 "error", "model_round", "served_dtype", "traced",
                 "queue_s", "device_s", "bucket", "gap_age_s")

    def __init__(self, idx, val, tenant=None, traced=False):
        self.idx = idx
        self.val = val
        self.tenant = tenant
        self.t_enq = time.monotonic()
        self.done = threading.Event()
        self.margin = None
        self.error = None
        self.model_round = None
        self.served_dtype = None
        # sampled query tracing (docs/DESIGN.md §22): a traced query
        # gets its batch's hop breakdown stamped at completion — the
        # untraced hot path pays one boolean test per query, nothing
        # else (the bit-identity / ≤5%-overhead contract)
        self.traced = traced
        self.queue_s = None
        self.device_s = None
        self.bucket = None
        self.gap_age_s = None

    def result(self, timeout: Optional[float] = None) -> float:
        if not self.done.wait(timeout):
            raise TimeoutError("serving batch never completed")
        if self.error is not None:
            raise self.error
        return self.margin


class MicroBatcher:
    """Owns the scoring thread: drains the request queue into padded
    buckets and dispatches them through the compiled scorer."""

    def __init__(self, scorer, slots, sla_s: float = 0.05,
                 algorithm: str = "serve", calibration=None):
        slots_sd = getattr(slots, "serve_dtype", "f32")
        scorer_sd = getattr(scorer, "serve_dtype", "f32")
        if slots_sd != scorer_sd:
            raise ValueError(
                f"serve dtype mismatch: ModelSlots publishes "
                f"{slots_sd} model forms but BatchScorer compiled for "
                f"{scorer_sd} — construct both with the same dtype= "
                f"(the CLI wires --serveDtype={slots_sd!s} into both)")
        self.scorer = scorer
        self.slots = slots
        self.sla_s = float(sla_s)
        self.algorithm = algorithm
        # ring of recent real queries the per-swap quantization
        # certificate is computed over (serving/quantize.py)
        self._calibration = calibration
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._device_est = {b: 0.0 for b in scorer.buckets}
        self.batches_total = 0
        self.requests_total = 0
        self.slots_total = 0    # Σ bucket — the fill-ratio denominator
        self.failed_total = 0   # requests that DIED (scorer raised);
        # rejected-at-parse queries never reach the batcher
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cocoa-serve-batcher")
        self._thread.start()

    def submit(self, idx, val, tenant=None, traced=False) -> PendingQuery:
        """Enqueue one parsed query; returns its pending handle.

        ``tenant`` is the catalogue row the query scores against (fleet
        serving, docs/DESIGN.md §21) — None on a single-model scorer.
        ``traced`` marks a sampled query (--traceSample): its batch's
        hop breakdown is stamped onto the handle at completion."""
        if self._calibration is not None:
            self._calibration.record(idx, val)
        pend = PendingQuery(idx, val, tenant, traced=traced)
        self._q.put(pend)
        return pend

    def score_sync(self, idx, val, timeout: Optional[float] = None,
                   tenant=None):
        """Submit + wait: the in-process client the bench and tests use."""
        return self.submit(idx, val, tenant=tenant).result(timeout)

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        self._q.put(None)   # wake the blocking get
        self._thread.join(timeout)

    # --- the scoring thread --------------------------------------------------

    def _admit(self, first) -> list:
        """Gather requests behind ``first`` while the SLA affords it."""
        max_bucket = self.scorer.buckets[-1]
        batch = [first]
        est = max(self._device_est.values())
        window = max(0.0, self.sla_s * (1.0 - _SLA_SAFETY) - est)
        deadline = first.t_enq + window
        while len(batch) < max_bucket:
            remaining = deadline - time.monotonic()
            try:
                nxt = (self._q.get_nowait() if remaining <= 0
                       else self._q.get(timeout=min(remaining,
                                                    _IDLE_GAP_S)))
            except queue.Empty:
                break   # queue went idle (or the SLA window closed):
                        # waiting longer buys latency, not fill
            if nxt is None:   # stop sentinel — score what we hold
                self._q.put(None)
                break
            batch.append(nxt)
        return batch

    def _run(self):
        import numpy as np

        from cocoa_tpu.analysis import sanitize
        from cocoa_tpu.telemetry import events as tele_events
        from cocoa_tpu.telemetry import tracing

        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                continue
            with tracing.span("serve_admit"):
                batch = self._admit(first)
            bucket = pick_bucket(len(batch), self.scorer.buckets)
            # one model per batch: the (w, scale, info) triple is
            # published atomically, so the scale always matches the
            # buffer it scales
            w_dev, scale, info = self.slots.current()
            t_score = time.monotonic()
            queue_s = t_score - first.t_enq
            try:
                with tracing.span("serve_score", bucket=bucket,
                                  n=len(batch)):
                    idx, val, hot = self.scorer.assemble(
                        [(p.idx, p.val) for p in batch], bucket)
                    # catalogue scorer: every query carries its tenant
                    # row (server.py validated the range at parse time);
                    # padded slots gather tenant 0 against all-zero
                    # values, contributing exactly 0
                    tenant = None
                    if getattr(self.scorer, "n_tenants", None) \
                            is not None:
                        tenant = self.scorer.assemble_tenants(
                            [p.tenant or 0 for p in batch], bucket)
                    out = self.scorer.score(w_dev, idx, val, hot,
                                            scale, tenant)
                    # the ONE sanctioned device→host crossing per batch
                    # (the zero-unintended-transfers contract)
                    with sanitize.intended_fetch("serve_fetch"):
                        margins = np.asarray(out)
            except Exception as e:   # answer the callers, keep serving
                self.failed_total += len(batch)
                for p in batch:
                    p.error = e
                    p.done.set()
                continue
            device_s = time.monotonic() - t_score
            est = self._device_est[bucket]
            self._device_est[bucket] = (device_s if est == 0.0
                                        else (1 - _EWMA) * est
                                        + _EWMA * device_s)
            done = time.monotonic()
            lats = [done - p.t_enq for p in batch]
            # the form that ANSWERED, derived from the captured buffer
            # (not a racy slots attribute read): how a client observes
            # a certificate fallback, the same way `round` observes a
            # hot-swap
            served = {"uint32": "bf16", "int32": "int8"} \
                .get(str(np.dtype(w_dev.dtype)), "f32")
            gap_age = None   # computed once per batch, only if traced
            for r, p in enumerate(batch):
                p.margin = float(margins[r])
                p.model_round = info.round
                p.served_dtype = served
                if p.traced:
                    # the per-query hop breakdown a sampled trace
                    # reads back (server.py): admission queue vs this
                    # batch's device dispatch, the bucket it padded
                    # into, and the answering certificate's age
                    if gap_age is None:
                        gap_age = max(0.0, time.time()
                                      - info.birth_ts)
                    p.queue_s = t_score - p.t_enq
                    p.device_s = device_s
                    p.bucket = bucket
                    p.gap_age_s = gap_age
                p.done.set()
            self.batches_total += 1
            self.requests_total += len(batch)
            self.slots_total += bucket
            bus = tele_events.get_bus()
            if bus.active():
                bus.emit(
                    "serve_request", algorithm=self.algorithm,
                    n=len(batch), bucket=bucket,
                    fill_ratio=len(batch) / bucket, queue_s=queue_s,
                    device_s=device_s, latency_max_s=max(lats),
                    latency_mean_s=sum(lats) / len(lats),
                    model_round=info.round)
