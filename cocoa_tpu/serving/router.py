"""The fleet front door: tenant-aware routing + admission shedding
over N scorer replicas (docs/DESIGN.md §21).

The router speaks the SAME line protocol as a single
:class:`~cocoa_tpu.serving.server.MarginServer` (one JSON response line
per request line, ``tenant=<id>;`` prefix, ``shutdown``) so a client
never knows whether it hit one process or a fleet.  Per request line it

- **routes**: ``rr`` round-robins over live replicas; ``tenant`` pins a
  tenant to ``tenant % len(replicas)`` (stable affinity keeps one
  tenant's traffic filling one replica's buckets; a dead home replica
  probes forward to the next live one, so affinity degrades, never
  fails).  Untagged lines always round-robin.
- **sheds before the SLA breaks**: each replica carries an inflight
  count and an EWMA of observed request latency; a line whose cheapest
  projected wait ``(inflight + 1) * ewma`` exceeds the shed budget
  (``_SHED_HEADROOM``  × SLA) on EVERY live replica is refused
  immediately — ``{"error": "shed: ...", "shed": true}`` plus a typed
  ``serve_shed`` event — instead of queueing into a latency violation.
  Shedding is an ADMISSION decision: once a line is admitted it is
  never shed, only requeued.  An idle replica (zero inflight) always
  admits — admitted lines are what update the estimate, so the idle
  probe is how a fleet recovers from a stale post-overload EWMA
  instead of shedding on it forever.
- **requeues on replica death**: a connection that dies mid-request
  (SIGKILLed replica, reset, timeout) marks the replica dead (typed
  ``replica_state`` event), and the line replays against another live
  replica (``requeue`` state, ``requeued=1``).  A killed replica costs
  latency, never a failed query: with no live replica the line WAITS
  (bounded by ``_REVIVE_WAIT_S``) for the fleet monitor to respawn one.

The router holds no model state and no JAX — it is pure sockets and
bookkeeping, so it composes with in-process thread replicas (tests) and
spawned CLI replicas (:mod:`cocoa_tpu.serving.fleet`) identically.
"""

from __future__ import annotations

import itertools
import json
import re
import socket
import socketserver
import threading
import time
from typing import Optional

# client-chosen trace ids (docs/DESIGN.md §22) — same grammar the
# replica enforces (serving/server.py); a prefix that fails it is left
# on the line so the replica rejects it with the numbers
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{1,32}$")

# fraction of the SLA the projected wait may consume before the router
# sheds; the remainder absorbs estimate error + the hop itself
_SHED_HEADROOM = 0.8
_EWMA = 0.3
# how long an admitted line waits for ANY live replica (fleet restart
# window) before it is allowed to fail — the zero-failed-queries pin
# assumes the monitor respawns well inside this
_REVIVE_WAIT_S = 30.0
_CONNECT_TIMEOUT_S = 5.0
_REPLY_TIMEOUT_S = 30.0


class Replica:
    """One scorer replica as the router sees it: an address, a pool of
    idle connections, and the load/latency bookkeeping the shed and
    route decisions read."""

    def __init__(self, name: str, address):
        self.name = str(name)
        self.address = (address[0], int(address[1]))
        self.live = True
        self.inflight = 0
        self.ewma_s = 0.0
        self.lock = threading.Lock()
        self._idle = []   # pooled (sock, rfile) pairs

    def projected_wait_s(self) -> float:
        """What a new line would wait here: queue depth × observed
        per-line latency.  0.0 until the first observation — an
        unmeasured replica is never shed against."""
        return (self.inflight + 1) * self.ewma_s

    def acquire(self):
        with self.lock:
            if self._idle:
                return self._idle.pop()
        sock = socket.create_connection(self.address,
                                        timeout=_CONNECT_TIMEOUT_S)
        sock.settimeout(_REPLY_TIMEOUT_S)
        return sock, sock.makefile("rb")

    def release(self, conn):
        with self.lock:
            if self.live:
                self._idle.append(conn)
                return
        _close(conn)

    def close_all(self):
        with self.lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            _close(conn)


def _close(conn):
    sock, rfile = conn
    for c in (rfile, sock):
        try:
            c.close()
        except OSError:
            pass


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        for raw in self.rfile:
            try:
                line = raw.decode("utf-8", errors="replace").strip()
            except Exception:
                break
            if not line:
                continue
            if line == "shutdown":
                self._reply({"ok": "shutting down"})
                srv.initiate_shutdown()
                return
            self._reply(srv.router.answer_line(line))

    def _reply(self, obj):
        try:
            payload = obj if isinstance(obj, (bytes, bytearray)) \
                else (json.dumps(obj) + "\n").encode()
            self.wfile.write(payload)
            self.wfile.flush()
        except OSError:
            pass


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    router: "Router" = None

    def initiate_shutdown(self):
        threading.Thread(target=self.shutdown, daemon=True).start()


class Router:
    """Front-door TCP server routing request lines across replicas."""

    ROUTES = ("rr", "tenant")

    def __init__(self, replicas, sla_s: float = 0.05,
                 route: str = "rr", host: str = "127.0.0.1",
                 port: int = 0, algorithm: str = "serve",
                 trace_sample: int = 0):
        if route not in self.ROUTES:
            raise ValueError(f"unknown route policy {route!r}: "
                             f"expected one of {self.ROUTES}")
        self.replicas = [r if isinstance(r, Replica) else Replica(*r)
                         for r in replicas]
        if not self.replicas:
            raise ValueError("a router needs at least one replica")
        self.sla_s = float(sla_s)
        self.route = route
        self.algorithm = algorithm
        # sampled query tracing (--traceSample, docs/DESIGN.md §22):
        # 1 in N ``trace=``-prefixed lines is traced end to end — the
        # router strips the prefix from the rest (the replica then does
        # zero trace work and answers byte-identically to an untraced
        # line) and re-stamps sampled lines with its own queue time so
        # the replica knows the line is already sampled upstream
        self.trace_sample = int(trace_sample)
        self._trace_seen = itertools.count()
        self._rr = 0
        self._lock = threading.Lock()
        self.forwarded_total = 0
        self.shed_total = 0
        self.requeue_total = 0
        self.failed_total = 0   # lines that exhausted every recourse —
        # the fleet pin holds this at 0 even under replica SIGKILL
        self._tcp = _TCPServer((host, port), _Handler,
                               bind_and_activate=True)
        self._tcp.router = self

    # --- fleet-facing state ------------------------------------------------

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves here."""
        return self._tcp.server_address

    def replicas_live(self) -> int:
        return sum(1 for r in self.replicas if r.live)

    def mark_dead(self, rep: "Replica", state: str = "dead"):
        with self._lock:
            was_live = rep.live
            rep.live = False
        rep.close_all()
        if was_live:
            self._emit_replica(rep, state)

    def mark_live(self, name: str, address):
        """Fleet monitor callback after a respawn: the replica returns
        (possibly on a new port) and rejoins routing."""
        for rep in self.replicas:
            if rep.name == name:
                with self._lock:
                    rep.address = (address[0], int(address[1]))
                    rep.live = True
                    rep.inflight = 0
                self._emit_replica(rep, "live")
                return rep
        raise KeyError(f"unknown replica {name!r}: the fleet knows "
                       f"{[r.name for r in self.replicas]}")

    def emit_initial_state(self):
        """One ``replica_state`` "live" event per replica at startup —
        what makes the ``cocoa_serve_replicas_live`` gauge render from
        the first metrics write, not the first death."""
        for rep in self.replicas:
            if rep.live:
                self._emit_replica(rep, "live")

    def _emit_replica(self, rep, state, requeued: int = 0,
                      trace_id: Optional[str] = None):
        from cocoa_tpu.telemetry import events as tele_events

        bus = tele_events.get_bus()
        if bus.active():
            bus.emit("replica_state", algorithm=self.algorithm,
                     replica=rep.name, state=state,
                     replicas_live=self.replicas_live(),
                     requeued=requeued, trace_id=trace_id)

    # --- routing -----------------------------------------------------------

    def _peel_trace(self, line: str):
        """Strip the optional ``trace=<id>;`` prefix (docs/DESIGN.md
        §22); returns ``(trace_id_or_None, rest)``.  A prefix that
        fails the id grammar is left on the line untouched — the
        replica rejects it with the numbers, keeping the router a pure
        relay for malformed input."""
        if not line.startswith("trace="):
            return None, line
        head, sep, rest = line.partition(";")
        tid = head[len("trace="):]
        if not sep or not _TRACE_ID_RE.match(tid):
            return None, line
        return tid, rest

    def _sample(self) -> bool:
        """Deterministic 1-in-N gate over trace-prefixed lines (the
        first is always sampled); 0 disarms tracing.  The counter is an
        ``itertools.count`` — atomic in CPython without taking the
        router lock, so the gate costs the hot path nothing."""
        n = self.trace_sample
        if n <= 0:
            return False
        return next(self._trace_seen) % n == 0

    def _peel_tenant(self, line: str) -> Optional[int]:
        if not line.startswith("tenant="):
            return None
        head = line.partition(";")[0]
        try:
            return int(head[len("tenant="):])
        except ValueError:
            return None   # the replica rejects it with the numbers

    def _live(self, exclude=()):
        return [r for r in self.replicas
                if r.live and r.name not in exclude]

    def _pick(self, tenant, exclude=()):
        live = self._live(exclude)
        if not live:
            return None
        if self.route == "tenant" and tenant is not None:
            # stable home slot; a dead home probes forward to the next
            # live replica, so affinity degrades instead of failing
            home = tenant % len(self.replicas)
            for off in range(len(self.replicas)):
                rep = self.replicas[(home + off) % len(self.replicas)]
                if rep.live and rep.name not in exclude:
                    return rep
            return None
        with self._lock:
            self._rr += 1
            start = self._rr
        return live[start % len(live)]

    def _shed(self, line, tenant, est_s, inflight,
              trace_id: Optional[str] = None):
        self.shed_total += 1
        from cocoa_tpu.telemetry import events as tele_events

        bus = tele_events.get_bus()
        if bus.active():
            # trace_id: the exemplar — a shed spike in the counter now
            # names concrete refused queries to go look at
            bus.emit("serve_shed", algorithm=self.algorithm,
                     route=self.route, tenant=tenant,
                     inflight=inflight, est_s=est_s,
                     sla_s=self.sla_s, trace_id=trace_id)
        return {"error": f"shed: projected wait {est_s * 1e3:.1f} ms "
                         f"exceeds the shed budget "
                         f"{self.sla_s * _SHED_HEADROOM * 1e3:.1f} ms "
                         f"(SLA {self.sla_s * 1e3:g} ms) on every "
                         f"live replica — back off and retry",
                "shed": True}

    def answer_line(self, line: str):
        """Route one request line; returns the replica's raw response
        bytes (relayed verbatim) or a router-level JSON object."""
        t_recv = time.monotonic()
        trace_id, line = self._peel_trace(line)
        sampled = trace_id is not None and self._sample()
        tenant = self._peel_tenant(line)
        # --- admission: shed only if EVERY live replica projects past
        # the budget (an unmeasured replica projects 0.0 → admits).
        # An IDLE replica (zero inflight) also always admits: the EWMA
        # is only updated by admitted lines, so after an overload burst
        # the estimate stays inflated until something re-measures it —
        # the idle probe is what lets the fleet recover instead of
        # shedding forever on a stale estimate.
        budget = self.sla_s * _SHED_HEADROOM
        rep = self._pick(tenant)
        if rep is not None and rep.projected_wait_s() > budget:
            best = min(self._live(), key=Replica.projected_wait_s)
            if best.projected_wait_s() > budget and best.inflight > 0:
                return self._shed(line, tenant, best.projected_wait_s(),
                                  best.inflight, trace_id=trace_id)
            rep = best
        # --- admitted: forward, requeueing past dead replicas; never
        # fail while a live replica exists or can still come back
        tried = set()
        requeues = 0
        deadline = time.monotonic() + _REVIVE_WAIT_S
        while True:
            if rep is None:
                if time.monotonic() > deadline:
                    self.failed_total += 1
                    return {"error": "no live replica: the whole "
                                     "fleet is down and none came "
                                     f"back within {_REVIVE_WAIT_S:g}"
                                     "s"}
                time.sleep(0.05)
                tried.clear()   # a respawn may reuse the name
                rep = self._pick(tenant, exclude=tried)
                continue
            t_fwd = time.monotonic()
            fwd_line = line
            if sampled:
                # re-stamp per attempt: the prefix carries THIS line's
                # accumulated router queue (admission + revive waits)
                # in microseconds, and its colon form tells the replica
                # the line is already sampled — the replica stamps its
                # hops into the response and emits nothing
                fwd_line = (f"trace={trace_id}:"
                            f"{int((t_fwd - t_recv) * 1e6)};{line}")
            resp = self._forward(rep, fwd_line)
            if resp is not None:
                self.forwarded_total += 1
                if sampled:
                    self._emit_trace(trace_id, tenant, rep, resp,
                                     t_recv, t_fwd, requeues)
                return resp
            # replica died under us: dead + requeue, stats first so
            # the gauges already show the requeue when the event lands
            self.mark_dead(rep)
            self.requeue_total += 1
            requeues += 1
            self._emit_replica(rep, "requeue", requeued=1,
                               trace_id=trace_id)
            tried.add(rep.name)
            rep = self._pick(tenant, exclude=tried)

    def _emit_trace(self, trace_id, tenant, rep, resp, t_recv, t_fwd,
                    requeues):
        """The fleet-mode ``query_trace`` event: the router saw the
        whole lifecycle, so it owns the emission.  Replica-side hops
        ride back in the response's ``"trace"`` object (relayed to the
        client verbatim); the forward hop is the wire + relay residual
        once those are subtracted."""
        from cocoa_tpu.telemetry import events as tele_events

        bus = tele_events.get_bus()
        if not bus.active():
            return
        t_reply = time.monotonic()
        tobj = None
        try:
            reply = json.loads(resp.decode("utf-8", errors="replace"))
            entries = reply if isinstance(reply, list) else [reply]
            for entry in entries:
                if isinstance(entry, dict) and "trace" in entry:
                    tobj = entry["trace"]
                    break
        except (ValueError, AttributeError):
            pass   # a malformed reply still gets its router-side hops
        tobj = tobj if isinstance(tobj, dict) else {}
        replica_total = sum(tobj.get(k) or 0.0
                            for k in ("replica_queue_s", "device_s",
                                      "serialize_s"))
        bus.emit("query_trace", algorithm=self.algorithm,
                 trace_id=trace_id, tenant=tenant, replica=rep.name,
                 router_queue_s=t_fwd - t_recv,
                 forward_s=max(0.0, (t_reply - t_fwd) - replica_total),
                 replica_queue_s=tobj.get("replica_queue_s"),
                 device_s=tobj.get("device_s"),
                 serialize_s=tobj.get("serialize_s"),
                 total_s=t_reply - t_recv,
                 bucket=tobj.get("bucket"),
                 model_round=tobj.get("round"),
                 gap_age_s=tobj.get("gap_age_s"),
                 dtype=tobj.get("dtype"), requeues=requeues)

    def _forward(self, rep: Replica, line: str):
        """One attempt against one replica; None means the replica is
        gone (caller requeues)."""
        t0 = time.monotonic()
        with self._lock:
            rep.inflight += 1
        try:
            conn = rep.acquire()
        except OSError:
            with self._lock:
                rep.inflight -= 1
            return None
        sock, rfile = conn
        try:
            sock.sendall((line + "\n").encode())
            raw = rfile.readline()
            if not raw:          # EOF: the replica process died
                raise OSError("replica closed the connection")
        except OSError:
            _close(conn)
            with self._lock:
                rep.inflight -= 1
            return None
        took = time.monotonic() - t0
        with self._lock:
            rep.inflight -= 1
            rep.ewma_s = (took if rep.ewma_s == 0.0
                          else (1 - _EWMA) * rep.ewma_s + _EWMA * took)
        rep.release(conn)
        return raw   # relayed verbatim — bytes already end in \n

    # --- lifecycle ---------------------------------------------------------

    def serve_forever(self, poll_interval: float = 0.2):
        self._tcp.serve_forever(poll_interval=poll_interval)

    def stop(self):
        self._tcp.initiate_shutdown()

    def close(self):
        self._tcp.server_close()
        for rep in self.replicas:
            rep.close_all()
