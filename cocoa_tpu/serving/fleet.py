"""Fleet lifecycle: spawn N scorer replica processes, watch them,
respawn the dead (docs/DESIGN.md §21).

Each replica is an ORDINARY single-process serve CLI
(``--serve=0`` on an ephemeral port) pointed at the same validated
checkpoint dir — the same binary a one-process deployment runs, which
is what keeps the fleet surface thin: models arrive per replica through
the watcher/hot-swap machinery, slabs and checkpoints read through the
same host-side caches (the memmap slab cache keeps host RSS at ~one
copy regardless of replica count), and the only NEW process is the
router in front.

:class:`ServeFleet` owns the subprocesses:

- ``start()`` spawns them and parses each replica's ``listening on
  host:port`` announce line (printed even under ``--quiet`` exactly so
  supervisors can do this);
- ``attach(router)`` starts the monitor thread: a replica whose
  process exits is marked dead on the router immediately (in-flight
  lines against it requeue, see router.py) and — with
  ``restart=True`` — respawned and re-registered under its old name,
  emitting the ``replica_state`` dead/live event pair;
- ``stop()`` tears everything down.

Tests that want a fleet without processes skip this module entirely:
:class:`~cocoa_tpu.serving.router.Router` takes any (name, address)
list, so in-process ``MarginServer`` threads compose the same way.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

_ANNOUNCE_RE = re.compile(r"listening on ([0-9.]+):([0-9]+)")
_POLL_S = 0.2


class ReplicaProc:
    """One spawned replica: its process, parsed address, restart count."""

    def __init__(self, name: str):
        self.name = name
        self.proc: Optional[subprocess.Popen] = None
        self.address: Optional[Tuple[str, int]] = None
        self.restarts = 0

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None


class ServeFleet:
    """Spawn, announce-parse, monitor and restart scorer replicas."""

    def __init__(self, base_argv: Sequence[str], n_replicas: int,
                 extra_argv_fn: Optional[Callable[[int], List[str]]]
                 = None, env: Optional[dict] = None,
                 start_timeout_s: float = 300.0, restart: bool = True,
                 echo: Optional[Callable[[str], None]] = None):
        """``base_argv`` is the per-replica CLI tail (everything after
        ``--serve=0`` — chkptDir, buckets, dtype...); ``extra_argv_fn``
        appends per-index flags (e.g. a per-replica events sink)."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got "
                             f"{n_replicas}")
        self.base_argv = list(base_argv)
        self.extra_argv_fn = extra_argv_fn
        self.env = dict(os.environ, **(env or {}))
        self.start_timeout_s = float(start_timeout_s)
        self.restart = restart
        self.echo = echo or (lambda s: None)
        self.replicas = [ReplicaProc(f"r{i}")
                         for i in range(n_replicas)]
        self._router = None
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # --- spawning ----------------------------------------------------------

    def _argv(self, i: int) -> List[str]:
        extra = self.extra_argv_fn(i) if self.extra_argv_fn else []
        return [sys.executable, "-m", "cocoa_tpu.cli", "--serve=0",
                *self.base_argv, *extra]

    def _spawn(self, rep: ReplicaProc, i: int):
        rep.proc = subprocess.Popen(
            self._argv(i), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=self.env)
        deadline = time.monotonic() + self.start_timeout_s
        head = []
        while True:
            line = rep.proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"replica {rep.name} exited before announcing "
                    f"(rc={rep.proc.poll()}); output:\n"
                    + "".join(head[-40:]))
            head.append(line)
            m = _ANNOUNCE_RE.search(line)
            if m:
                rep.address = (m.group(1), int(m.group(2)))
                break
            if time.monotonic() > deadline:
                rep.proc.kill()
                raise RuntimeError(
                    f"replica {rep.name} never announced within "
                    f"{self.start_timeout_s:g}s; output:\n"
                    + "".join(head[-40:]))
        # keep draining stdout so the pipe never fills and blocks the
        # replica; lines are handed to the echo hook (the CLI prefixes
        # and prints them, the bench discards them)
        threading.Thread(target=self._drain, args=(rep,),
                         daemon=True).start()
        self.echo(f"replica {rep.name} pid={rep.pid} "
                  f"port={rep.address[1]}")

    def _drain(self, rep: ReplicaProc):
        proc = rep.proc
        for line in proc.stdout:
            self.echo(f"[{rep.name}] {line.rstrip()}")

    def start(self) -> List[Tuple[str, Tuple[str, int]]]:
        """Spawn every replica; returns [(name, (host, port))] for the
        router."""
        for i, rep in enumerate(self.replicas):
            self._spawn(rep, i)
        return [(r.name, r.address) for r in self.replicas]

    # --- monitoring --------------------------------------------------------

    def attach(self, router):
        """Start the liveness monitor against ``router``."""
        self._router = router
        self._monitor = threading.Thread(target=self._watch,
                                         daemon=True,
                                         name="cocoa-fleet-monitor")
        self._monitor.start()

    def _watch(self):
        while not self._stop.is_set():
            for i, rep in enumerate(self.replicas):
                if rep.proc is None or rep.proc.poll() is None:
                    continue
                rc = rep.proc.returncode
                self.echo(f"replica {rep.name} died (rc={rc})")
                dead = next(r for r in self._router.replicas
                            if r.name == rep.name)
                self._router.mark_dead(dead)
                if not self.restart or self._stop.is_set():
                    rep.proc = None
                    continue
                try:
                    rep.restarts += 1
                    self._spawn(rep, i)
                    self._router.mark_live(rep.name, rep.address)
                except RuntimeError as e:
                    self.echo(f"replica {rep.name} respawn failed: "
                              f"{e}")
                    rep.proc = None
            self._stop.wait(_POLL_S)

    # --- teardown ----------------------------------------------------------

    def pids(self) -> List[Optional[int]]:
        return [r.pid for r in self.replicas]

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout)
        for rep in self.replicas:
            proc = rep.proc
            if proc is None or proc.poll() is not None:
                continue
            proc.terminate()
        deadline = time.monotonic() + timeout
        for rep in self.replicas:
            proc = rep.proc
            if proc is None:
                continue
            try:
                proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(5.0)
