"""The serving front end: a line-oriented TCP margin server.

Protocol (one JSON response line per request line):

- a request line is one query in the LIBSVM feature grammar
  (``idx:val idx:val ...``, 1-based ids), or several queries joined
  with ``;`` — a client-side batch, which the micro-batcher scores as
  one padded bucket;
- a CATALOGUE server (fleet serving, docs/DESIGN.md §21) additionally
  requires a ``tenant=<id>;`` prefix selecting the catalogue row the
  line's queries score against; responses then carry ``"tenant"``;
- an optional ``trace=<id>;`` prefix BEFORE the tenant prefix arms
  per-query distributed tracing (docs/DESIGN.md §22): the id is 1-32
  lowercase hex chars the CLIENT chose.  A sampled line (1 in
  ``--traceSample``, deterministic counter) gets a ``"trace"`` object
  on its first response entry — the id echoed back plus the per-hop
  seconds (admission queue, device, protocol parse/serialize) and the
  answering generation's round/gap-age/dtype — and, on a solo server,
  a typed ``query_trace`` event.  A ``trace=<id>:<us>;`` form (the
  colon part is the upstream router's queue stamp in microseconds)
  marks a line the fleet router already sampled: it is always traced
  and the ROUTER emits the event (it sees the whole lifecycle).
  Unsampled lines are answered byte-identically to untraced ones —
  the margin math never sees the prefix either way;
- the response is ``{"margin": m, "round": r, "dtype": d}`` per query
  (``round`` = the training round of the model generation that answered
  — how a client observes a hot-swap; ``dtype`` = the model form that
  answered, ``f32``/``bf16``/``int8`` — how a client observes a
  ``--serveDtype`` certificate fallback), a JSON array of those for a
  ``;`` batch,
  or ``{"error": "..."}`` with the numbers for a rejected query
  (rejections are per query: one bad query in a batch fails only
  itself);
- ``shutdown`` stops the whole server (acknowledged first) — the
  clean-exit path the smoke tests and the CLI's signal handlers share.

Connections are thread-per-client (stdlib ThreadingTCPServer); the
batcher is what turns concurrent connections into filled buckets.  The
server owns no model state — it parses, submits, and relays — so
nothing here ever touches the swap path.
"""

from __future__ import annotations

import itertools
import json
import re
import socketserver
import threading
import time
from typing import Optional

from cocoa_tpu.serving.scorer import QueryError, parse_query

# client-chosen trace ids: lowercase hex, bounded — the id is echoed
# into responses and event streams, so the grammar is strict
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{1,32}$")


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        for raw in self.rfile:
            try:
                line = raw.decode("utf-8", errors="replace").strip()
            except Exception:
                break
            if not line:
                continue
            if line == "shutdown":
                self._reply({"ok": "shutting down"})
                srv.initiate_shutdown()
                return
            self._reply(srv.margin_server.answer_line(line))

    def _reply(self, obj):
        try:
            self.wfile.write((json.dumps(obj) + "\n").encode())
            self.wfile.flush()
        except OSError:
            pass   # client went away; its answers are already computed


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    margin_server: "MarginServer" = None

    def initiate_shutdown(self):
        # shutdown() blocks until serve_forever exits — never call it
        # from a handler (or signal) frame that serve_forever is waiting
        # on; hand it to a throwaway thread
        threading.Thread(target=self.shutdown, daemon=True).start()


class MarginServer:
    """Glue: sockets in front, the micro-batcher behind."""

    def __init__(self, batcher, num_features: int, max_nnz: int,
                 host: str = "127.0.0.1", port: int = 0,
                 n_tenants=None, trace_sample: int = 0,
                 algorithm: str = "serve"):
        self.batcher = batcher
        self.num_features = int(num_features)
        self.max_nnz = int(max_nnz)
        # catalogue mode (fleet serving, docs/DESIGN.md §21): queries
        # carry a ``tenant=<id>;`` prefix selecting their catalogue row
        self.n_tenants = None if n_tenants is None else int(n_tenants)
        # sampled query tracing (--traceSample, docs/DESIGN.md §22):
        # 1 in N ``trace=``-prefixed lines is traced; 0 disarms — the
        # prefix is peeled and ignored, answers byte-identical
        self.trace_sample = int(trace_sample)
        self.algorithm = algorithm
        self._trace_seen = itertools.count()
        self._tcp = _TCPServer((host, port), _Handler,
                               bind_and_activate=True)
        self._tcp.margin_server = self

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves here."""
        return self._tcp.server_address

    def _peel_trace(self, line: str):
        """Split the optional ``trace=<id>[:<us>];`` prefix off a
        request line (docs/DESIGN.md §22); returns
        ``((trace_id, router_queue_s_or_None), rest)`` or
        ``(None, line)``.  The colon form is the fleet router's mark:
        the line was already sampled upstream and the router will emit
        the ``query_trace`` event — this server only stamps its hops
        into the response."""
        if not line.startswith("trace="):
            return None, line
        head, sep, rest = line.partition(";")
        if not sep:
            raise QueryError(
                "trace prefix without a query: expected "
                f"'trace=<id>[:<us>];<query>[;<query>...]', got "
                f"{line!r}")
        body = head[len("trace="):]
        tid, colon, stamp = body.partition(":")
        if not _TRACE_ID_RE.match(tid):
            raise QueryError(
                f"malformed trace id {tid!r}: expected 1-32 lowercase "
                f"hex chars")
        rq_s = None
        if colon:
            try:
                rq_s = int(stamp) / 1e6
            except ValueError:
                raise QueryError(
                    f"malformed trace hop stamp {stamp!r}: expected "
                    f"integer microseconds after ':'")
        return (tid, rq_s), rest

    def _sample(self) -> bool:
        """The deterministic 1-in-N gate: the first trace-prefixed
        line is always sampled (test-friendly), then every Nth.  0
        disarms tracing entirely."""
        n = self.trace_sample
        if n <= 0:
            return False
        return next(self._trace_seen) % n == 0

    def _peel_tenant(self, line: str):
        """Split the optional ``tenant=<id>;`` prefix off a request
        line; returns (tenant_or_None, rest) or raises QueryError with
        the numbers.  The prefix applies to EVERY ``;``-joined query on
        the line (a client batch is one tenant's batch — the router
        groups by tenant, so cross-tenant mixing happens server-side in
        the bucket, not in the protocol)."""
        tenant = None
        if line.startswith("tenant="):
            head, sep, rest = line.partition(";")
            if not sep:
                raise QueryError(
                    "tenant prefix without a query: expected "
                    "'tenant=<id>;<query>[;<query>...]', got "
                    f"{line!r}")
            try:
                tenant = int(head[len("tenant="):])
            except ValueError:
                raise QueryError(
                    f"malformed tenant prefix {head!r}: expected "
                    f"'tenant=<id>' with an integer id")
            line = rest
        if tenant is None and self.n_tenants is not None:
            raise QueryError(
                f"this server serves a catalogue of "
                f"{self.n_tenants} tenant models — prefix queries "
                f"with 'tenant=<id>;' (id in [0, {self.n_tenants}))")
        if tenant is not None and self.n_tenants is None:
            raise QueryError(
                "tenant prefix on a single-model server: this server "
                "serves one model, not a catalogue — drop the "
                "'tenant=' prefix (catalogue serving needs a (T, d) "
                "checkpoint, docs/DESIGN.md §21)")
        if tenant is not None and not 0 <= tenant < self.n_tenants:
            raise QueryError(
                f"tenant {tenant} out of range: this catalogue "
                f"serves {self.n_tenants} tenants (ids 0.."
                f"{self.n_tenants - 1})")
        return tenant, line

    def answer_line(self, line: str):
        """Parse one request line, submit through the batcher, wait for
        the batch, shape the JSON-able response."""
        t_line = time.monotonic()
        try:
            trace, line = self._peel_trace(line)
            tenant, line = self._peel_tenant(line)
        except QueryError as e:
            return {"error": str(e)}
        traced = emit_here = False
        if trace is not None:
            if trace[1] is not None:
                traced = True        # sampled upstream by the router,
                                     # which also emits the event
            elif self._sample():
                traced = emit_here = True
        texts = [t for t in line.split(";") if t.strip()]
        pendings = []
        for text in texts:
            try:
                idx, val = parse_query(text, self.num_features,
                                       self.max_nnz)
            except QueryError as e:
                pendings.append({"error": str(e)})
                continue
            pendings.append(self.batcher.submit(idx, val,
                                                tenant=tenant,
                                                traced=traced))
        t_submitted = time.monotonic()
        out = []
        stamped = None   # the first answered query: its batch's hops
        for p in pendings:
            if isinstance(p, dict):
                out.append(p)
                continue
            try:
                margin = p.result(timeout=30.0)
                resp = {"margin": margin, "round": p.model_round,
                        "dtype": p.served_dtype}
                if tenant is not None:
                    resp["tenant"] = tenant
                out.append(resp)
                if stamped is None:
                    stamped = p
            except Exception as e:
                out.append({"error": f"{type(e).__name__}: {e}"})
        if traced:
            self._stamp_trace(trace, tenant, out, stamped, t_line,
                              t_submitted, emit_here)
        return out if len(texts) > 1 else out[0] if out \
            else {"error": "empty request line"}

    def _stamp_trace(self, trace, tenant, out, stamped, t_line,
                     t_submitted, emit_here):
        """Attach the ``"trace"`` hop breakdown to the line's first
        response entry and (solo mode) emit the ``query_trace`` event.
        ``serialize`` is the host protocol work — the line parse +
        submit leg, the hop the queue/device split cannot see (response
        shaping overlaps the batch wait, so it is not separable)."""
        serialize_s = t_submitted - t_line
        obj = {"id": trace[0],
               "replica_queue_s": None if stamped is None
               else stamped.queue_s,
               "device_s": None if stamped is None
               else stamped.device_s,
               "serialize_s": serialize_s,
               "bucket": None if stamped is None else stamped.bucket,
               "round": None if stamped is None
               else stamped.model_round,
               "gap_age_s": None if stamped is None
               else stamped.gap_age_s,
               "dtype": None if stamped is None
               else stamped.served_dtype}
        if out:
            out[0] = {**out[0], "trace": obj}
        if not emit_here:
            return
        from cocoa_tpu.telemetry import events as tele_events

        bus = tele_events.get_bus()
        if bus.active():
            bus.emit("query_trace", algorithm=self.algorithm,
                     trace_id=trace[0], tenant=tenant, replica=None,
                     router_queue_s=None, forward_s=None,
                     replica_queue_s=obj["replica_queue_s"],
                     device_s=obj["device_s"],
                     serialize_s=serialize_s,
                     total_s=time.monotonic() - t_line,
                     bucket=obj["bucket"], model_round=obj["round"],
                     gap_age_s=obj["gap_age_s"], dtype=obj["dtype"],
                     requeues=0)

    def serve_forever(self, poll_interval: float = 0.2):
        """Block until ``shutdown`` (protocol line or :meth:`stop`)."""
        self._tcp.serve_forever(poll_interval=poll_interval)

    def stop(self):
        self._tcp.initiate_shutdown()

    def close(self):
        self._tcp.server_close()
