"""The serving front end: a line-oriented TCP margin server.

Protocol (one JSON response line per request line):

- a request line is one query in the LIBSVM feature grammar
  (``idx:val idx:val ...``, 1-based ids), or several queries joined
  with ``;`` — a client-side batch, which the micro-batcher scores as
  one padded bucket;
- the response is ``{"margin": m, "round": r, "dtype": d}`` per query
  (``round`` = the training round of the model generation that answered
  — how a client observes a hot-swap; ``dtype`` = the model form that
  answered, ``f32``/``bf16``/``int8`` — how a client observes a
  ``--serveDtype`` certificate fallback), a JSON array of those for a
  ``;`` batch,
  or ``{"error": "..."}`` with the numbers for a rejected query
  (rejections are per query: one bad query in a batch fails only
  itself);
- ``shutdown`` stops the whole server (acknowledged first) — the
  clean-exit path the smoke tests and the CLI's signal handlers share.

Connections are thread-per-client (stdlib ThreadingTCPServer); the
batcher is what turns concurrent connections into filled buckets.  The
server owns no model state — it parses, submits, and relays — so
nothing here ever touches the swap path.
"""

from __future__ import annotations

import json
import socketserver
import threading
from typing import Optional

from cocoa_tpu.serving.scorer import QueryError, parse_query


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        for raw in self.rfile:
            try:
                line = raw.decode("utf-8", errors="replace").strip()
            except Exception:
                break
            if not line:
                continue
            if line == "shutdown":
                self._reply({"ok": "shutting down"})
                srv.initiate_shutdown()
                return
            self._reply(srv.margin_server.answer_line(line))

    def _reply(self, obj):
        try:
            self.wfile.write((json.dumps(obj) + "\n").encode())
            self.wfile.flush()
        except OSError:
            pass   # client went away; its answers are already computed


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    margin_server: "MarginServer" = None

    def initiate_shutdown(self):
        # shutdown() blocks until serve_forever exits — never call it
        # from a handler (or signal) frame that serve_forever is waiting
        # on; hand it to a throwaway thread
        threading.Thread(target=self.shutdown, daemon=True).start()


class MarginServer:
    """Glue: sockets in front, the micro-batcher behind."""

    def __init__(self, batcher, num_features: int, max_nnz: int,
                 host: str = "127.0.0.1", port: int = 0):
        self.batcher = batcher
        self.num_features = int(num_features)
        self.max_nnz = int(max_nnz)
        self._tcp = _TCPServer((host, port), _Handler,
                               bind_and_activate=True)
        self._tcp.margin_server = self

    @property
    def address(self):
        """(host, port) actually bound — port 0 resolves here."""
        return self._tcp.server_address

    def answer_line(self, line: str):
        """Parse one request line, submit through the batcher, wait for
        the batch, shape the JSON-able response."""
        texts = [t for t in line.split(";") if t.strip()]
        pendings = []
        for text in texts:
            try:
                idx, val = parse_query(text, self.num_features,
                                       self.max_nnz)
            except QueryError as e:
                pendings.append({"error": str(e)})
                continue
            pendings.append(self.batcher.submit(idx, val))
        out = []
        for p in pendings:
            if isinstance(p, dict):
                out.append(p)
                continue
            try:
                margin = p.result(timeout=30.0)
                out.append({"margin": margin, "round": p.model_round,
                            "dtype": p.served_dtype})
            except Exception as e:
                out.append({"error": f"{type(e).__name__}: {e}"})
        return out if len(texts) > 1 else out[0] if out \
            else {"error": "empty request line"}

    def serve_forever(self, poll_interval: float = 0.2):
        """Block until ``shutdown`` (protocol line or :meth:`stop`)."""
        self._tcp.serve_forever(poll_interval=poll_interval)

    def stop(self):
        self._tcp.initiate_shutdown()

    def close(self):
        self._tcp.server_close()
