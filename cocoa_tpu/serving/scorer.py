"""Compiled batched margin scoring with static buckets + atomic hot-swap.

The serving hot path answers batched margin queries ``x·w`` while a
background trainer keeps ``w`` fresh (docs/DESIGN.md §17).  Two
perf-critical contracts live here:

- **One compile per bucket, ever.**  Queries are padded UP to a static
  batch bucket (default 64/256/1024 — :data:`DEFAULT_BUCKETS`), so the
  one jitted scoring function specializes exactly once per bucket shape
  and NEVER again: the model ``w`` is an ordinary argument with a fixed
  shape/dtype, which is what makes a hot-swap free — a swap changes
  bytes, not shapes, so it cannot retrace, recompile, or stall the
  dispatch queue behind a compile.  Padded slots carry index 0 / value
  0 and contribute exactly 0 to every margin, the same convention as
  the training padded-CSR (ops/rows.py).
- **The same kernels the evaluator uses.**  Scoring goes through
  ``ops/rows.shard_margins`` — the one layout dispatch point — so a
  sparse query batch rides the gather-sum, and when the model was
  trained with a hot/cold column split (``--hotCols``, data/hybrid.py)
  the batch is split the same way: the hot majority of nonzeros as one
  MXU panel matvec, only the cold tail through the gather.

:class:`ModelSlots` is the double-buffered model holder: the live
``(w, scale, info)`` triple is published as ONE tuple behind a single
attribute, so a reader (the batcher thread) either sees the old model
or the new one, never a torn mix; an in-flight batch keeps its
reference to the old device buffer until its dispatch completes, so a
swap can never drop or block a request.  The spare slot is wherever the
next upload lands — ``device_put`` into fresh memory while the old
buffer serves.

Low-precision serving (``--serveDtype``, docs/DESIGN.md §20) hangs off
the publish: with a bf16/int8 serve dtype, :meth:`ModelSlots.swap`
quantizes the incoming f32 model ONCE on the host (serving/quantize.py
packed-lane forms), computes the per-swap margin-error certificate
over a calibration batch, and — if the bound could flip the weakest
calibrated margin's sign — publishes the f32 model instead.  Either
way it is the same atomic publish, and the scorer warmed BOTH model
forms per bucket up front, so neither the quantized generation nor the
certificate fallback ever compiles after warmup.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple, Optional

import numpy as np

DEFAULT_BUCKETS = (64, 256, 1024)

# static per-query nonzero budget when the caller gives none: covers the
# text-classification row shapes this repo trains on (rcv1 max row nnz
# 548 at the shard level, typical queries far shorter) without making
# the padded batch huge.  `--serveMaxNnz` overrides it on the CLI.
DEFAULT_MAX_NNZ = 512


class QueryError(ValueError):
    """A malformed or out-of-contract query — rejected with the numbers,
    never silently truncated (the CLI-hardening principle)."""


def parse_query(text: str, num_features: int, max_nnz: int):
    """One query line (LIBSVM feature grammar, ``idx:val`` pairs,
    1-based ids) -> ``(idx, val)`` int32/float arrays, 0-based.

    Rejections carry the numbers: a feature id outside the trained
    width, more nonzeros than the static padding budget, or a pair the
    shared decimal grammar cannot parse."""
    toks = text.split()
    if not toks:
        raise QueryError("empty query (expected 'idx:val idx:val ...', "
                         "1-based feature ids)")
    idx, val = [], []
    for m, tok in enumerate(toks):
        head, sep, tail = tok.partition(":")
        try:
            i = int(head)
            v = float(tail)
        except ValueError:
            sep = ""
        if not sep:
            raise QueryError(f"malformed pair {tok!r} at position {m} "
                             f"(expected 'idx:val')")
        if i < 1 or i > num_features:
            raise QueryError(
                f"feature id {i} outside the trained width: this model "
                f"serves num_features={num_features} (1-based ids "
                f"1..{num_features})")
        idx.append(i - 1)
        val.append(v)
    if len(toks) > max_nnz:
        raise QueryError(
            f"query carries {len(toks)} nonzeros but the compiled "
            f"scoring path pads to max_nnz={max_nnz} — restart the "
            f"server with --serveMaxNnz>={len(toks)} or sparsify the "
            f"query")
    # jaxlint: allow=f64 -- exact host-side text parse; values cast to
    # f32 at batch assembly (quantization is weights-only — the query
    # side never narrows), never enter device compute as f64
    return np.asarray(idx, np.int32), np.asarray(val, np.float64)


def pick_bucket(n: int, buckets: tuple) -> int:
    """The smallest static bucket that holds ``n`` requests (the
    throughput maximizer: least padding = most real rows per compiled
    dispatch).  Callers cap admission at ``buckets[-1]``."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket "
                     f"{buckets[-1]} — the batcher must cap admission")


class ModelInfo(NamedTuple):
    """What the serving loop knows about the model in the live slot."""

    round: Optional[int]       # training round the checkpoint stamped
    path: Optional[str]        # checkpoint file it came from
    birth_ts: float            # checkpoint mtime: when the certificate
                               # (and the model) was produced — the
                               # anchor of the gap-age freshness gauge
    gap: Optional[float]       # certified duality gap the checkpoint
                               # meta recorded (None on pre-gap metas)
    seq: int                   # swap sequence number (0 = initial load)
    # per-tenant certification metadata of a stacked (T, d) catalogue
    # (checkpoint meta tenant_gaps / tenant_cert_ts, docs/DESIGN.md
    # §22): one certified gap and one certification wall-clock per
    # tenant row — what the tenant-labeled gap-age gauge renders from.
    # None on single-model checkpoints and pre-fleet metas
    tenant_gaps: Optional[tuple] = None
    tenant_cert_ts: Optional[tuple] = None


class ModelSlots:
    """Double-buffered device-resident model with atomic hot-swap.

    ``current()`` returns the live ``(w_device, ModelInfo)`` tuple; the
    pair is swapped by replacing ONE attribute reference, so readers on
    the scoring thread never observe a torn (new w, old info) state and
    never block on a swap.  The upload of the incoming model happens on
    the CALLER's thread (the watcher) into a fresh buffer — the live
    buffer keeps serving until the publish, and in-flight batches that
    already captured the old reference complete against it untouched.
    """

    def __init__(self, w, info: ModelInfo, dtype=None, calibration=None,
                 algorithm: str = "serve",
                 flip_guard: Optional[float] = None):
        from cocoa_tpu.serving import quantize as quantize_mod

        self.serve_dtype = quantize_mod.resolve_serve_dtype(dtype)
        self.algorithm = algorithm
        self._calibration = calibration   # CalibrationBuffer or None
        # certificate fallback threshold: publish f32 when the measured
        # bound reaches the weakest calibrated |margin| (default), or an
        # explicit absolute threshold (tests force the crossing with it)
        self._flip_guard = flip_guard
        w = np.asarray(w, np.float32)
        # a 2-D (T, d) w is a served CATALOGUE: T tenant models scored
        # through the one flat-gather executable (docs/DESIGN.md §21);
        # anything else flattens to the classic single-model vector
        if w.ndim != 2:
            w = w.reshape(-1)
        self.n_tenants = int(w.shape[0]) if w.ndim == 2 else None
        if self.n_tenants is not None and self.serve_dtype != "f32":
            raise QueryError(
                f"a served catalogue ({self.n_tenants} tenants x "
                f"{w.shape[1]} features) only supports "
                f"--serveDtype=f32: per-tenant quantization "
                f"certificates are not in the fleet v1 surface "
                f"(docs/DESIGN.md §21)")
        self._shape = tuple(int(s) for s in w.shape)
        self._d = self._shape[-1]
        self.served_dtype = "f32"       # form of the LIVE slot
        self.last_bound: Optional[float] = None
        self.fallbacks_total = 0
        self._lock = threading.Lock()   # serializes WRITERS only
        self._publish(w, info)

    def _publish(self, w32, info: ModelInfo):
        """Quantize (if armed), certify, upload, publish — the one
        place a model becomes live.  Caller holds the writer lock (or
        is ``__init__``)."""
        import jax

        from cocoa_tpu.serving import quantize as quantize_mod

        served, qm, bound, calib_n, flips, fallback = \
            "f32", None, None, 0, 0, 0
        if self.serve_dtype != "f32":
            qm = quantize_mod.quantize(w32, self.serve_dtype)
            if self._calibration is not None:
                batch = self._calibration.sample()
                calib_n = len(batch)
                if batch:
                    wq = quantize_mod.dequantize(qm, self._d)
                    bound, weakest, flips = \
                        quantize_mod.margin_error_bound(w32, wq, batch)
                    guard = (weakest if self._flip_guard is None
                             else self._flip_guard)
                    fallback = int(bound >= guard)
            if not fallback:
                served = self.serve_dtype
        if served == "f32":
            w_dev, scale = jax.device_put(w32), None
        else:
            w_dev, scale = jax.device_put(qm.packed), qm.scale
        self._live = (w_dev, scale, info)
        self.served_dtype = served
        self.last_bound = bound
        self.fallbacks_total += fallback
        if self.serve_dtype != "f32":
            self._emit_quantize(info, served, bound, calib_n, flips,
                                fallback, qm)

    def _emit_quantize(self, info, served, bound, calib_n, flips,
                       fallback, qm):
        from cocoa_tpu.telemetry import events as tele_events

        bus = tele_events.get_bus()
        if not bus.active():
            return
        bus.emit(
            "model_quantize", algorithm=self.algorithm,
            serve_dtype=self.serve_dtype, served=served,
            round=info.round, swap_seq=info.seq, bound=bound,
            calib_n=calib_n, flips=flips, fallback=fallback,
            scale=(None if qm is None or qm.scale is None
                   else float(qm.scale)))

    def current(self):
        """The live ``(w_device, scale, info)`` triple — ``scale`` is
        the int8 per-model symmetric scale (None for f32/bf16 forms),
        published atomically WITH the buffer it scales."""
        return self._live

    @property
    def info(self) -> ModelInfo:
        return self._live[2]

    def gap_age_s(self, now: Optional[float] = None) -> float:
        """Seconds since the live model's certificate was produced —
        the freshness the serving loop exports
        (``cocoa_model_gap_age_seconds``)."""
        return (now if now is not None else time.time()) \
            - self._live[2].birth_ts

    def swap(self, w, info: ModelInfo):
        """Quantize + certify + upload ``w`` into the spare slot and
        publish atomically.

        A shape change is rejected with the numbers — static shapes are
        what make a swap compile-free, so a width change (or a tenant-
        count change on a served catalogue) is a different MODEL, not a
        fresh generation of this one."""
        with self._lock:
            w = np.asarray(w)
            if tuple(w.shape) != self._shape:
                raise QueryError(
                    f"refusing hot-swap: incoming w has shape "
                    f"{tuple(w.shape)} but the serving executable is "
                    f"compiled for {self._shape} — a shape change is a "
                    f"new model (restart the server)")
            self._publish(np.asarray(w, np.float32), info)
        return info


class BatchScorer:
    """The compiled scoring path: one jit, one specialization per
    bucket, the model as a plain argument (hot-swap never retraces).

    ``hot_ids`` (optional) arms the hybrid path: queries split into a
    dense panel over the trained hot columns plus a cold residual, and
    ride the SAME panel+residual dispatch in ``shard_margins`` the
    evaluator uses (docs/DESIGN.md §3b-vi).
    """

    def __init__(self, num_features: int, dtype=None,
                 buckets: tuple = DEFAULT_BUCKETS,
                 max_nnz: int = DEFAULT_MAX_NNZ,
                 hot_ids=None, model_width=None, n_tenants=None):
        import jax
        import jax.numpy as jnp

        from cocoa_tpu.ops import rows as rows_mod
        from cocoa_tpu.serving import quantize as quantize_mod

        if not buckets or list(buckets) != sorted(set(int(b)
                                                      for b in buckets)):
            raise ValueError(f"buckets must be strictly increasing "
                             f"positive ints, got {buckets!r}")
        if buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {buckets!r}")
        self.num_features = int(num_features)
        # catalogue mode (docs/DESIGN.md §21): score against a (T, d)
        # tenant catalogue — every batch carries a per-row tenant vector
        # and the model gathers flat with a static row stride, so
        # cross-tenant batches still compile ONCE per bucket
        self.n_tenants = int(n_tenants) if n_tenants is not None else None
        if self.n_tenants is not None and self.n_tenants < 1:
            raise ValueError(f"n_tenants must be >= 1, "
                             f"got {n_tenants!r}")
        # the trained width may exceed the query width by lane padding
        # (the CLI passes the checkpoint's w width); the packed model
        # forms are sized from THIS, so the warmed executables match
        # every future publish exactly
        self.model_width = (int(model_width) if model_width is not None
                            else self.num_features)
        if self.model_width < self.num_features:
            raise ValueError(
                f"model_width={self.model_width} is narrower than the "
                f"query surface num_features={self.num_features} — a "
                f"query could gather past the model")
        # ``dtype`` is the SERVE dtype (--serveDtype): it selects which
        # packed model form this scorer compiles for.  Query assembly is
        # always f32 — quantization is weights-only (quantize.py), so
        # the request side never narrows
        self.serve_dtype = quantize_mod.resolve_serve_dtype(dtype)
        self.dtype = jnp.dtype(jnp.float32)
        if self.n_tenants is not None and self.serve_dtype != "f32":
            raise ValueError(
                f"a catalogue scorer ({self.n_tenants} tenants) only "
                f"supports serve dtype f32 — per-tenant quantization "
                f"certificates are not in the fleet v1 surface "
                f"(docs/DESIGN.md §21)")
        if self.n_tenants is not None and hot_ids is not None \
                and len(hot_ids):
            raise ValueError(
                "a catalogue scorer does not combine with a hot-column "
                "panel: the hot split is a single-model layout "
                "(per-tenant panels are not in the fleet v1 surface)")
        # model forms this scorer serves: the configured form plus the
        # f32 certificate-fallback form — keyed by (device dtype,
        # full shape), the numbers a mismatch is rejected with
        model_shape = ((self.model_width,) if self.n_tenants is None
                       else (self.n_tenants, self.model_width))
        self._forms = {"f32": (np.dtype(np.float32), model_shape)}
        if self.serve_dtype != "f32":
            self._forms[self.serve_dtype] = (
                quantize_mod.PACKED_DTYPE[self.serve_dtype],
                (quantize_mod.packed_len(self.model_width,
                                         self.serve_dtype),))
        self.buckets = tuple(int(b) for b in buckets)
        self.max_nnz = int(min(max_nnz, num_features))
        self.hot_rank = None
        self._hot_cols_dev = None
        if hot_ids is not None and len(hot_ids):
            from cocoa_tpu.data import hybrid as hybrid_lib

            hot_ids = np.asarray(hot_ids, np.int64)
            self.hot_rank = hybrid_lib.hot_rank(self.num_features,
                                                hot_ids)
            self._hot_cols_dev = jax.device_put(
                np.asarray(hot_ids, np.int32))
        self.n_hot = (0 if self._hot_cols_dev is None
                      else int(self._hot_cols_dev.shape[0]))

        hot_cols = self._hot_cols_dev

        def serve_margins(w, idx, val, hot, scale, tenant):
            shard = {"sp_indices": idx, "sp_values": val}
            if hot is not None:
                shard["X_hot"] = hot
                shard["hot_cols"] = hot_cols
            if tenant is not None:
                shard["tenant"] = tenant
            return rows_mod.serve_margins(w, shard, scale)

        # built ONCE at construction (the serve-hygiene rule pins this
        # shape statically): every later call only re-specializes on a
        # new BUCKET shape or model FORM (the w dtype is the trace-time
        # dispatch key in rows.gather_dequant — both forms are warmed up
        # front), never on the model bytes or the request content.  The
        # int8 scale rides as a traced scalar: a new scale per swap
        # never retraces
        self._jit = jax.jit(serve_margins)

    def assemble(self, queries: list, bucket: int):
        """Pad parsed ``(idx, val)`` queries up to ``bucket`` rows of
        static width; returns the device-ready host arrays.  With a hot
        split armed, each query's nonzeros partition into the panel
        lanes and the cold residual exactly like the training slabs
        (data/hybrid.split_slab semantics, per query row)."""
        np_dtype = np.dtype(self.dtype)
        idx = np.zeros((bucket, self.max_nnz), np.int32)
        val = np.zeros((bucket, self.max_nnz), np_dtype)
        hot = (np.zeros((bucket, self.n_hot), np_dtype)
               if self.n_hot else None)
        for r, (qi, qv) in enumerate(queries):
            if self.hot_rank is None:
                idx[r, :len(qi)] = qi
                val[r, :len(qi)] = qv
            else:
                lanes = self.hot_rank[qi]
                is_hot = lanes >= 0
                # ACCUMULATE into the panel (np.add.at), don't assign:
                # a query may repeat a feature id, and the gather path
                # sums duplicates (each occupies its own CSR slot) — a
                # last-write assignment here would answer differently
                # on a --hotCols server than on a plain one
                np.add.at(hot[r], lanes[is_hot], qv[is_hot])
                ci, cv = qi[~is_hot], qv[~is_hot]
                idx[r, :len(ci)] = ci
                val[r, :len(cv)] = cv
        return idx, val, hot

    def assemble_tenants(self, tenants: list, bucket: int):
        """The catalogue batch's per-row tenant vector, padded to
        ``bucket`` rows (padded slots carry tenant 0 — their values are
        all 0, so whichever tenant row they gather contributes nothing
        and the padded margins are never read)."""
        out = np.zeros((bucket,), np.int32)
        for r, t in enumerate(tenants):
            out[r] = t
        return out

    def score(self, w_dev, idx, val, hot=None, scale=None, tenant=None):
        """Dispatch one padded bucket; returns the DEVICE margins array
        (the caller fetches once, under ``intended_fetch`` — the
        zero-unintended-transfers contract).

        The model must be one of the forms this scorer compiled for
        (its ``--serveDtype`` form or the f32 certificate fallback) —
        anything else would silently compile a new executable per
        publish, so it is rejected with the numbers instead."""
        wd = np.dtype(w_dev.dtype)
        ws = tuple(int(s) for s in w_dev.shape)
        if not any(wd == fd and ws == fs
                   for fd, fs in self._forms.values()):
            raise QueryError(
                f"model form mismatch: got w dtype={wd.name} shape="
                f"{ws} but this scorer (serve dtype "
                f"{self.serve_dtype}, num_features="
                f"{self.num_features}) compiles only "
                + " or ".join(f"{sd}:{fd.name}{fs}"
                              for sd, (fd, fs) in self._forms.items())
                + " — construct ModelSlots and BatchScorer with the "
                  "same dtype= (the CLI wires --serveDtype into both)")
        needs_scale = wd == np.dtype(np.int32)
        if (scale is None) == needs_scale:
            raise QueryError(
                f"scale mismatch: an int8-packed model carries its "
                f"per-model scale as a traced scalar and every other "
                f"form carries None — got w dtype={wd.name} with "
                f"scale={scale!r}; a stray scale would silently "
                f"compile a new specialization per publish")
        if (tenant is None) != (self.n_tenants is None):
            if self.n_tenants is not None:
                what = (f"serves a catalogue of {self.n_tenants} "
                        f"tenants and every batch must carry a "
                        f"tenant vector")
            else:
                what = ("serves a single model and takes no tenant "
                        "vector")
            raise QueryError(
                f"tenant mismatch: this scorer {what} — got "
                f"tenant={tenant!r}")
        return self._jit(w_dev, idx, val, hot, scale, tenant)

    def warmup(self, w_dev, scale=None):
        """Compile every (bucket, model form) pair up front so no
        request ever pays a compile — under a quantized serve dtype
        that is TWO forms per bucket (the packed form and the f32
        certificate-fallback form), so a mid-flight fallback publish
        can never stall the dispatch queue behind a compile.  Returns
        the specialization count (== the expected compile count, what
        the sanitizer pin asserts)."""
        import jax

        wd = np.dtype(w_dev.dtype)
        forms = [(w_dev, scale)]
        for sd, (fd, fs) in self._forms.items():
            if fd == wd:
                continue
            forms.append((jax.device_put(np.zeros(fs, fd)),
                          np.float32(1.0) if sd == "int8" else None))
        for b in self.buckets:
            idx, val, hot = self.assemble([], b)
            tenant = (None if self.n_tenants is None
                      else self.assemble_tenants([], b))
            for wv, sv in forms:
                np.asarray(self.score(wv, idx, val, hot, sv, tenant))
        return len(self.buckets) * len(forms)
