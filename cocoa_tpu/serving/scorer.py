"""Compiled batched margin scoring with static buckets + atomic hot-swap.

The serving hot path answers batched margin queries ``x·w`` while a
background trainer keeps ``w`` fresh (docs/DESIGN.md §17).  Two
perf-critical contracts live here:

- **One compile per bucket, ever.**  Queries are padded UP to a static
  batch bucket (default 64/256/1024 — :data:`DEFAULT_BUCKETS`), so the
  one jitted scoring function specializes exactly once per bucket shape
  and NEVER again: the model ``w`` is an ordinary argument with a fixed
  shape/dtype, which is what makes a hot-swap free — a swap changes
  bytes, not shapes, so it cannot retrace, recompile, or stall the
  dispatch queue behind a compile.  Padded slots carry index 0 / value
  0 and contribute exactly 0 to every margin, the same convention as
  the training padded-CSR (ops/rows.py).
- **The same kernels the evaluator uses.**  Scoring goes through
  ``ops/rows.shard_margins`` — the one layout dispatch point — so a
  sparse query batch rides the gather-sum, and when the model was
  trained with a hot/cold column split (``--hotCols``, data/hybrid.py)
  the batch is split the same way: the hot majority of nonzeros as one
  MXU panel matvec, only the cold tail through the gather.

:class:`ModelSlots` is the double-buffered model holder: the live
``(w, info)`` pair is published as ONE tuple behind a single attribute,
so a reader (the batcher thread) either sees the old model or the new
one, never a torn mix; an in-flight batch keeps its reference to the
old device buffer until its dispatch completes, so a swap can never
drop or block a request.  The spare slot is wherever the next upload
lands — ``device_put`` into fresh memory while the old buffer serves.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple, Optional

import numpy as np

DEFAULT_BUCKETS = (64, 256, 1024)

# static per-query nonzero budget when the caller gives none: covers the
# text-classification row shapes this repo trains on (rcv1 max row nnz
# 548 at the shard level, typical queries far shorter) without making
# the padded batch huge.  `--serveMaxNnz` overrides it on the CLI.
DEFAULT_MAX_NNZ = 512


class QueryError(ValueError):
    """A malformed or out-of-contract query — rejected with the numbers,
    never silently truncated (the CLI-hardening principle)."""


def parse_query(text: str, num_features: int, max_nnz: int):
    """One query line (LIBSVM feature grammar, ``idx:val`` pairs,
    1-based ids) -> ``(idx, val)`` int32/float arrays, 0-based.

    Rejections carry the numbers: a feature id outside the trained
    width, more nonzeros than the static padding budget, or a pair the
    shared decimal grammar cannot parse."""
    toks = text.split()
    if not toks:
        raise QueryError("empty query (expected 'idx:val idx:val ...', "
                         "1-based feature ids)")
    idx, val = [], []
    for m, tok in enumerate(toks):
        head, sep, tail = tok.partition(":")
        try:
            i = int(head)
            v = float(tail)
        except ValueError:
            sep = ""
        if not sep:
            raise QueryError(f"malformed pair {tok!r} at position {m} "
                             f"(expected 'idx:val')")
        if i < 1 or i > num_features:
            raise QueryError(
                f"feature id {i} outside the trained width: this model "
                f"serves num_features={num_features} (1-based ids "
                f"1..{num_features})")
        idx.append(i - 1)
        val.append(v)
    if len(toks) > max_nnz:
        raise QueryError(
            f"query carries {len(toks)} nonzeros but the compiled "
            f"scoring path pads to max_nnz={max_nnz} — restart the "
            f"server with --serveMaxNnz>={len(toks)} or sparsify the "
            f"query")
    # jaxlint: allow=f64 -- exact host-side text parse; values cast to
    # the serving dtype at batch assembly, never enter device compute
    return np.asarray(idx, np.int32), np.asarray(val, np.float64)


def pick_bucket(n: int, buckets: tuple) -> int:
    """The smallest static bucket that holds ``n`` requests (the
    throughput maximizer: least padding = most real rows per compiled
    dispatch).  Callers cap admission at ``buckets[-1]``."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket "
                     f"{buckets[-1]} — the batcher must cap admission")


class ModelInfo(NamedTuple):
    """What the serving loop knows about the model in the live slot."""

    round: Optional[int]       # training round the checkpoint stamped
    path: Optional[str]        # checkpoint file it came from
    birth_ts: float            # checkpoint mtime: when the certificate
                               # (and the model) was produced — the
                               # anchor of the gap-age freshness gauge
    gap: Optional[float]       # certified duality gap the checkpoint
                               # meta recorded (None on pre-gap metas)
    seq: int                   # swap sequence number (0 = initial load)


class ModelSlots:
    """Double-buffered device-resident model with atomic hot-swap.

    ``current()`` returns the live ``(w_device, ModelInfo)`` tuple; the
    pair is swapped by replacing ONE attribute reference, so readers on
    the scoring thread never observe a torn (new w, old info) state and
    never block on a swap.  The upload of the incoming model happens on
    the CALLER's thread (the watcher) into a fresh buffer — the live
    buffer keeps serving until the publish, and in-flight batches that
    already captured the old reference complete against it untouched.
    """

    def __init__(self, w, info: ModelInfo, dtype=None):
        import jax
        import jax.numpy as jnp

        self._dtype = jnp.dtype(dtype) if dtype is not None else None
        w_dev = jax.device_put(self._cast(w))
        self._live = (w_dev, info)
        self._lock = threading.Lock()   # serializes WRITERS only

    def _cast(self, w):
        w = np.asarray(w)
        if self._dtype is not None:
            w = w.astype(self._dtype)
        return w

    def current(self):
        return self._live

    @property
    def info(self) -> ModelInfo:
        return self._live[1]

    def gap_age_s(self, now: Optional[float] = None) -> float:
        """Seconds since the live model's certificate was produced —
        the freshness the serving loop exports
        (``cocoa_model_gap_age_seconds``)."""
        return (now if now is not None else time.time()) \
            - self._live[1].birth_ts

    def swap(self, w, info: ModelInfo):
        """Upload ``w`` into the spare slot and publish atomically.

        A shape/dtype change is rejected with the numbers — static
        shapes are what make a swap compile-free, so a width change is
        a different MODEL, not a fresh generation of this one."""
        import jax

        with self._lock:
            live_w = self._live[0]
            w = self._cast(w)
            if w.shape != live_w.shape:
                raise QueryError(
                    f"refusing hot-swap: incoming w has shape "
                    f"{tuple(w.shape)} but the serving executable is "
                    f"compiled for {tuple(live_w.shape)} — a width "
                    f"change is a new model (restart the server)")
            w_dev = jax.device_put(w)
            self._live = (w_dev, info)
        return info


class BatchScorer:
    """The compiled scoring path: one jit, one specialization per
    bucket, the model as a plain argument (hot-swap never retraces).

    ``hot_ids`` (optional) arms the hybrid path: queries split into a
    dense panel over the trained hot columns plus a cold residual, and
    ride the SAME panel+residual dispatch in ``shard_margins`` the
    evaluator uses (docs/DESIGN.md §3b-vi).
    """

    def __init__(self, num_features: int, dtype=None,
                 buckets: tuple = DEFAULT_BUCKETS,
                 max_nnz: int = DEFAULT_MAX_NNZ,
                 hot_ids=None):
        import jax
        import jax.numpy as jnp

        from cocoa_tpu.ops import rows as rows_mod

        if not buckets or list(buckets) != sorted(set(int(b)
                                                      for b in buckets)):
            raise ValueError(f"buckets must be strictly increasing "
                             f"positive ints, got {buckets!r}")
        if buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {buckets!r}")
        self.num_features = int(num_features)
        self.dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.dtype(jnp.float32)
        self.buckets = tuple(int(b) for b in buckets)
        self.max_nnz = int(min(max_nnz, num_features))
        self.hot_rank = None
        self._hot_cols_dev = None
        if hot_ids is not None and len(hot_ids):
            from cocoa_tpu.data import hybrid as hybrid_lib

            hot_ids = np.asarray(hot_ids, np.int64)
            self.hot_rank = hybrid_lib.hot_rank(self.num_features,
                                                hot_ids)
            self._hot_cols_dev = jax.device_put(
                np.asarray(hot_ids, np.int32))
        self.n_hot = (0 if self._hot_cols_dev is None
                      else int(self._hot_cols_dev.shape[0]))

        hot_cols = self._hot_cols_dev

        def serve_margins(w, idx, val, hot):
            shard = {"sp_indices": idx, "sp_values": val}
            if hot is not None:
                shard["X_hot"] = hot
                shard["hot_cols"] = hot_cols
            return rows_mod.shard_margins(w, shard)

        # built ONCE at construction (the serve-hygiene rule pins this
        # shape statically): every later call only re-specializes on a
        # new BUCKET shape, never on the model or the request content
        self._jit = jax.jit(serve_margins)

    def assemble(self, queries: list, bucket: int):
        """Pad parsed ``(idx, val)`` queries up to ``bucket`` rows of
        static width; returns the device-ready host arrays.  With a hot
        split armed, each query's nonzeros partition into the panel
        lanes and the cold residual exactly like the training slabs
        (data/hybrid.split_slab semantics, per query row)."""
        np_dtype = np.dtype(self.dtype)
        idx = np.zeros((bucket, self.max_nnz), np.int32)
        val = np.zeros((bucket, self.max_nnz), np_dtype)
        hot = (np.zeros((bucket, self.n_hot), np_dtype)
               if self.n_hot else None)
        for r, (qi, qv) in enumerate(queries):
            if self.hot_rank is None:
                idx[r, :len(qi)] = qi
                val[r, :len(qi)] = qv
            else:
                lanes = self.hot_rank[qi]
                is_hot = lanes >= 0
                # ACCUMULATE into the panel (np.add.at), don't assign:
                # a query may repeat a feature id, and the gather path
                # sums duplicates (each occupies its own CSR slot) — a
                # last-write assignment here would answer differently
                # on a --hotCols server than on a plain one
                np.add.at(hot[r], lanes[is_hot], qv[is_hot])
                ci, cv = qi[~is_hot], qv[~is_hot]
                idx[r, :len(ci)] = ci
                val[r, :len(cv)] = cv
        return idx, val, hot

    def score(self, w_dev, idx, val, hot=None):
        """Dispatch one padded bucket; returns the DEVICE margins array
        (the caller fetches once, under ``intended_fetch`` — the
        zero-unintended-transfers contract)."""
        return self._jit(w_dev, idx, val, hot)

    def warmup(self, w_dev):
        """Compile every bucket up front so no request ever pays a
        compile; returns the bucket count (== the expected compile
        count, what the sanitizer pin asserts)."""
        for b in self.buckets:
            idx, val, hot = self.assemble([], b)
            np.asarray(self.score(w_dev, idx, val, hot))
        return len(self.buckets)
