"""Production margin serving: score while you train (docs/DESIGN.md §17).

The last north-star scenario: a compiled batched margin-scoring path
(static buckets, one compile per bucket, the model as a plain argument)
behind an adaptive micro-batcher, with double-buffered model slots that
a background watcher hot-swaps atomically from the newest VALIDATED
checkpoint generation — so the model a query hits is always certified,
and its freshness is exported as gap age.  ``--serve`` on the CLI wires
the whole stack; the pieces compose independently for tests and the
bench:

- scorer.py   — BatchScorer / ModelSlots / parse_query (the hot path)
- batcher.py  — MicroBatcher (admission under the SLA, bucket choice)
- watcher.py  — SwapWatcher / wait_for_model (checkpoint → slot)
- server.py   — MarginServer (the TCP line protocol)
- quantize.py — swap-time bf16/int8 packing + the per-swap margin-error
                certificate (``--serveDtype``, docs/DESIGN.md §20)
- router.py   — Router / Replica (fleet front door: tenant routing,
                admission shedding, requeue-on-death, §21)
- fleet.py    — ServeFleet (replica subprocess lifecycle + respawn)
"""

from cocoa_tpu.serving.batcher import MicroBatcher, PendingQuery
from cocoa_tpu.serving.fleet import ReplicaProc, ServeFleet
from cocoa_tpu.serving.router import Replica, Router
from cocoa_tpu.serving.quantize import (SERVE_DTYPES, CalibrationBuffer,
                                        resolve_serve_dtype)
from cocoa_tpu.serving.scorer import (DEFAULT_BUCKETS, DEFAULT_MAX_NNZ,
                                      BatchScorer, ModelInfo, ModelSlots,
                                      QueryError, parse_query,
                                      pick_bucket)
from cocoa_tpu.serving.server import MarginServer
from cocoa_tpu.serving.watcher import (SwapWatcher, load_model,
                                       wait_for_model)

__all__ = [
    "DEFAULT_BUCKETS", "DEFAULT_MAX_NNZ", "BatchScorer", "ModelInfo",
    "ModelSlots", "QueryError", "parse_query", "pick_bucket",
    "MicroBatcher", "PendingQuery", "MarginServer", "SwapWatcher",
    "load_model", "wait_for_model", "SERVE_DTYPES", "CalibrationBuffer",
    "resolve_serve_dtype", "Router", "Replica", "ServeFleet",
    "ReplicaProc",
]
