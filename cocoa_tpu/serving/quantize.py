"""Swap-time model quantization for low-precision serving
(``--serveDtype``, docs/DESIGN.md §20).

Training rejected bf16 for a measured reason (tests/test_bf16.py: the
bf16 duality gap quantizes to 0.0) — but serving never evaluates the
gap.  A margin only needs SIGN and RANKING fidelity, so the scorer can
trade precision for throughput without touching the certificate the
trainer owns.  Three design decisions keep that trade honest:

- **Weights-only, once per swap.**  Only the MODEL is narrowed, on the
  host, at publish time; query values and the padded-batch assembly
  stay f32, and the compiled scoring path dequantizes gathered lanes in
  registers (ops/rows.gather_dequant).  Quantization never appears
  inside the compiled path — the serve-hygiene lint rule makes that an
  error — so a batch never pays a cast of the model, and the f32
  serving path is BIT-IDENTICAL to the pre-quantization scorer.
- **Packed lanes, not narrow arrays.**  bf16 is stored two lanes per
  uint32 word, int8 four lanes per int32 word, so the per-nonzero
  gather stays on the hardware 4-byte gather path while the model's
  cache/HBM footprint halves (quarters).  XLA's CPU backend EMULATES
  narrow arithmetic — a plain ``jnp.bfloat16`` model measures SLOWER
  than f32 — so the packed layout is where the measured throughput win
  actually comes from (benchmarks/serve_bench.py ``--serveDtype``); on
  TPU the same layout is what halves the HBM stream.  Dequantization is
  exact bit manipulation (bf16 -> f32 is lossless; int8 lanes sign-
  extend exactly), so packed and unpacked forms answer identically.
- **A per-swap error certificate.**  Every publish computes an
  empirical f32-vs-quantized margin-error bound over a calibration
  batch of recent real queries (warmup-seeded synthetic fallback,
  :class:`CalibrationBuffer`) and compares it against the weakest
  calibrated margin: if the bound could flip that sign, the swap FALLS
  BACK to publishing the f32 model — a normal slot publish, so it
  inherits the atomic no-recompile swap guarantees (the scorer warms
  both forms; one compiled executable per (bucket, dtype), ever).
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Optional

import numpy as np

# the serve-dtype vocabulary: resolve_serve_dtype() maps every accepted
# spelling (CLI strings, numpy/jax dtype objects) onto these
SERVE_DTYPES = ("f32", "bf16", "int8")

# device dtype of each packed model form — the trace-time dispatch key:
# the compiled scoring path picks its dequantize kernel from w.dtype
# alone (ops/rows.gather_dequant), so the three forms MUST be distinct
PACKED_DTYPE = {"f32": np.dtype(np.float32),
                "bf16": np.dtype(np.uint32),   # 2 bf16 lanes per word
                "int8": np.dtype(np.int32)}    # 4 int8 lanes per word

LANES = {"f32": 1, "bf16": 2, "int8": 4}

_ALIASES = {"f32": "f32", "float32": "f32",
            "bf16": "bf16", "bfloat16": "bf16",
            "int8": "int8"}


def resolve_serve_dtype(dtype) -> str:
    """Canonical serve dtype (``f32``/``bf16``/``int8``) from any
    accepted spelling; anything else is rejected with the vocabulary."""
    if dtype is None:
        return "f32"
    if isinstance(dtype, str):
        key = dtype.strip().lower()
    else:
        try:
            key = np.dtype(dtype).name
        except TypeError:
            key = str(dtype)
    got = _ALIASES.get(key)
    if got is None:
        raise ValueError(
            f"unsupported serve dtype {dtype!r}: the serving stack "
            f"quantizes to one of {'/'.join(SERVE_DTYPES)} "
            f"(--serveDtype)")
    return got


def packed_len(num_features: int, serve_dtype: str) -> int:
    """Length of the packed device array for a width-``num_features``
    model (the tail word zero-padded; pad lanes dequantize to 0.0 and a
    padded query slot carries value 0, so they contribute nothing)."""
    lanes = LANES[serve_dtype]
    return -(-int(num_features) // lanes)


class QuantizedModel(NamedTuple):
    """One quantized publishable form of a model vector."""

    serve_dtype: str              # "bf16" | "int8" ("f32" = passthrough)
    packed: np.ndarray            # device-ready packed array
    scale: Optional[np.float32]   # int8 symmetric per-model scale, else
                                  # None (the scale rides the compiled
                                  # path as a TRACED scalar — a new
                                  # scale per swap never retraces)


def quantize(w, serve_dtype: str) -> QuantizedModel:
    """Host-side quantize+pack of a model vector.  bf16 rounds to
    nearest-even then packs lane ``i`` into bits ``16*(i&1)`` of word
    ``i>>1``; int8 uses a symmetric per-model scale ``max|w|/127``
    (zero-model guard: scale 1.0) and packs lane ``i`` into bits
    ``8*(i&3)`` of word ``i>>2`` — both exactly the layouts
    ops/rows.gather_dequant unpacks."""
    import ml_dtypes

    w = np.asarray(w, np.float32).reshape(-1)
    d = w.shape[0]
    sd = resolve_serve_dtype(serve_dtype)
    if sd == "f32":
        return QuantizedModel("f32", w, None)
    if sd == "bf16":
        lanes = np.asarray(w.astype(ml_dtypes.bfloat16)
                           .view(np.uint16), np.uint32)
        pad = packed_len(d, sd) * 2 - d
        if pad:
            lanes = np.concatenate(
                [lanes, np.asarray([0] * pad, np.uint32)])
        return QuantizedModel(
            "bf16", lanes[0::2] | (lanes[1::2] << np.uint32(16)), None)
    scale = np.float32(np.max(np.abs(w)) / 127.0) if np.any(w) \
        else np.float32(1.0)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    lanes = q.view(np.uint8).astype(np.uint32)
    pad = packed_len(d, sd) * 4 - d
    if pad:
        lanes = np.concatenate([lanes, np.asarray([0] * pad, np.uint32)])
    packed = (lanes[0::4] | (lanes[1::4] << np.uint32(8))
              | (lanes[2::4] << np.uint32(16))
              | (lanes[3::4] << np.uint32(24))).view(np.int32)
    return QuantizedModel("int8", packed, scale)


def dequantize(qm: QuantizedModel, num_features: int) -> np.ndarray:
    """Exact f32 image of the quantized model — what the compiled path
    effectively serves.  Bounds and tests compare against THIS, so the
    certificate measures quantization error, not kernel mystery."""
    d = int(num_features)
    if qm.serve_dtype == "f32":
        return np.asarray(qm.packed, np.float32)[:d]
    if qm.serve_dtype == "bf16":
        lanes = np.empty(qm.packed.shape[0] * 2, np.uint16)
        lanes[0::2] = (qm.packed & np.uint32(0xFFFF)).astype(np.uint16)
        lanes[1::2] = (qm.packed >> np.uint32(16)).astype(np.uint16)
        return (lanes.astype(np.uint32) << np.uint32(16)) \
            .view(np.float32)[:d]
    words = qm.packed.view(np.uint32)
    lanes = np.empty(words.shape[0] * 4, np.uint8)
    for j in range(4):
        lanes[j::4] = ((words >> np.uint32(8 * j))
                       & np.uint32(0xFF)).astype(np.uint8)
    return lanes.view(np.int8).astype(np.float32)[:d] \
        * np.float32(qm.scale)


def margin_error_bound(w32, w_served, queries):
    """Empirical per-swap certificate over a calibration batch.

    Returns ``(bound, weakest, flips)``: the max f64 margin error of the
    served (dequantized) model vs the incoming f32 model, the smallest
    nonzero |f32 margin| it must not exceed, and how many calibration
    margins actually changed sign.  The fallback policy is
    ``bound >= weakest`` — the measured error could flip the weakest
    margin a real query produced, so sign fidelity is no longer
    certified and the swap publishes f32 instead."""
    # jaxlint: allow=f64 -- host-side certificate arithmetic at swap
    # time; never enters device compute
    w32 = np.asarray(w32, np.float64)
    wq = np.asarray(w_served, np.float64)  # jaxlint: allow=f64 -- cert
    bound, weakest, flips = 0.0, np.inf, 0
    for qi, qv in queries:
        qi = np.asarray(qi, np.int64)
        qv = np.asarray(qv, np.float64)  # jaxlint: allow=f64 -- cert
        m32 = float(np.dot(w32[qi], qv))
        mq = float(np.dot(wq[qi], qv))
        bound = max(bound, abs(mq - m32))
        if m32 != 0.0:
            weakest = min(weakest, abs(m32))
        if (mq < 0.0) != (m32 < 0.0) and mq != m32:
            flips += 1
    return bound, weakest, flips


class CalibrationBuffer:
    """Ring of recent REAL queries the certificate is computed over,
    warmup-seeded with synthetic queries so the very first publish
    (before any traffic) still carries a bound.  The batcher records
    every admitted query (cheap append under a lock); the swap path
    samples the most recent window."""

    def __init__(self, num_features: int, max_nnz: int = 16,
                 capacity: int = 256, seed: int = 0,
                 warmup_n: int = 64):
        self._lock = threading.Lock()
        self._cap = int(capacity)
        self._ring = []
        self.recorded_total = 0
        rng = np.random.default_rng(seed)
        nnz = max(1, min(int(max_nnz), 8))
        for _ in range(warmup_n):
            qi = rng.integers(0, num_features, size=nnz,
                              dtype=np.int32)
            qv = rng.standard_normal(nnz).astype(np.float32)
            self._ring.append((qi, qv))

    def record(self, idx, val):
        with self._lock:
            self._ring.append((idx, val))
            self.recorded_total += 1
            if len(self._ring) > self._cap:
                del self._ring[:len(self._ring) - self._cap]

    def sample(self, n: int = 64) -> list:
        """The most recent ``n`` queries (newest-biased: recent traffic
        is what the next generation will actually answer)."""
        with self._lock:
            return list(self._ring[-int(n):])
