"""The hot-swap watcher: poll for the newest VALIDATED generation, swap
atomically, export freshness as gap age.

A background thread polls ``checkpoint.latest()`` (whose validation is
cached on (path, mtime, size) — an unchanged generation costs one stat
per retained file, so poll-rate watching is cheap) and, when a NEW
healthy generation appears, loads it and swaps the model slots.  The
swap is a device-buffer update behind one atomic reference publish
(serving/scorer.ModelSlots): shapes are static, so it never recompiles,
and an in-flight batch keeps the old buffer — a swap under sustained
traffic drops zero requests and the post-swap margins are bit-identical
to a cold restart on the new checkpoint (pinned,
tests/test_serving.py).

Low-precision serving rides the same publish: with ``--serveDtype``
armed, ``slots.swap`` quantizes the incoming generation and computes
its margin-error certificate INSIDE the swap (serving/quantize.py), so
this watcher needs no dtype awareness — a generation that certifies
serves quantized, one that doesn't serves f32, and either way the poll
loop here only ever sees an atomic publish that cannot recompile (the
scorer warmed both forms).

Freshness semantics (docs/DESIGN.md §17): the paper's primal-dual
certificate is what makes serving-while-training trustworthy, so the
exported freshness is **gap age** — seconds since the live model's
certificate (its checkpoint, whose meta carries the last certified
duality gap) was produced.  A healthy trainer keeps gap age bounded by
its checkpoint cadence; a dead or wedged trainer shows up as a
monotonically climbing gauge long before anyone reads a stale margin.

Elastic interaction: checkpoints are complete and shard-count-keyed
(docs/DESIGN.md §13), so a gang restart or shrink-to-survivors of the
background trainer changes WHO writes the next generation, never what
this watcher reads — serving degrades to "older model, climbing gap
age" during the outage and recovers at the next validated save.  A torn
generation falls back inside ``checkpoint.latest`` (with its typed
``checkpoint_corrupt`` event) and is simply not swapped in.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

from cocoa_tpu.serving.scorer import ModelInfo, QueryError


def load_model(path: str):
    """(w, ModelInfo) from one validated checkpoint path."""
    from cocoa_tpu import checkpoint as ckpt_lib

    meta, arrays = ckpt_lib.load_full(path)
    try:
        birth = os.stat(path).st_mtime
    except OSError:
        birth = time.time()
    # per-tenant certification metadata of a stacked catalogue rides
    # the meta (checkpoint.save tenant_gaps/tenant_cert_ts) — tuples so
    # the published ModelInfo stays immutable like every other field
    tg = meta.get("tenant_gaps")
    tc = meta.get("tenant_cert_ts")
    info = ModelInfo(round=meta.get("round"), path=path, birth_ts=birth,
                     gap=meta.get("gap"), seq=0,
                     tenant_gaps=None if tg is None else tuple(tg),
                     tenant_cert_ts=None if tc is None else tuple(tc))
    return arrays["w"], info


def wait_for_model(directory: str, algorithm: str,
                   timeout_s: float = 300.0, poll_s: float = 0.25,
                   quiet: bool = False) -> Optional[str]:
    """Block until a validated checkpoint exists (serve-while-you-train:
    the trainer may still be warming up when the server starts); None
    on timeout."""
    from cocoa_tpu import checkpoint as ckpt_lib

    deadline = time.monotonic() + timeout_s
    noted = False
    while True:
        path = ckpt_lib.latest(directory, algorithm)
        if path is not None:
            return path
        if time.monotonic() >= deadline:
            return None
        if not noted and not quiet:
            print(f"serve: waiting for the first validated {algorithm} "
                  f"checkpoint in {directory} (the background trainer "
                  f"has not saved yet)", file=sys.stderr, flush=True)
            noted = True
        time.sleep(poll_s)


class SwapWatcher:
    """Poll-and-swap thread.  ``on_swap(info)`` (optional) runs after
    each publish — the server uses it for console notes."""

    def __init__(self, slots, directory: str, algorithm: str,
                 poll_s: float = 0.25, on_swap=None):
        self.slots = slots
        self.directory = directory
        self.algorithm = algorithm
        self.poll_s = float(poll_s)
        self.on_swap = on_swap
        self.swaps_total = 0
        self.rejected_total = 0
        self._stop = threading.Event()
        self._seen = slots.info.path
        self._rejected = None   # a generation refused once (width
        # mismatch) is not retried every poll — it cannot heal in place
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cocoa-serve-watcher")

    def start(self):
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        self._thread.join(timeout)

    def poll_once(self) -> bool:
        """One poll step (also the test hook): swap if a new validated
        generation appeared; returns whether a swap happened."""
        from cocoa_tpu import checkpoint as ckpt_lib

        path = ckpt_lib.latest(self.directory, self.algorithm)
        if path is None or path == self._seen or path == self._rejected:
            return False
        try:
            w, info = load_model(path)
        except (OSError, ValueError, KeyError) as e:
            # lost a race with pruning, or a reader-level tear latest()'s
            # validation could not see — the next poll re-resolves
            print(f"serve: could not load {path} ({e}); keeping the "
                  f"current model", file=sys.stderr, flush=True)
            return False
        self.swaps_total += 1
        info = info._replace(seq=self.swaps_total)
        try:
            self.slots.swap(w, info)
        except QueryError as e:
            self.rejected_total += 1
            self.swaps_total -= 1
            self._rejected = path
            print(f"serve: {e}", file=sys.stderr, flush=True)
            return False
        self._seen = path
        emit_model_swap(self.algorithm, info)
        if self.on_swap is not None:
            self.on_swap(info)
        return True

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:   # the watcher must outlive hiccups
                print(f"serve: watcher error ({type(e).__name__}: {e}); "
                      f"retrying", file=sys.stderr, flush=True)
            self._stop.wait(self.poll_s)


def emit_model_swap(algorithm: str, info: ModelInfo):
    """The typed ``model_swap`` event: which generation went live, what
    certificate it carries, and how old that certificate already was at
    swap time (the gap-age gauge anchors on ``birth_ts``)."""
    from cocoa_tpu.telemetry import events as tele_events

    bus = tele_events.get_bus()
    if bus.active():
        # swap_seq, not "seq": every bus record already carries the
        # stream-ordering seq, and a same-named field would overwrite it
        bus.emit("model_swap", algorithm=algorithm,
                 round=(int(info.round) if info.round is not None
                        else None),
                 path=info.path, birth_ts=info.birth_ts, gap=info.gap,
                 gap_age_s=max(0.0, time.time() - info.birth_ts),
                 swap_seq=info.seq,
                 tenant_gaps=(None if info.tenant_gaps is None
                              else list(info.tenant_gaps)),
                 tenant_cert_ts=(None if info.tenant_cert_ts is None
                                 else list(info.tenant_cert_ts)))
