"""Row access over the two shard layouts (dense / padded-CSR).

The sequential local solvers touch one example per step: a row gather, one or
two dots against d-vectors, and a scaled row-axpy back into d-vectors
(CoCoA.scala:157-185 shape).  These helpers give that per-row contract a
layout-independent form:

- dense: the row is a (d,) slice; dot is an O(d) dense dot; axpy is dense add.
- sparse: the row is (max_nnz,) index/value arrays; dot is gather+reduce;
  axpy is scatter-add.  Padded slots carry index 0 / value 0, so they
  contribute exactly 0 to every dot and axpy — no masking needed.

Layout choice is static (Python-level), so each jit specialization contains
only its own code path.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax


class Row(NamedTuple):
    """One example's features, in whichever layout the shard uses."""

    dense: Optional[jax.Array] = None    # (d,)
    idx: Optional[jax.Array] = None      # (max_nnz,) int32
    val: Optional[jax.Array] = None      # (max_nnz,)


def get_row(shard: dict, i) -> Row:
    if "X" in shard:
        return Row(dense=jax.lax.dynamic_index_in_dim(shard["X"], i, 0, keepdims=False))
    return Row(
        idx=jax.lax.dynamic_index_in_dim(shard["sp_indices"], i, 0, keepdims=False),
        val=jax.lax.dynamic_index_in_dim(shard["sp_values"], i, 0, keepdims=False),
    )


def row_dot(row: Row, vec: jax.Array) -> jax.Array:
    """x · vec."""
    if row.dense is not None:
        return row.dense @ vec
    return vec[row.idx] @ row.val


def row_axpy(row: Row, coef, vec: jax.Array) -> jax.Array:
    """vec + coef * x."""
    if row.dense is not None:
        return vec + coef * row.dense
    return vec.at[row.idx].add(coef * row.val)


def shard_margins(w: jax.Array, shard: dict) -> jax.Array:
    """x_i·w for every row of one shard at once, shape (n_shard,).

    The batched counterpart of ``row_dot`` — on the dense layout a single
    MXU matvec; on padded-CSR a gather + reduction (padded slots contribute
    0).  Shared by the vectorized inner solver (ops/subgradient.py) and
    the fast-math margins pass so layout dispatch lives in one place.

    TRAINING-side: deliberately ignores the dense eval twin ``X_eval`` a
    sparse shard may carry — the twin's float summation order differs
    from the gather-sum, and every training path must stay bit-identical
    whether or not the twin exists (see :func:`eval_margins`).
    """
    if "X" in shard:
        return shard["X"] @ w
    return (w[shard["sp_indices"]] * shard["sp_values"]).sum(-1)


def eval_margins(w: jax.Array, shard: dict) -> jax.Array:
    """EVAL-side :func:`shard_margins`: additionally prefers the dense
    eval twin ``X_eval`` (data/sharding.py ``eval_dense=True``) — the
    certificate's full margins pass then rides one MXU matvec instead of
    an every-nonzero w-gather.  Measured through the production rcv1
    device-loop path, the gather-based eval was 31% of the round time
    (9.42 -> 6.46 ms/round with the twin).  Eval-only by construction:
    training uses :func:`shard_margins`, which never reads the twin, so
    trained (w, α) are bit-identical with or without it."""
    if "X_eval" in shard:
        return shard["X_eval"] @ w
    return shard_margins(w, shard)
