"""Row access over the shard layouts (dense / padded-CSR / hybrid).

The sequential local solvers touch one example per step: a row gather, one or
two dots against d-vectors, and a scaled row-axpy back into d-vectors
(CoCoA.scala:157-185 shape).  These helpers give that per-row contract a
layout-independent form:

- dense: the row is a (d,) slice; dot is an O(d) dense dot; axpy is dense add.
- sparse: the row is (max_nnz,) index/value arrays; dot is gather+reduce;
  axpy is scatter-add.  Padded slots carry index 0 / value 0, so they
  contribute exactly 0 to every dot and axpy — no masking needed.
- hybrid (the hot/cold column split, data/hybrid.py ``--hotCols``): the row
  additionally carries its dense (n_hot,) hot-panel slice; dot and axpy add
  the panel term through the ``hot_cols`` lane→column map.  Columns
  partition between panel and residual, so hot + cold is a permutation of
  the unsplit per-nonzero sum — identical real arithmetic, fp reassociated
  (docs/DESIGN.md §3b-vi).

Layout choice is static (Python-level), so each jit specialization contains
only its own code path.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax


class Row(NamedTuple):
    """One example's features, in whichever layout the shard uses."""

    dense: Optional[jax.Array] = None    # (d,)
    idx: Optional[jax.Array] = None      # (max_nnz,) int32
    val: Optional[jax.Array] = None      # (max_nnz,)
    hot: Optional[jax.Array] = None      # hybrid: (n_hot,) panel values
    hot_cols: Optional[jax.Array] = None  # hybrid: (n_hot,) int32 column ids


def get_row(shard: dict, i) -> Row:
    if "X" in shard:
        return Row(dense=jax.lax.dynamic_index_in_dim(shard["X"], i, 0, keepdims=False))
    hot = hot_cols = None
    if "X_hot" in shard:
        hot = jax.lax.dynamic_index_in_dim(shard["X_hot"], i, 0,
                                           keepdims=False)
        hot_cols = shard["hot_cols"]
    return Row(
        idx=jax.lax.dynamic_index_in_dim(shard["sp_indices"], i, 0, keepdims=False),
        val=jax.lax.dynamic_index_in_dim(shard["sp_values"], i, 0, keepdims=False),
        hot=hot,
        hot_cols=hot_cols,
    )


def row_dot(row: Row, vec: jax.Array) -> jax.Array:
    """x · vec."""
    if row.dense is not None:
        return row.dense @ vec
    d = vec[row.idx] @ row.val
    if row.hot is not None:
        d = d + row.hot @ vec[row.hot_cols]
    return d


def row_axpy(row: Row, coef, vec: jax.Array) -> jax.Array:
    """vec + coef * x."""
    if row.dense is not None:
        return vec + coef * row.dense
    vec = vec.at[row.idx].add(coef * row.val)
    if row.hot is not None:
        # hot and cold columns are disjoint (the split partitions by
        # column), so the two scatters never race on a coordinate
        vec = vec.at[row.hot_cols].add(coef * row.hot)
    return vec


def shard_margins(w: jax.Array, shard: dict) -> jax.Array:
    """x_i·w for every row of one shard at once, shape (n_shard,).

    The batched counterpart of ``row_dot`` — on the dense layout a single
    MXU matvec; on padded-CSR a gather + reduction (padded slots contribute
    0); on the hybrid layout the residual gather-sum PLUS the hot panel as
    one MXU matvec against the gathered hot w slice.  Shared by the
    vectorized inner solver (ops/subgradient.py) and the fast-math margins
    pass so layout dispatch lives in one place.

    TRAINING-side: deliberately ignores the dense eval twin ``X_eval`` a
    sparse shard may carry — the twin's float summation order differs
    from the gather-sum, and every training path must stay bit-identical
    whether or not the twin exists (see :func:`eval_margins`).
    """
    if "X" in shard:
        return shard["X"] @ w
    m = (w[shard["sp_indices"]] * shard["sp_values"]).sum(-1)
    if "X_hot" in shard:
        m = m + shard["X_hot"] @ w[shard["hot_cols"]]
    return m


def gather_dequant(w: jax.Array, idx: jax.Array) -> jax.Array:
    """``w[idx]`` that understands the packed low-precision serving
    forms (serving/quantize.py): the model's DEVICE dtype is the
    trace-time dispatch key, so one jitted scoring function specializes
    per (bucket, dtype) and a hot-swap between forms never retraces.

    - f32 (the training dtype): a plain gather — BIT-IDENTICAL to the
      pre-quantization path, which is what makes the certificate
      fallback a normal slot publish.
    - uint32 = two packed bf16 lanes per word: gather word ``i>>1``,
      shift lane ``i&1`` down, widen by bit-shift + bitcast (bf16->f32
      is exact).  The gather rides the hardware 4-byte path at HALF the
      f32 cache/HBM footprint — XLA would EMULATE a narrow bf16 gather,
      so the packing, not the arithmetic, is the throughput mechanism.
    - int32 = four packed int8 lanes per word: gather word ``i>>2``,
      shift lane ``i&3`` down, sign-extend exactly; the caller applies
      the per-model symmetric scale ONCE on the reduced margins.

    Padded query slots (index 0, value 0) dequantize whatever lane 0
    holds and multiply by 0 — the padding convention is unchanged.
    """
    import jax.numpy as jnp
    from jax import lax

    if w.dtype == jnp.uint32:
        word = w[idx >> 1]
        lane = (word >> ((idx & 1).astype(jnp.uint32) << 4)) \
            & jnp.uint32(0xFFFF)
        return lax.bitcast_convert_type(lane << 16, jnp.float32)
    if w.dtype == jnp.int32:
        word = w[idx >> 2]
        lane = (word >> ((idx & 3) << 3)) & jnp.int32(0xFF)
        lane = lane - ((lane & jnp.int32(0x80)) << 1)
        return lane.astype(jnp.float32)
    return w[idx]


def serve_margins(w: jax.Array, shard: dict, scale=None) -> jax.Array:
    """Dtype-generic serving twin of :func:`shard_margins`: the same
    panel+residual split, but every model read goes through
    :func:`gather_dequant` so packed bf16/int8 models ride the same
    dispatch.  With an f32 model and ``scale=None`` this traces to
    EXACTLY the :func:`shard_margins` sparse/hybrid graph (the
    serving bit-identity pin in tests/test_serving.py).

    ``scale`` is the int8 per-model symmetric scale as a TRACED scalar
    (a new scale per swap never retraces); it multiplies the reduced
    margins once — the hot panel term gathers the same quantized model,
    so panel + residual share the one scale.

    **Catalogue mode** (the multi-tenant fleet, docs/DESIGN.md §21): a
    2-D ``w`` of shape ``(T, d)`` is a served catalogue of T tenant
    models, and the shard carries a per-row ``"tenant"`` vector
    (``(bucket,)`` int32).  Row r then scores against ``w[tenant[r]]``
    via ONE flat gather — ``w.reshape(-1)[tenant*d + idx]`` with the
    static row stride ``d`` — so a cross-tenant batch shares the same
    single compiled executable per bucket, and each row's gathered
    values and reduction order are IDENTICAL to the 1-D gather-sum a
    single-tenant server runs on ``w[tenant[r]]``: per-tenant answers
    are bit-identical to T independent servers (pinned,
    tests/test_serving.py).  Padded slots (tenant 0, index 0, value 0)
    contribute exactly 0, the unchanged padding convention.
    """
    if w.ndim == 2:
        stride = w.shape[1]
        flat_idx = (shard["tenant"][:, None] * stride
                    + shard["sp_indices"])
        m = (gather_dequant(w.reshape(-1), flat_idx)
             * shard["sp_values"]).sum(-1)
    else:
        m = (gather_dequant(w, shard["sp_indices"])
             * shard["sp_values"]).sum(-1)
        if "X_hot" in shard:
            m = m + shard["X_hot"] @ gather_dequant(w,
                                                    shard["hot_cols"])
    if scale is not None:
        m = m * scale
    return m


def shards_axpy(coefs: jax.Array, shards: dict, vec: jax.Array) -> jax.Array:
    """vec + Σ_{k,i} coefs[k,i] · x_{k,i} over EVERY row of the stacked
    (K, …) shard arrays — the transpose counterpart of
    :func:`shard_margins` (margins contract each row against a d-vector;
    this scatters one coefficient per row back into a d-vector).  Used by
    the ``--accel`` secant jump (solvers/cocoa.py): the extrapolated
    dual's exact correspondence update Δw = Σ y·Δα·x/(λn) in one batched
    pass at eval cadence.

    Same layout dispatch and padding conventions as the row accessors
    above: padded CSR slots carry value 0, so they contribute exactly 0;
    the hybrid split's hot and cold columns are disjoint, so the two
    scatters never race on a coordinate.  TRAINING-side: never reads the
    dense eval twin.
    """
    import jax.numpy as jnp

    if "X" in shards:
        return vec + jnp.einsum("kn,knd->d", coefs, shards["X"])
    vec = vec.at[shards["sp_indices"]].add(
        coefs[..., None] * shards["sp_values"])
    if "X_hot" in shards:
        # hot_cols arrives (K, n_hot) — replicated per shard by the
        # loader — so the panel contribution scatters per shard: a
        # summed (n_hot,) update here would be added K times by the
        # leading index dim (pinned against the dense einsum in
        # tests/test_accel.py::test_shards_axpy_hybrid_matches_dense)
        vec = vec.at[shards["hot_cols"]].add(
            jnp.einsum("kn,knh->kh", coefs, shards["X_hot"]))
    return vec


def eval_margins(w: jax.Array, shard: dict) -> jax.Array:
    """EVAL-side :func:`shard_margins`: additionally prefers the dense
    eval twin ``X_eval`` (data/sharding.py ``eval_dense=True``) — the
    certificate's full margins pass then rides one MXU matvec instead of
    an every-nonzero w-gather.  Measured through the production rcv1
    device-loop path, the gather-based eval was 31% of the round time
    (9.42 -> 6.46 ms/round with the twin).  Without the twin, a HYBRID
    shard (``--hotCols`` + ``--evalDense=auto`` when the twin exceeds the
    HBM budget) still gets most of that win structurally: the falls-through
    :func:`shard_margins` runs the hot majority of nonzeros as one MXU
    panel matvec and gathers only the residual tail.  Eval-only by
    construction: training uses :func:`shard_margins` directly, which
    never reads the twin, so trained (w, α) are bit-identical with or
    without it."""
    if "X_eval" in shard:
        return shard["X_eval"] @ w
    return shard_margins(w, shard)
