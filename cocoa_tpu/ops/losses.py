"""Pluggable loss objectives for the primal-dual solvers.

The reference is hinge-only but explicitly designed for swappable local
solvers/objectives (README.md:14, CoCoA.scala:13-14); BASELINE.md lists the
smoothed-hinge / logistic local-solver variant as an evaluation config.  This
module is the single place a loss is defined; solvers and evals look
everything up by ``params.loss`` name so adding a loss means adding one entry
here plus an oracle for the tests.

Each loss ℓ acts on the margin z = y·(x·w) and ships four pieces:

- ``primal(z)``      — elementwise loss value (the avg-loss term of the
                        primal objective, OptUtils.scala:65-75 shape)
- ``dual_term(a)``   — per-example −ℓ*(−α) so the dual objective is
                        −(λ/2)‖w‖² + Σ dual_term(αᵢ)/n (OptUtils.scala:80-84
                        generalized; for hinge this is Σα/n exactly)
- ``grad_factor(z)`` — g(z) = −ℓ'(z) ∈ [0,1]; (sub)gradient methods
                        accumulate y·g(z)·x (SGD.scala:124-127 generalized,
                        where hinge's g is the 0/1 "active" indicator)
- ``alpha_step(a, z, qii, lam_n)`` — the SDCA single-coordinate dual ascent
                        update (CoCoA.scala:166-178 generalized), with qii
                        already σ′-scaled by the caller

Closed forms (α ∈ [0,1] throughout; derivations in the docstrings):

- hinge           ℓ(z) = max(0, 1−z);      −ℓ*(−α) = α
- smooth_hinge(s) ℓ(z) = 0 | 1−z−s/2 | (1−z)²/(2s);  −ℓ*(−α) = α − s·α²/2
                  (quadratically smoothed hinge, SDCA smoothing parameter s)
- logistic        ℓ(z) = log(1+e^{−z});    −ℓ*(−α) = entropy
                  −α·log α − (1−α)·log(1−α); coordinate step has no closed
                  form → damped Newton on the scalar subproblem
"""

from __future__ import annotations

import jax.numpy as jnp

LOSSES = ("hinge", "smooth_hinge", "logistic")
# scalar prox rules for the primal (ProxCoCoA+) solvers — valid for
# alpha_step but NOT classification losses (no primal/dual_term/grad_factor)
PROX_RULES = ("lasso",)

# α clamp for logistic: the entropy dual needs α ∈ (0,1) strictly
_EPS = 1e-12
_U_MAX = 35.0  # |logit| cap: σ(±35) is exact 0/1 in f32, underflow-safe
_NEWTON_ITERS = 10


def validate(loss: str, smoothing=None) -> str:
    if loss not in LOSSES + PROX_RULES:
        raise ValueError(
            f"loss must be one of {LOSSES + PROX_RULES}, got {loss!r}"
        )
    if loss == "smooth_hinge" and smoothing is not None and smoothing <= 0.0:
        # s ≤ 0 flips the ascent denominator's sign / divides by zero
        raise ValueError(
            f"smooth_hinge needs smoothing > 0, got {smoothing}"
        )
    if loss == "lasso" and smoothing is not None and smoothing < 0.0:
        raise ValueError(
            f"lasso's smoothing is the elastic-net l2 weight, needs >= 0, "
            f"got {smoothing}"
        )
    return loss


def primal(loss: str, z, smoothing: float = 1.0):
    """Elementwise ℓ(z) on the margin z = y·(x·w)."""
    if loss == "hinge":
        return jnp.maximum(1.0 - z, 0.0)  # OptUtils.scala:57-61
    if loss == "smooth_hinge":
        s = smoothing
        # 0 for z≥1; 1−z−s/2 for z≤1−s; (1−z)²/(2s) between
        gap = 1.0 - z
        return jnp.where(
            gap <= 0.0,
            0.0,
            jnp.where(gap >= s, gap - 0.5 * s, 0.5 * gap * gap / s),
        )
    if loss == "logistic":
        # stable softplus(−z)
        return jnp.logaddexp(0.0, -z)
    raise ValueError(f"unknown loss {loss!r}")


def dual_term(loss: str, a, smoothing: float = 1.0):
    """Per-example −ℓ*(−α): the dual objective is
    −(λ/2)‖w‖² + Σ dual_term(αᵢ)/n."""
    if loss == "hinge":
        return a  # Σα/n term, OptUtils.scala:82-83
    if loss == "smooth_hinge":
        return a - 0.5 * smoothing * a * a
    if loss == "logistic":
        # xlogy gives the correct 0·log0 = 0 limit at the box corners — an
        # eps-clip is NOT enough: in f32, 1 − 1e-12 rounds to exactly 1.0 and
        # (1−α)·log1p(−α) becomes 0·(−inf) = NaN once a coordinate saturates
        from jax.scipy.special import xlogy

        ac = jnp.clip(a, 0.0, 1.0)
        return -(xlogy(ac, ac) + xlogy(1.0 - ac, 1.0 - ac))
    raise ValueError(f"unknown loss {loss!r}")


def grad_factor(loss: str, z, smoothing: float = 1.0):
    """g(z) = −ℓ'(z) ∈ [0,1]; (sub)gradient methods accumulate y·g·x.
    Hinge's subgradient choice matches the reference exactly: active iff
    1 − z > 0 (SGD.scala:115,124 — the flat side takes 0 at z=1)."""
    if loss == "hinge":
        return jnp.where(1.0 - z > 0.0, 1.0, 0.0)
    if loss == "smooth_hinge":
        return jnp.clip((1.0 - z) / smoothing, 0.0, 1.0)
    if loss == "logistic":
        return jnp.where(z >= 0.0, jnp.exp(-z) / (1.0 + jnp.exp(-z)),
                         1.0 / (1.0 + jnp.exp(z)))  # σ(−z), stable both tails
    raise ValueError(f"unknown loss {loss!r}")


def alpha_step(loss: str, a, z, qii, lam_n, smoothing: float = 1.0):
    """Single-coordinate update: SDCA dual ascent → new α ∈ [0,1] for the
    classification losses; prox-CD → new (unbounded) coordinate value for
    the ``PROX_RULES``.

    ``z`` is the margin the subproblem sees (mode-dependent: w, w+Δw, or
    w+σ′Δw — the caller computes it); ``qii`` is the σ′-scaled ‖x‖².

    - hinge: the reference's exact sequence — projected gradient against the
      box's active face, vanishing-gradient no-op, qii==0 → 1, clip
      (CoCoA.scala:166-178).
    - smooth_hinge: maximizing δ in the smoothed dual adds an s·λn quadratic
      to the denominator and an s·α term to the gradient:
      α ← clip(α − ((z−1+s·α)·λn) / (qii + s·λn), 0, 1); s→0 recovers hinge
      (and qii==0 no longer needs a special case — the denominator is >0).
    - logistic: ∂δ of [entropy(α+δ)/n − z·δ/n − δ²·qii/(2λn²)] = 0 has no
      closed form.  Solved in logit space u = log(α′/(1−α′)) where the
      stationarity condition becomes g(u) = u + z + q·(σ(u) − α) = 0 with
      q = qii/λn: g is strictly increasing with g′ = 1 + q·σ′(u) ≥ 1, so
      Newton is well-conditioned everywhere and the box constraint is
      enforced by the sigmoid itself (no boundary clamping that can stall).
    """
    if loss == "hinge":
        grad = (z - 1.0) * lam_n
        proj_grad = jnp.where(
            a <= 0.0,
            jnp.minimum(grad, 0.0),
            jnp.where(a >= 1.0, jnp.maximum(grad, 0.0), grad),
        )
        safe_qii = jnp.where(qii != 0.0, qii, 1.0)
        new_a = jnp.where(
            qii != 0.0, jnp.clip(a - grad / safe_qii, 0.0, 1.0), 1.0
        )
        return jnp.where(proj_grad != 0.0, new_a, a)
    if loss == "smooth_hinge":
        s = smoothing
        grad = (z - 1.0 + s * a) * lam_n
        return jnp.clip(a - grad / (qii + s * lam_n), 0.0, 1.0)
    if loss == "logistic":
        ac = jnp.clip(a, _EPS, 1.0 - _EPS)
        q = qii / lam_n
        u = jnp.clip(jnp.log(ac / (1.0 - ac)), -_U_MAX, _U_MAX)
        for _ in range(_NEWTON_ITERS):
            sig = 1.0 / (1.0 + jnp.exp(-u))
            g = u + z + q * (sig - ac)
            gp = 1.0 + q * sig * (1.0 - sig)
            u = jnp.clip(u - g / gp, -_U_MAX, _U_MAX)
        return 1.0 / (1.0 + jnp.exp(-u))
    if loss == "lasso":
        # ProxCoCoA+ primal coordinate step (mode="prox"): ``a`` is the
        # working coordinate value x_j + Δx_j, ``z`` the σ′-corrected
        # gradient a_jᵀ(r₀ + σ′Δv), ``qii`` = σ′·‖a_j‖², ``lam_n`` the L1
        # weight λ, ``smoothing`` the elastic-net l2 weight s (0 = lasso).
        # Exact minimizer over t = a + δ of
        #   (z − qii·a)·t + (qii + s)/2·t² + λ|t|
        # is the soft-threshold t* = S_{λ/(qii+s)}((qii·a − z)/(qii + s));
        # a zero column with s=0 (qii==0) is a no-op.
        denom = qii + smoothing
        safe = jnp.where(denom > 0.0, denom, 1.0)
        u = (qii * a - z) / safe
        thr = lam_n / safe
        t = jnp.sign(u) * jnp.maximum(jnp.abs(u) - thr, 0.0)
        return jnp.where(denom > 0.0, t, a)
    raise ValueError(f"unknown loss {loss!r}")
