"""Local SDCA — the per-worker inner solver of CoCoA / CoCoA+ / mini-batch CD.

TPU-native re-implementation of the reference's sequential coordinate-ascent
loops (CoCoA.scala:130-192 ``localSDCA`` and MinibatchCD.scala:76-132).  The
H coordinate steps are inherently sequential (step i+1 reads the w/Δw written
by step i — CoCoA.scala:159,183-185), so the loop runs as one fused
``lax.fori_loop`` inside jit with the whole shard resident in HBM; per step:
one row gather, one or two d-dots, a box projection, and a row axpy.

Three statically-selected gradient modes cover the three algorithms:

- ``"cocoa"``  — CoCoA (plus=false): grad reads the locally-advancing w
  (CoCoA.scala:161), w += update each step (:182-184), qii = ‖x‖²       (:174)
- ``"plus"``   — CoCoA+: w frozen; grad reads x·(w + σ′·Δw) (:158-160),
  qii = ‖x‖²·σ′ (:174)
- ``"frozen"`` — mini-batch CD: w frozen, plain grad (MinibatchCD.scala:104),
  qii = ‖x‖² (:114); α still advances within the batch (:123)
- ``"prox"``   — ProxCoCoA+ primal coordinate descent (no reference
  analogue; arXiv:1512.04011 structure): the roles of examples and
  features swap — the shard's "rows" are columns a_j of the design
  matrix, ``w`` is the replicated residual r₀ = Ax − b, ``alpha`` the
  shard's coordinate block of x, and the margin a_jᵀ(r₀ + σ′Δv) feeds a
  prox rule (losses.PROX_RULES) instead of a dual-ascent rule.  Same
  σ′-scaled read structure as "plus"; the Δw axpy coefficient is the raw
  coordinate delta (``coef_divisor`` == 1) rather than y·Δα/(λn)

Sampled indices arrive precomputed as ``idxs`` (H,) — index draws are
data-independent, so hoisting RNG off the device hot path changes nothing
algorithmically; it is what makes the reference-faithful java.util.Random
mode exact (see cocoa_tpu/utils/prng.py).

Row squared norms arrive precomputed per shard (``sq_norms``): the reference
recomputes ‖x‖² every step (CoCoA.scala:173) — same values, wasted FLOPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.rows import get_row, row_axpy, row_dot

MODES = ("cocoa", "plus", "frozen", "prox")


def coef_divisor(mode: str, lam_n: float) -> float:
    """The Δw axpy coefficient is y·(α_new − α)/(λn) for the dual-ascent
    modes (CoCoA.scala:181) but the raw coordinate delta for the primal
    prox mode (Δv += a_j·δ)."""
    return 1.0 if mode == "prox" else lam_n


def _coef_staging(mode: str, lam, n, lam_n, dtype):
    """The one λn/coefficient staging shared by :func:`local_sdca` and
    :func:`local_sdca_fast` (bit-parity-critical — a fix to one path
    must never miss the other).  Returns ``(lam_n, coef_of)``:

    - static path (``lam_n is None``): λn and the divisor are baked-in
      constants from ``lam * n`` — the original arithmetic, untouched;
    - traced path: ``lam_n`` arrives precomputed (possibly per-tenant,
      solvers/fleet.py) and ``coef_of`` MIRRORS XLA's
      divide-by-constant rewrite — the static path's jit folds /λn into
      ·(1/λn) (one f32 reciprocal), so the traced twin multiplies by
      the same f32 reciprocal, computed once at the kernel head, which
      is what keeps a traced-λn fleet lane bit-identical to the solo
      executable (tests/test_fleet.py)."""
    if lam_n is None:
        lam_n = jnp.asarray(lam * n, dtype)
        coef_div = jnp.asarray(coef_divisor(mode, lam * n), dtype)

        def coef_of(y, delta):
            return y * delta / coef_div
    else:
        lam_n = jnp.asarray(lam_n, dtype)
        inv = (jnp.asarray(1.0, dtype) if mode == "prox"
               else jnp.asarray(1.0, dtype) / lam_n)

        def coef_of(y, delta):
            return y * delta * inv
    return lam_n, coef_of


def local_sdca(
    w_init: jax.Array,     # (d,) shared primal vector (replicated)
    alpha: jax.Array,      # (n_shard,) local dual variables
    shard: dict,           # labels, sq_norms, X | sp_indices+sp_values
    idxs: jax.Array,       # (H,) int32 sampled local coordinates
    lam: float,
    n: int,                # GLOBAL example count (primal-dual correspondence)
    mode: str = "cocoa",
    sigma: float = 1.0,    # sigma' = K * gamma, used by mode=="plus"
    loss: str = "hinge",
    smoothing: float = 1.0,
    lam_n=None,
):
    """Run H sequential SDCA steps.  Returns (delta_alpha, delta_w).

    With ``loss="hinge"`` matches the reference bit-for-bit in x64 given the
    same index sequence (validated against tests/oracle.py); the dual-ascent
    coordinate update for other losses comes from ops/losses.py.

    ``lam_n`` (the fleet path, solvers/fleet.py): a precomputed —
    possibly TRACED — λ·n scalar overriding the ``lam * n`` computed
    here, so ONE compiled kernel can serve every tenant of a vmapped
    fleet; ``sigma`` may then be traced too.  The host computes the
    override as float32(float64(λ)·n) — exactly the value the static
    path's cast produces — which is what keeps a T=1 fleet run
    bit-identical to the solo path.
    """
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
    losses.validate(loss, smoothing)
    labels = shard["labels"]
    sq_norms = shard["sq_norms"]
    dtype = w_init.dtype
    lam_n, coef_of = _coef_staging(mode, lam, n, lam_n, dtype)
    sigma_c = jnp.asarray(sigma, dtype)
    one = jnp.asarray(1.0, dtype)

    def step(i, carry):
        w, dw, a_vec = carry
        idx = idxs[i]
        row = get_row(shard, idx)
        y = labels[idx]
        a = a_vec[idx]

        if mode in ("plus", "prox"):
            margin = row_dot(row, w) + sigma_c * row_dot(row, dw)
        else:
            margin = row_dot(row, w)

        qii = sq_norms[idx] * (sigma_c if mode in ("plus", "prox") else one)
        new_a = losses.alpha_step(loss, a, y * margin, qii, lam_n,
                                  smoothing=smoothing)

        coef = coef_of(y, new_a - a)
        dw = row_axpy(row, coef, dw)
        if mode == "cocoa":
            w = row_axpy(row, coef, w)  # local view advances (CoCoA.scala:182-184)
        a_vec = a_vec.at[idx].set(new_a)
        return w, dw, a_vec

    dw0 = jnp.zeros_like(w_init)
    w_final, dw, alpha_final = lax.fori_loop(
        0, idxs.shape[0], step, (w_init, dw0, alpha)
    )
    del w_final
    return alpha_final - alpha, dw


def mode_factors(mode: str, sigma: float):
    """(sig_eff, qii_factor) for the margin decomposition used by the fast
    kernels: x·w_step = margins0[idx] + sig_eff·(x·Δw), where margins0 = X·w₀
    is precomputed once per round (one MXU matvec).

    - cocoa:  w_step = w₀ + Δw exactly (the local w advance accumulates the
      same updates as Δw, CoCoA.scala:182-185) ⇒ sig_eff = 1, qii = ‖x‖².
    - plus:   w frozen, subproblem reads σ′·Δw (CoCoA.scala:158-160)
      ⇒ sig_eff = σ′, qii = ‖x‖²·σ′.
    - frozen: w frozen, no Δw term (MinibatchCD.scala:104)
      ⇒ sig_eff = 0, qii = ‖x‖².
    - prox:   same read structure as plus (r₀ frozen, σ′-scaled Δv reads)
      ⇒ sig_eff = σ′, qii = ‖a_j‖²·σ′.
    """
    if mode == "cocoa":
        return 1.0, 1.0
    if mode in ("plus", "prox"):
        return sigma, sigma
    if mode == "frozen":
        return 0.0, 1.0
    raise ValueError(f"mode must be one of {MODES}, got {mode!r}")


def local_sdca_fast(
    margins0: jax.Array,   # (n_shard,) precomputed x_i·w₀
    alpha: jax.Array,      # (n_shard,)
    shard: dict,
    idxs: jax.Array,       # (H,) int32
    lam: float,
    n: int,
    dw_init: jax.Array,    # (d,) zeros, built from w by the caller so its
                           # varying-axes type matches under shard_map
    mode: str = "cocoa",
    sigma: float = 1.0,
    loss: str = "hinge",
    smoothing: float = 1.0,
    lam_n=None,
):
    """Fast-math variant of :func:`local_sdca`: the per-step w dot is
    replaced by the precomputed round margin plus an incremental Δw dot
    (see :func:`mode_factors`).  Exactly equal in real arithmetic; floating
    point rounds differently than the reference order, so trajectories agree
    to ~1e-6 rather than bit-exactly.  Returns (delta_alpha, delta_w).

    The frozen mode skips the Δw dot entirely — its only sequential state is
    alpha itself.  ``lam_n``: the fleet path's traced λ·n override — same
    contract as on :func:`local_sdca` (``sigma`` may then be traced too;
    ``mode_factors`` passes a traced σ′ through untouched for the plus
    mode the fleet runs).
    """
    losses.validate(loss, smoothing)
    sig_eff, qii_factor = mode_factors(mode, sigma)
    labels = shard["labels"]
    sq_norms = shard["sq_norms"]
    dtype = margins0.dtype
    lam_n, coef_of = _coef_staging(mode, lam, n, lam_n, dtype)
    sig_c = jnp.asarray(sig_eff, dtype)
    qf = jnp.asarray(qii_factor, dtype)

    def step(i, carry):
        dw, a_vec = carry
        idx = idxs[i]
        row = get_row(shard, idx)
        y = labels[idx]
        a = a_vec[idx]

        margin = margins0[idx]
        if mode != "frozen":
            margin = margin + sig_c * row_dot(row, dw)
        qii = sq_norms[idx] * qf
        new_a = losses.alpha_step(loss, a, y * margin, qii, lam_n,
                                  smoothing=smoothing)

        coef = coef_of(y, new_a - a)
        dw = row_axpy(row, coef, dw)
        a_vec = a_vec.at[idx].set(new_a)
        return dw, a_vec

    dw, alpha_final = lax.fori_loop(0, idxs.shape[0], step, (dw_init, alpha))
    return alpha_final - alpha, dw


def local_sdca_block(
    margins0: jax.Array,   # (n_shard,) precomputed x_i·w₀
    alpha: jax.Array,      # (n_shard,)
    shard: dict,
    idxs: jax.Array,       # (H,) int32
    lam: float,
    n: int,
    dw_init: jax.Array,    # (d,) zeros (see local_sdca_fast)
    mode: str = "cocoa",
    sigma: float = 1.0,
    loss: str = "hinge",
    smoothing: float = 1.0,
    block: int = 16,
):
    """Block-coordinate variant of :func:`local_sdca_fast` — same sampled
    index stream, same math, restructured for the MXU.

    The sequential kernels pay a data-dependent O(d) dot + axpy per
    coordinate step (the latency chain the reference's hot loop imposes,
    CoCoA.scala:148-188).  This kernel processes the H steps in ⌈H/B⌉
    blocks of B consecutive draws: per block it gathers the B rows as one
    (B, d) tile, computes the block's Δw margins ``X_B·Δw`` and Gram matrix
    ``G = X_B·X_Bᵀ`` as two MXU matmuls, then replays the B coordinate
    updates as a *scalar* sequential loop in which step j's margin is

        margins0[idx_j] + sig_eff·(X_B·Δw)[j] + sig_eff·Σ_{i<j} c_i·G[i, j]

    — exactly the sequential recurrence, with the running Δw dot replaced
    by cached pairwise dots (identical in real arithmetic; floating point
    reassociates, so trajectories agree to fp tolerance like the fast
    path).  Δw advances once per block via ``cᵀ·X_B``.  The critical path
    per coordinate drops from O(d) memory-bound work to O(B) scalar work;
    the O(B·d) tile work is parallel MXU/VPU traffic.

    Duplicate draws inside a block are exact: α is read/written through the
    shard vector every scalar step, and the Gram term carries the earlier
    occurrence's contribution to the later one's margin.  H is padded up to
    a multiple of B with masked no-op steps.

    The sparse (padded-CSR) layout densifies each block's rows into the
    (B, d) tile first — padded slots carry index 0 / value 0 and scatter
    harmlessly — then runs the identical dense block math.

    This is the portable XLA form (each chained step still pays XLA's ~µs
    loop overhead); the TPU production form is
    :func:`local_sdca_block_batched`, which runs the recurrence as a Pallas
    kernel and serves as the ``--blockSize`` hot path.

    Flag-gated (``--blockSize``); the default path stays the
    reference-faithful strictly-sequential kernel.
    """
    losses.validate(loss, smoothing)
    if block <= 0:
        raise ValueError(f"block must be positive, got {block}")
    sig_eff, qii_factor = mode_factors(mode, sigma)
    labels = shard["labels"]
    sq_norms = shard["sq_norms"]
    dtype = margins0.dtype
    lam_n = jnp.asarray(lam * n, dtype)
    coef_div = jnp.asarray(coef_divisor(mode, lam * n), dtype)
    sig_c = jnp.asarray(sig_eff, dtype)
    qf = jnp.asarray(qii_factor, dtype)
    d = dw_init.shape[0]

    h = idxs.shape[0]
    nb = -(-h // block)
    idxs_b = jnp.pad(idxs, (0, nb * block - h)).reshape(nb, block)
    mask_b = (jnp.arange(nb * block) < h).reshape(nb, block)

    def block_step(carry, inp):
        dw, a_vec = carry
        bidx, bmask = inp
        if "X" in shard:
            xb = shard["X"][bidx]                              # (B, d)
        else:
            spi = shard["sp_indices"][bidx]                    # (B, nnz)
            spv = shard["sp_values"][bidx]
            xb = jnp.zeros((block, d), dtype).at[
                jnp.arange(block)[:, None], spi].add(spv)
            if "X_hot" in shard:
                # hybrid layout: the residual scatter above misses the
                # hot-panel nonzeros — add them at their column ids
                # (disjoint from every residual id, so adds never collide)
                xb = xb.at[jnp.arange(block)[:, None],
                           shard["hot_cols"][None, :]].add(
                    shard["X_hot"][bidx])
        yb = labels[bidx]
        m0b = margins0[bidx]
        qb = sq_norms[bidx] * qf
        if mode != "frozen":
            mb = xb @ dw                                       # (B,)
            gram = xb @ xb.T                                   # (B, B)

        def scalar_step(j, sc):
            coefs, a_vec = sc
            idx = bidx[j]
            a = a_vec[idx]
            margin = m0b[j]
            if mode != "frozen":
                margin = margin + sig_c * (mb[j] + coefs @ gram[:, j])
            new_a = losses.alpha_step(loss, a, yb[j] * margin, qb[j], lam_n,
                                      smoothing=smoothing)
            keep = bmask[j]
            coef = jnp.where(keep, yb[j] * (new_a - a) / coef_div,
                             jnp.asarray(0.0, dtype))
            a_vec = a_vec.at[idx].set(jnp.where(keep, new_a, a))
            return coefs.at[j].set(coef), a_vec

        # init the coef carry from varying data (yb) so its VMA type matches
        # the loop output under shard_map, like the callers do for dw_init
        coefs, a_vec = lax.fori_loop(
            0, block, scalar_step, (yb * jnp.asarray(0.0, dtype), a_vec)
        )
        return (dw + coefs @ xb, a_vec), None

    (dw, alpha_final), _ = lax.scan(
        block_step, (dw_init, alpha), (idxs_b, mask_b)
    )
    return alpha_final - alpha, dw


def local_sdca_block_batched(
    w: jax.Array,          # (d,) shared primal vector (replicated)
    alpha: jax.Array,      # (K, n_shard)
    shards: dict,          # leaves with leading K dim
    idxs_kh: jax.Array,    # (K, H) int32
    lam: float,
    n: int,
    mode: str = "cocoa",
    sigma: float = 1.0,
    loss: str = "hinge",
    smoothing: float = 1.0,
    block: int = 128,
    interpret: bool = False,
    distinct: bool = False,
    sparse_gram: "bool | None" = None,
    pipeline: "bool | None" = None,
):
    """All-K-shards block-coordinate round on one chip — the TPU-native
    shape of :func:`local_sdca_block`, and the ``--blockSize`` hot path.

    Hot configs run ops/pallas_chain.fused_block: ONE kernel per block
    computing the sampled rows' margins, the K Gram matrices, the
    duplicate-equality tile, the B-step lockstep chain, and the Δw update
    entirely in VMEM (see the design note in pallas_chain.py — profiling
    showed the XLA einsum/concat/scatter materialization around the
    chain-only kernel cost ~4 ms/round at epsilon scale, an order of
    magnitude more than the chain itself).  Per block the XLA side does
    only the truly XLA-shaped work: the row-tile gather, the α
    gather/scatter (TPU has no cheap in-kernel vector gather), and two
    (K, d) adds.  Configs whose half-tile does not fit VMEM
    (``fused_fits``) fall back to the split form: per-block XLA einsums
    feeding the chain-only kernel (chain_block_batched).

    Unlike the sequential fast path there is NO whole-shard margins matvec:
    only the H sampled rows' margins are ever computed, from the same row
    tiles the Gram matrices need — at localIterFrac = 0.1 the full-shard
    X·w pass the other paths pay per round reads 10x more of X than the
    round touches (at epsilon scale that pass alone is ~4 ms/round of pure
    HBM traffic).

    Identical real arithmetic to K independent :func:`local_sdca_fast`
    runs.  Precision policy (f32 on TPU): margins/Gram at DEFAULT — the
    precision the fast path's ``shard_margins`` matvec uses — and the Δw
    update accumulated in f32 so the primal-dual correspondence
    ``w = (1/λn)·Σyαx`` the gap certificate rests on stays tight over
    thousands of accumulated blocks.  Returns (delta_alpha (K, n_shard),
    delta_w (K, d)).

    ``distinct=True`` asserts the round's H indices are pairwise distinct
    within every shard (the caller's obligation — true for permuted
    sampling whenever n_local % H == 0, because each round then sits
    inside one epoch's permutation).  That license removes the hottest
    XLA glue around the fused kernel (measured round 5: the per-block α
    scatter was 23% of device time, more than half the kernel itself):
    the α₀ gather hoists to ONE (K, H) gather per round, the per-block
    scatters collapse to ONE batched scatter-add after the scan, and the
    scan carry drops α entirely.  Bit-identical to the per-block path
    under the distinctness precondition: no earlier block of the same
    round can have touched a later block's coordinates, so every chain
    reads exactly the values it would have read, and each coordinate
    receives exactly one add.  Fused path only (the split fallback keeps
    the per-block scatter).

    ``sparse_gram`` selects the SPARSE block-chain path (padded-CSR
    layouts only): the (B, B) block Gram and the margin base are computed
    IN-KERNEL from SMEM-scalar-prefetched CSR streams and the Δw apply is
    a sparse scatter (ops/pallas_sparse.sparse_block_gram/_apply) — no
    (K, B, d) densify.  ``None`` (auto) picks it for sparse layouts the
    fused kernel cannot hold (the rcv1 regime, where the densified tile is
    ~650x the rows' nonzero bytes) whenever the CSR streams fit the SMEM
    segmentation (sparse_chain_fits); ``True`` forces it (tests),
    ``False`` disables.  Same math as the split path — the chain kernel
    consumes the identical (scal, gq) contract — so trajectory parity
    carries over; the α update stays per-block (``distinct`` is a fused-
    path-only license).  On a HYBRID layout (the ``--hotCols`` hot/cold
    column split — ``X_hot``/``hot_cols`` in the shard dict, docs/DESIGN.md
    §3b-vi) this path becomes the hybrid branch: the streams carry only
    the cold residual, and the hot-panel majority of nonzeros joins the
    Gram as one MXU (B, n_hot)·(n_hot, B) panel matmul, the margin base
    as a panel matvec, and the apply as coefᵀ·panel into a separate hot
    Δw — same chain, same contract, exact column-partitioned split.

    ``pipeline`` (None = auto: on whenever the round spans more than one
    block) software-pipelines the dense block scan into a two-phase
    schedule: the row tile for block b+1 is gathered by block b's scan
    iteration — as an op with NO data dependence on block b's chain
    kernel — and rides the scan carry into iteration b+1.  The round-5
    trace (benchmarks/TRACE.md) showed the serial schedule spending
    1.17 ms/round in the row-tile gather and ~0.5 ms in a tile copy
    strictly SERIALIZED with the 1.39 ms chain kernel; the pipelined
    schedule (a) hands XLA's scheduler a gather whose DMA traffic can
    overlap the Pallas kernel's execution window, and (b) lands the
    gather directly in the loop-carried tile buffer instead of a fresh
    per-iteration allocation, which is what fed the ~0.5 ms ``copy.13``
    relayout.  The prefetch reorders memory traffic ONLY — every kernel
    invocation consumes a tile gathered from the same indices by the same
    gather op, so the pipelined and serial schedules are bit-identical
    (pinned by tests/test_block.py); the last block prefetches block 0's
    tile and discards it (one dead gather per round, ~1/nb of the gather
    budget).  ``False`` restores the serial schedule (the A/B control in
    benchmarks/kernels.py).  Scope: the fused and split (dense/densified)
    paths only — the ``sparse_gram`` CSR path returns before the pipeline
    machinery and always runs its serial schedule (its streams are
    SMEM-prefetched inside the kernels; an explicit ``pipeline`` value is
    inert there, so a pipelined-vs-serial A/B on a sparse-Gram config
    measures nothing).
    """
    from cocoa_tpu.ops.pallas_chain import (
        chain_block_batched, fused_block, fused_fits,
    )
    from cocoa_tpu.ops.pallas_sparse import sparse_chain_fits

    losses.validate(loss, smoothing)
    sig_eff, qii_factor = mode_factors(mode, sigma)
    labels = shards["labels"]
    sq_norms = shards["sq_norms"]
    dtype = w.dtype
    qf = jnp.asarray(qii_factor, dtype)
    sig_c = jnp.asarray(sig_eff, dtype)
    k = alpha.shape[0]
    h = idxs_kh.shape[-1]
    d = w.shape[-1]
    mm = jax.lax.Precision.DEFAULT
    hi = jax.lax.Precision.HIGH

    nb = -(-h // block)
    idxs_b = jnp.pad(idxs_kh, ((0, 0), (0, nb * block - h))) \
        .reshape(k, nb, block).transpose(1, 0, 2)             # (nb, K, B)
    mask_b = (jnp.arange(nb * block) < h).reshape(nb, block)  # (nb, B)

    def gather_rows(bidx):
        """(K, B, d) dense row tile for one block (sparse rows densify —
        padded slots carry index 0 / value 0 and scatter harmlessly; the
        hybrid layout's hot panel scatters at its disjoint column ids)."""
        if "X" in shards:
            return jnp.take_along_axis(shards["X"], bidx[:, :, None], axis=1)
        spi = jnp.take_along_axis(shards["sp_indices"], bidx[:, :, None],
                                  axis=1)
        spv = jnp.take_along_axis(shards["sp_values"], bidx[:, :, None],
                                  axis=1)
        tile = jnp.zeros((k, block, d), dtype).at[
            jnp.arange(k)[:, None, None],
            jnp.arange(block)[None, :, None], spi].add(spv)
        if "X_hot" in shards:
            xh = jnp.take_along_axis(shards["X_hot"], bidx[:, :, None],
                                     axis=1)
            tile = tile.at[jnp.arange(k)[:, None, None],
                           jnp.arange(block)[None, :, None],
                           shards["hot_cols"][:, None, :]].add(xh)
        return tile

    gat = lambda v, bidx: jnp.take_along_axis(v, bidx, axis=1)  # noqa: E731

    itemsize = jnp.dtype(dtype).itemsize
    if sparse_gram is None:
        # auto: the sparse Gram path is the sparse-layout block default
        # whenever the fused kernel cannot hold the densified tile (the
        # rcv1 regime) and the CSR streams fit the SMEM segmentation
        sparse_gram = (
            "sp_indices" in shards
            and itemsize == 4
            and not fused_fits(k, block, d, itemsize, alpha.shape[1])
            and sparse_chain_fits(k, alpha.shape[1], d,
                                  int(shards["sp_indices"].shape[-1]),
                                  block, itemsize)
        )
    if sparse_gram:
        from cocoa_tpu.ops.pallas_sparse import (
            GROUP, row_lengths, sparse_block_apply, sparse_block_gram,
            wd_delta, wd_stack,
        )

        if "sp_indices" not in shards:
            raise ValueError("sparse_gram=True requires the padded-CSR "
                             "(sparse) layout")
        sp_idx, sp_val = shards["sp_indices"], shards["sp_values"]
        w_nnz = sp_idx.shape[-1]
        group = min(GROUP, max(1, w_nnz))
        w_r = -(-w_nnz // group) * group
        row_len = shards.get("sp_row_len")
        if row_len is None:
            row_len = row_lengths(sp_val)
        frozen = mode == "frozen"
        wd0 = wd_stack(w, k)
        # HYBRID branch (hot/cold column split, docs/DESIGN.md §3b-vi):
        # the CSR streams above are the COLD RESIDUAL only; the hot-panel
        # majority of the nonzeros rides the MXU — per block one
        # (B, n_hot)·(n_hot, B) panel Gram matmul, one panel margin-base
        # matvec against [w_hot + σ′Δw_hot], and one coefᵀ·panel apply
        # into a separately-carried (K, n_hot) hot Δw.  Columns partition
        # between panel and streams, so gram/mbase/Δw each split exactly
        # (hot + cold permutes the per-nonzero sums; parity pinned by
        # tests/test_hybrid_sparse.py).
        hybrid = "X_hot" in shards
        if hybrid:
            xh_all = shards["X_hot"]                  # (K, n_shard, n_hot)
            hot_cols_k = shards["hot_cols"]           # (K, n_hot)
            wh = jnp.take_along_axis(
                jnp.broadcast_to(w[None], (k, d)), hot_cols_k, axis=1)
            dwh0 = jnp.zeros_like(wh)                 # (K, n_hot)

        def sparse_block_step(carry, inp):
            if hybrid:
                wd, dwh, a_vec = carry
            else:
                wd, a_vec = carry        # (K, d/128, 2·128), (K, n_shard)
            bidx, bmask = inp            # (K, B), (B,)
            gidx = jnp.take_along_axis(sp_idx, bidx[:, :, None], axis=1)
            gvals = jnp.take_along_axis(sp_val, bidx[:, :, None], axis=1) \
                .astype(dtype)
            if w_r != w_nnz:
                # pad the slot axis to the GROUP-rounded width the trip
                # counts assume (zero slots are inert)
                pad3 = ((0, 0), (0, 0), (0, w_r - w_nnz))
                gidx = jnp.pad(gidx, pad3)
                gvals = jnp.pad(gvals, pad3)
            cnts = jnp.where(bmask[None, :],
                             jnp.take_along_axis(row_len, bidx, axis=1),
                             jnp.int32(-1))
            gram, mbase = sparse_block_gram(
                wd, gidx, gvals, cnts, sig_eff=sig_eff, frozen=frozen,
                interpret=interpret,
            )
            if hybrid:
                xh_b = jnp.take_along_axis(xh_all, bidx[:, :, None],
                                           axis=1)   # (K, B, n_hot)
                v_hot = wh if frozen else wh + sig_c * dwh
                mbase = mbase + jnp.einsum("kbh,kh->kb", xh_b, v_hot,
                                           precision=mm)
                if not frozen:
                    # full panel Gram; the chain reads only i < j entries,
                    # exactly as the split dense path's full einsum Gram
                    gram = gram + jnp.einsum("kjh,kih->jki", xh_b, xh_b,
                                             precision=mm)
            eq_t = (bidx.T[:, :, None] == bidx[None, :, :]).astype(dtype)
            gq = eq_t if frozen else jnp.concatenate([gram, eq_t], axis=1)
            scal = jnp.stack([
                mbase, gat(labels, bidx), gat(sq_norms, bidx) * qf,
                gat(a_vec, bidx),
                jnp.zeros_like(mbase),  # within-block Δw margin is in gram
                jnp.broadcast_to(bmask[None].astype(dtype), (k, block)),
            ], axis=1)                                    # (K, 6, B)
            delta, coefs = chain_block_batched(
                scal, gq,
                lam_n=float(lam * n),
                coef_div=float(coef_divisor(mode, lam * n)),
                sig_eff=float(sig_eff), frozen=frozen,
                loss=loss, smoothing=smoothing, interpret=interpret,
            )
            a_vec = a_vec.at[jnp.arange(k)[:, None], bidx].add(delta)
            wd = sparse_block_apply(wd, gidx, gvals, cnts, coefs,
                                    interpret=interpret)
            if hybrid:
                dwh = dwh + jnp.einsum("kb,kbh->kh", coefs, xh_b,
                                       precision=hi)
                return (wd, dwh, a_vec), None
            return (wd, a_vec), None

        if hybrid:
            (wd, dwh, alpha_final), _ = lax.scan(
                sparse_block_step, (wd0, dwh0, alpha), (idxs_b, mask_b)
            )
            dw = wd_delta(wd, d)
            # hot and cold columns are disjoint; panel-padding lanes carry
            # value 0 at column 0, so this scatter-add is exact
            dw = dw.at[jnp.arange(k)[:, None], hot_cols_k].add(dwh)
            return alpha_final - alpha, dw
        (wd, alpha_final), _ = lax.scan(
            sparse_block_step, (wd0, alpha), (idxs_b, mask_b)
        )
        return alpha_final - alpha, wd_delta(wd, d)

    # software pipeline (see the ``pipeline`` docstring note): block b's
    # scan iteration also issues block b+1's row-tile gather — the one
    # per-block input with no dependence on b's kernel — so the gather's
    # HBM traffic can hide behind the chain kernel instead of serializing
    # with it.  The last block prefetches block 0's tile (discarded).
    if pipeline is None:
        pipeline = nb > 1
    idxs_next = jnp.roll(idxs_b, -1, axis=0) if pipeline else None

    def pipelined_scan(body, carry0, xs):
        """Run ``body(carry, xb, *x_leaves) -> carry, out`` over the
        blocks with the row tile double-buffered through the scan carry
        (pipelined) or gathered in-iteration (serial).  Bit-identical
        either way: the same gather feeds the same kernel."""
        if not pipeline:
            def step(carry, inp):
                return body(carry, gather_rows(inp[0]), *inp)

            return lax.scan(step, carry0, xs)

        def step(carry, inp):
            inner, xb = carry
            bnext = inp[-1]
            xb_next = gather_rows(bnext)    # block b+1: independent of
            inner, out = body(inner, xb, *inp[:-1])   # block b's kernel
            return (inner, xb_next), out

        (carry, _), outs = lax.scan(
            step, (carry0, gather_rows(idxs_b[0])), (*xs, idxs_next)
        )
        return carry, outs

    if fused_fits(k, block, d, itemsize,
                  alpha.shape[1]):
        dw0 = jnp.zeros((k, d), dtype) + 0.0 * w[None]

        def fused_call(dw, xb, bidx, yb, qb, live, a0b):
            if mode == "frozen":
                v = jnp.broadcast_to(w[None], (k, d)).astype(dtype)
            else:
                v = w[None] + sig_c * dw
            return fused_block(
                xb, bidx.astype(dtype), yb, qb, a0b, live, v,
                lam_n=float(lam * n),
                coef_div=float(coef_divisor(mode, lam * n)),
                sig_eff=float(sig_eff), frozen=(mode == "frozen"),
                loss=loss, smoothing=smoothing, interpret=interpret,
            )

        def live_of(bmask):
            return jnp.broadcast_to(bmask[None].astype(dtype), (k, block))

        if distinct:
            # pairwise-distinct indices (caller-checked): the per-block α
            # gather/scatter (the hottest glue in the round-5 trace)
            # vanishes — α₀ comes from the per-round (K, ns, 3) stack, the
            # per-step deltas ride out as scan outputs, and α takes ONE
            # batched scatter-add per round.  The y/q/α₀ gathers also
            # merge into ONE width-3 row gather per block: TPU scalar
            # gathers pay per index fetched, and three fetches from the
            # same index vector are pure waste.  The stack costs one
            # streaming write per round (~6 µs at epsilon scale) against
            # ~0.6 ms of saved gather.  Gathering per BLOCK (not one
            # hoisted per-round gather) keeps the gather carry-independent
            # — so it pipelines — and kills the (nb, K, B, 3) transposed
            # staging copy the hoisted form materialized as scan inputs.
            yqa = jnp.stack([labels, sq_norms * qf, alpha], axis=-1)

            def body(dw, xb, bidx, bmask):
                g = jnp.take_along_axis(yqa, bidx[:, :, None], axis=1)
                yb, qb, a0b = g[..., 0], g[..., 1], g[..., 2]
                delta, dwu = fused_call(dw, xb, bidx, yb, qb,
                                        live_of(bmask), a0b)
                # (a0+δ)−a0 on the gathered values == what the old
                # alpha.at[].add(δ)−alpha computed at these coordinates,
                # bit for bit — but scattered into ZEROS below, so α is
                # never copied to preserve the subtrahend (the donation
                # miss behind the round-5 trace's copy glue)
                return dw + dwu, (a0b + delta) - a0b

            dw, dvals = pipelined_scan(body, dw0, (idxs_b, mask_b))
            flat = idxs_b.transpose(1, 0, 2).reshape(k, nb * block)
            dval_flat = dvals.transpose(1, 0, 2).reshape(k, nb * block)
            da = jnp.zeros_like(alpha).at[
                jnp.arange(k)[:, None], flat].add(dval_flat)
            return da, dw

        # non-distinct: α must ride the carry (a later block may re-draw
        # an earlier block's coordinate), but y/q still merge into one
        # width-2 per-block gather from a per-round stack
        yq = jnp.stack([labels, sq_norms * qf], axis=-1)

        def body(carry, xb, bidx, bmask):
            dw, a_vec = carry            # (K, d), (K, n_shard)
            g = jnp.take_along_axis(yq, bidx[:, :, None], axis=1)
            delta, dwu = fused_call(dw, xb, bidx, g[..., 0], g[..., 1],
                                    live_of(bmask), gat(a_vec, bidx))
            a_vec = a_vec.at[jnp.arange(k)[:, None], bidx].add(delta)
            return (dw + dwu, a_vec), None

        (dw, alpha_final), _ = pipelined_scan(
            body, (dw0, alpha), (idxs_b, mask_b)
        )
        return alpha_final - alpha, dw

    # legacy split path: per-block XLA einsums feeding the chain-only
    # kernel (configs whose half-tile does not fit VMEM); the row-tile
    # prefetch applies unchanged — the gather is the same op

    def body(carry, xb, bidx, bmask):
        dw, a_vec = carry            # (K, d), (K, n_shard)
        # the equality tile, directly in the kernel's (B, K, B)
        # j-sliceable layout: eq_t[j, k, i] = (idx_i == idx_j) in shard k
        eq_t = (bidx.T[:, :, None] == bidx[None, :, :]).astype(dtype)
        if mode == "frozen":
            # frozen margins never see Δw: base = X_B·w, no Gram needed
            mbase = jnp.einsum("kbd,d->kb", xb, w, precision=mm)
            gq = eq_t
        else:
            # one matvec carries both margin terms:
            # x·w + sig_eff·(x·Δw_blockstart)
            mbase = jnp.einsum("kbd,kd->kb", xb, w[None] + sig_c * dw,
                               precision=mm)
            gq = jnp.concatenate(
                [jnp.einsum("kjd,kid->jki", xb, xb, precision=mm), eq_t],
                axis=1,
            )                                             # (B, 2K, B)
        scal = jnp.stack([
            mbase, gat(labels, bidx), gat(sq_norms, bidx) * qf,
            gat(a_vec, bidx),
            jnp.zeros_like(mbase),  # within-block Δw margin lives in gram
            jnp.broadcast_to(bmask[None].astype(dtype), (k, block)),
        ], axis=1)                                        # (K, 6, B)
        delta, coefs = chain_block_batched(
            scal, gq,
            lam_n=float(lam * n),
            coef_div=float(coef_divisor(mode, lam * n)),
            sig_eff=float(sig_eff), frozen=(mode == "frozen"),
            loss=loss, smoothing=smoothing, interpret=interpret,
        )
        a_vec = a_vec.at[jnp.arange(k)[:, None], bidx].add(delta)
        dw = dw + jnp.einsum("kb,kbd->kd", coefs, xb, precision=hi)
        return (dw, a_vec), None

    dw0 = jnp.zeros((k, d), dtype) + 0.0 * w[None]  # inherit w's VMA type
    (dw, alpha_final), _ = pipelined_scan(
        body, (dw0, alpha), (idxs_b, mask_b)
    )
    return alpha_final - alpha, dw
