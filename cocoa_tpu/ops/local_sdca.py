"""Local SDCA — the per-worker inner solver of CoCoA / CoCoA+ / mini-batch CD.

TPU-native re-implementation of the reference's sequential coordinate-ascent
loops (CoCoA.scala:130-192 ``localSDCA`` and MinibatchCD.scala:76-132).  The
H coordinate steps are inherently sequential (step i+1 reads the w/Δw written
by step i — CoCoA.scala:159,183-185), so the loop runs as one fused
``lax.fori_loop`` inside jit with the whole shard resident in HBM; per step:
one row gather, one or two d-dots, a box projection, and a row axpy.

Three statically-selected gradient modes cover the three algorithms:

- ``"cocoa"``  — CoCoA (plus=false): grad reads the locally-advancing w
  (CoCoA.scala:161), w += update each step (:182-184), qii = ‖x‖²       (:174)
- ``"plus"``   — CoCoA+: w frozen; grad reads x·(w + σ′·Δw) (:158-160),
  qii = ‖x‖²·σ′ (:174)
- ``"frozen"`` — mini-batch CD: w frozen, plain grad (MinibatchCD.scala:104),
  qii = ‖x‖² (:114); α still advances within the batch (:123)
- ``"prox"``   — ProxCoCoA+ primal coordinate descent (no reference
  analogue; arXiv:1512.04011 structure): the roles of examples and
  features swap — the shard's "rows" are columns a_j of the design
  matrix, ``w`` is the replicated residual r₀ = Ax − b, ``alpha`` the
  shard's coordinate block of x, and the margin a_jᵀ(r₀ + σ′Δv) feeds a
  prox rule (losses.PROX_RULES) instead of a dual-ascent rule.  Same
  σ′-scaled read structure as "plus"; the Δw axpy coefficient is the raw
  coordinate delta (``coef_divisor`` == 1) rather than y·Δα/(λn)

Sampled indices arrive precomputed as ``idxs`` (H,) — index draws are
data-independent, so hoisting RNG off the device hot path changes nothing
algorithmically; it is what makes the reference-faithful java.util.Random
mode exact (see cocoa_tpu/utils/prng.py).

Row squared norms arrive precomputed per shard (``sq_norms``): the reference
recomputes ‖x‖² every step (CoCoA.scala:173) — same values, wasted FLOPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.rows import get_row, row_axpy, row_dot

MODES = ("cocoa", "plus", "frozen", "prox")


def coef_divisor(mode: str, lam_n: float) -> float:
    """The Δw axpy coefficient is y·(α_new − α)/(λn) for the dual-ascent
    modes (CoCoA.scala:181) but the raw coordinate delta for the primal
    prox mode (Δv += a_j·δ)."""
    return 1.0 if mode == "prox" else lam_n


def local_sdca(
    w_init: jax.Array,     # (d,) shared primal vector (replicated)
    alpha: jax.Array,      # (n_shard,) local dual variables
    shard: dict,           # labels, sq_norms, X | sp_indices+sp_values
    idxs: jax.Array,       # (H,) int32 sampled local coordinates
    lam: float,
    n: int,                # GLOBAL example count (primal-dual correspondence)
    mode: str = "cocoa",
    sigma: float = 1.0,    # sigma' = K * gamma, used by mode=="plus"
    loss: str = "hinge",
    smoothing: float = 1.0,
):
    """Run H sequential SDCA steps.  Returns (delta_alpha, delta_w).

    With ``loss="hinge"`` matches the reference bit-for-bit in x64 given the
    same index sequence (validated against tests/oracle.py); the dual-ascent
    coordinate update for other losses comes from ops/losses.py.
    """
    if mode not in MODES:
        raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
    losses.validate(loss, smoothing)
    labels = shard["labels"]
    sq_norms = shard["sq_norms"]
    dtype = w_init.dtype
    lam_n = jnp.asarray(lam * n, dtype)
    coef_div = jnp.asarray(coef_divisor(mode, lam * n), dtype)
    sigma_c = jnp.asarray(sigma, dtype)
    one = jnp.asarray(1.0, dtype)

    def step(i, carry):
        w, dw, a_vec = carry
        idx = idxs[i]
        row = get_row(shard, idx)
        y = labels[idx]
        a = a_vec[idx]

        if mode in ("plus", "prox"):
            margin = row_dot(row, w) + sigma_c * row_dot(row, dw)
        else:
            margin = row_dot(row, w)

        qii = sq_norms[idx] * (sigma_c if mode in ("plus", "prox") else one)
        new_a = losses.alpha_step(loss, a, y * margin, qii, lam_n,
                                  smoothing=smoothing)

        coef = y * (new_a - a) / coef_div
        dw = row_axpy(row, coef, dw)
        if mode == "cocoa":
            w = row_axpy(row, coef, w)  # local view advances (CoCoA.scala:182-184)
        a_vec = a_vec.at[idx].set(new_a)
        return w, dw, a_vec

    dw0 = jnp.zeros_like(w_init)
    w_final, dw, alpha_final = lax.fori_loop(
        0, idxs.shape[0], step, (w_init, dw0, alpha)
    )
    del w_final
    return alpha_final - alpha, dw


def mode_factors(mode: str, sigma: float):
    """(sig_eff, qii_factor) for the margin decomposition used by the fast
    kernels: x·w_step = margins0[idx] + sig_eff·(x·Δw), where margins0 = X·w₀
    is precomputed once per round (one MXU matvec).

    - cocoa:  w_step = w₀ + Δw exactly (the local w advance accumulates the
      same updates as Δw, CoCoA.scala:182-185) ⇒ sig_eff = 1, qii = ‖x‖².
    - plus:   w frozen, subproblem reads σ′·Δw (CoCoA.scala:158-160)
      ⇒ sig_eff = σ′, qii = ‖x‖²·σ′.
    - frozen: w frozen, no Δw term (MinibatchCD.scala:104)
      ⇒ sig_eff = 0, qii = ‖x‖².
    - prox:   same read structure as plus (r₀ frozen, σ′-scaled Δv reads)
      ⇒ sig_eff = σ′, qii = ‖a_j‖²·σ′.
    """
    if mode == "cocoa":
        return 1.0, 1.0
    if mode in ("plus", "prox"):
        return sigma, sigma
    if mode == "frozen":
        return 0.0, 1.0
    raise ValueError(f"mode must be one of {MODES}, got {mode!r}")


def local_sdca_fast(
    margins0: jax.Array,   # (n_shard,) precomputed x_i·w₀
    alpha: jax.Array,      # (n_shard,)
    shard: dict,
    idxs: jax.Array,       # (H,) int32
    lam: float,
    n: int,
    dw_init: jax.Array,    # (d,) zeros, built from w by the caller so its
                           # varying-axes type matches under shard_map
    mode: str = "cocoa",
    sigma: float = 1.0,
    loss: str = "hinge",
    smoothing: float = 1.0,
):
    """Fast-math variant of :func:`local_sdca`: the per-step w dot is
    replaced by the precomputed round margin plus an incremental Δw dot
    (see :func:`mode_factors`).  Exactly equal in real arithmetic; floating
    point rounds differently than the reference order, so trajectories agree
    to ~1e-6 rather than bit-exactly.  Returns (delta_alpha, delta_w).

    The frozen mode skips the Δw dot entirely — its only sequential state is
    alpha itself.
    """
    losses.validate(loss, smoothing)
    sig_eff, qii_factor = mode_factors(mode, sigma)
    labels = shard["labels"]
    sq_norms = shard["sq_norms"]
    dtype = margins0.dtype
    lam_n = jnp.asarray(lam * n, dtype)
    coef_div = jnp.asarray(coef_divisor(mode, lam * n), dtype)
    sig_c = jnp.asarray(sig_eff, dtype)
    qf = jnp.asarray(qii_factor, dtype)

    def step(i, carry):
        dw, a_vec = carry
        idx = idxs[i]
        row = get_row(shard, idx)
        y = labels[idx]
        a = a_vec[idx]

        margin = margins0[idx]
        if mode != "frozen":
            margin = margin + sig_c * row_dot(row, dw)
        qii = sq_norms[idx] * qf
        new_a = losses.alpha_step(loss, a, y * margin, qii, lam_n,
                                  smoothing=smoothing)

        coef = y * (new_a - a) / coef_div
        dw = row_axpy(row, coef, dw)
        a_vec = a_vec.at[idx].set(new_a)
        return dw, a_vec

    dw, alpha_final = lax.fori_loop(0, idxs.shape[0], step, (dw_init, alpha))
    return alpha_final - alpha, dw
