"""Pallas TPU kernel for the block-coordinate scalar recurrence.

The block-coordinate inner solver (ops/local_sdca.local_sdca_block) reduces
each coordinate step's O(d) sequential work to O(B) — margins read cached
block Gram entries instead of re-dotting Δw (the hot-loop contract is
CoCoA.scala:148-188; the restructuring is exact, see that docstring).  But
under plain XLA each of the B chained steps still costs ~µs of loop
overhead, which is the same price the O(d) sequential kernels pay — the
blocking buys nothing (measured: 31 ms/round vs the sequential Pallas
kernel's ~9 ms at epsilon scale).

This kernel runs the whole B-step recurrence inside one ``pallas_call``
with every operand VMEM-resident and ZERO dynamic HBM traffic in the chain,
and — the part that actually wins — advances ALL K logical shards' chains
in lockstep inside one kernel instance:

- the per-step scalars (margins0, y, ‖x‖²·qf, α₀, X_B·Δw, live-mask) of
  every shard arrive lane-blocked as one (6K, B) tile; a single masked
  reduce yields the step-j column for all shards at once;
- the Gram row for step j arrives for all shards from ONE dynamic sublane
  slice of a precomputed (B, 2K, B) operand (gram is symmetric, so row j ==
  column j), concatenated with the equality rows (below);
- within-block duplicate draws are exact through the equality tiles
  ``eq_k[i, j] = (idx_i == idx_j)``: the live α for step j is
  ``α₀[j] + Σ_i δ_i·eq[i, j]`` — δ_i is zero for i ≥ j, so the sum ranges
  over earlier same-index steps only, exactly the sequential recurrence
  (cross-block duplicates are the caller's additive α scatter);
- the running (2K, B) coef/δ rows live in loop-carried vector registers;
- the coordinate update itself is elementwise on (K, 1) columns — one
  evaluation serves every shard.

Per step that is ~a dozen small VPU ops and one sublane slice FOR ALL K
CHAINS — hundreds of ns where the sequential kernels pay ~1.7 µs per
lockstep — while the O(B·d) tile work (row gathers, Gram matrices, Δw
apply) stays outside in XLA where it lands on the MXU
(local_sdca_block_batched).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from cocoa_tpu.ops import losses

LANES = 128
SCAL_ROWS = 6  # [margins0 | labels | qii | alpha0 | mb | live-mask]
CHAIN_VMEM_BUDGET = 12 << 20  # leave ~4 MB of the ~16 MB VMEM for Mosaic


def chain_vmem_estimate(k: int, b: int, itemsize: int) -> int:
    """Rough VMEM working set of one chain_block_batched instance: the
    (B, 2K, B) gq operand, the (6K, B) scal input + prologue copy, the
    (2K, B) carry + outputs."""
    return itemsize * (2 * k * b * b + 12 * k * b + 6 * k * b)


def chain_fits(k: int, b: int, itemsize: int) -> bool:
    return chain_vmem_estimate(k, b, itemsize) <= CHAIN_VMEM_BUDGET


def _chain_kernel_batched(scal_ref, gq_ref, delta_ref, coef_ref, *,
                          k, b, lam_n, coef_div, sig_eff, frozen, loss,
                          smoothing):
    """All K shards' B-step chains advance in lockstep: one masked reduce
    yields every shard's step scalars as a (·K, 1) column, one dynamic
    sublane slice of the (B, 2K, B) gq operand yields every shard's
    Gram AND duplicate-equality rows at once, one fused (2K, B)
    multiply-reduce forms both chain dots, and the coordinate update
    itself is elementwise on (K, 1) columns — the per-step latency is that
    of ONE chain."""
    gw = k if frozen else 2 * k   # frozen gq carries only the eq rows
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, b), 1)
    dtype = scal_ref.dtype
    scal = scal_ref[...]            # (6K, b)
    zero = jnp.zeros((2 * k, b), dtype)
    one = jnp.asarray(1.0, dtype)

    if loss == "hinge":
        # Hinge collapses algebraically: for qii > 0 the reference's
        # projected-gradient + vanishing-gradient branches are reproduced
        # exactly by the plain clip (at a boundary the clip re-pins α
        # wherever the projection would have zeroed the step), and for
        # qii == 0 the rule is the constant 1 (z = 0 ⇒ grad = −λn ≠ 0).
        # That lets every per-step constant hoist into a vectorized
        # prologue — the chained work per step is the two dots, one clip,
        # and one masked write:
        #     u_j  = a_j − (base_j + S_j·(c·G row j)),  a_j = a0_j + δ·eq row j
        #     α'_j = qii>0 ? clip(u_j, 0, 1) : 1
        m0, y, qii, a0, mb, live = (scal[i * k:(i + 1) * k]
                                    for i in range(6))
        q_safe = jnp.where(qii != 0.0, qii, one)
        base = (y * (m0 + sig_eff * mb) - 1.0) * lam_n / q_safe
        s_row = y * (sig_eff * lam_n) / q_safe
        fac = jnp.concatenate([y * (live / coef_div), live], axis=0)
        pre = jnp.concatenate(
            [base, s_row, a0, jnp.where(qii != 0.0, one, 0.0), fac], axis=0
        )  # (6K, b): [base | S | a0 | qflag | Yl | Ll]

        def step(j, cd):            # cd rows: [coefs_0..K-1 | delta_0..K-1]
            mask = lane == j
            sv = jnp.sum(jnp.where(mask, pre, 0.0), axis=1, keepdims=True)
            gq = gq_ref[pl.ds(j, 1)].reshape(gw, b)
            dots = jnp.sum(cd[2 * k - gw:] * gq, axis=1, keepdims=True)
            a = sv[2 * k:3 * k] + dots[gw - k:]
            u = a - sv[:k]
            if not frozen:
                u = u - sv[k:2 * k] * dots[:k]
            new_a = jnp.where(sv[3 * k:4 * k] > 0.0,
                              jnp.clip(u, 0.0, 1.0), one)
            dm = new_a - a
            upd = sv[4 * k:] * jnp.concatenate([dm, dm], axis=0)
            return jnp.where(mask, upd, cd)

        cd = jax.lax.fori_loop(0, b, step, zero)
        coef_ref[...] = cd[:k]
        delta_ref[...] = cd[k:]
        return

    def step(j, cd):                # cd rows: [coefs_0..K-1 | delta_0..K-1]
        mask = lane == j
        sv = jnp.sum(jnp.where(mask, scal, 0.0), axis=1, keepdims=True)
        m0, y, qii, a0, mb, live = (sv[i * k:(i + 1) * k] for i in range(6))
        gq = gq_ref[pl.ds(j, 1)].reshape(gw, b)
        dots = jnp.sum(cd[2 * k - gw:] * gq, axis=1, keepdims=True)
        if frozen:
            margin = m0
        else:
            margin = m0 + sig_eff * (mb + dots[:k])
        a = a0 + dots[gw - k:]
        new_a = losses.alpha_step(loss, a, y * margin, qii, lam_n,
                                  smoothing=smoothing)
        d_j = (new_a - a) * live
        c_j = y * d_j / coef_div
        return jnp.where(mask, jnp.concatenate([c_j, d_j], axis=0), cd)

    cd = jax.lax.fori_loop(0, b, step, zero)
    coef_ref[...] = cd[:k]
    delta_ref[...] = cd[k:]


@functools.partial(
    jax.jit,
    static_argnames=("lam_n", "coef_div", "sig_eff", "frozen", "loss",
                     "smoothing", "interpret"),
)
def chain_block_batched(
    scal: jax.Array,   # (K, 6, B): [m0 | y | qii | alpha0 | mb | mask]
    gq: jax.Array,     # (B, 2K, B) fused Gram+equality operand:
                       # gq[j, k, i] = x_i·x_j of shard k (transposed Gram,
                       # einsum("kjd,kid->jki")), gq[j, K+k, i] =
                       # (idx_i == idx_j); frozen mode passes (B, K, B)
                       # with only the equality rows
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    frozen: bool,
    loss: str,
    smoothing: float,
    interpret: bool = False,
):
    """Run one block's B-step recurrence for K shards in lockstep.
    Returns ``(delta, coefs)``, both (K, B): per-step α deltas (for the
    caller's additive scatter — duplicate-safe by construction) and Δw
    coefficients (for the caller's ``coefs·X_B`` apply).  B must be a
    multiple of 128 (whole lane tiles)."""
    k, nrows, b = scal.shape
    if nrows != SCAL_ROWS:
        raise ValueError(f"scal must carry {SCAL_ROWS} metric rows, "
                         f"got {nrows}")
    if b % LANES:
        raise ValueError(f"chain_block_batched needs B % {LANES} == 0, "
                         f"got {b}")
    if gq.shape != (b, (k if frozen else 2 * k), b):
        raise ValueError(f"gq shape {gq.shape} does not match frozen={frozen}")
    # (K, 6, B) -> (6K, B) grouped by metric so the kernel's static column
    # slices are [m0_0..m0_K-1 | y_0.. | ...]
    scal_rows = scal.transpose(1, 0, 2).reshape(SCAL_ROWS * k, b)
    kernel = functools.partial(
        _chain_kernel_batched, k=k, b=b, lam_n=lam_n, coef_div=coef_div,
        sig_eff=sig_eff, frozen=frozen,
        loss=losses.validate(loss, smoothing), smoothing=smoothing,
    )
    delta, coefs = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((k, b), scal.dtype),
            jax.ShapeDtypeStruct((k, b), scal.dtype),
        ],
        interpret=interpret,
    )(scal_rows, gq)
    return delta, coefs
