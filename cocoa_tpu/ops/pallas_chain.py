"""Pallas TPU kernel for the block-coordinate scalar recurrence.

The block-coordinate inner solver (ops/local_sdca.local_sdca_block) reduces
each coordinate step's O(d) sequential work to O(B) — margins read cached
block Gram entries instead of re-dotting Δw (the hot-loop contract is
CoCoA.scala:148-188; the restructuring is exact, see that docstring).  But
under plain XLA each of the B chained steps still costs ~µs of loop
overhead, which is the same price the O(d) sequential kernels pay — the
blocking buys nothing (measured: 31 ms/round vs the sequential Pallas
kernel's ~9 ms at epsilon scale).

This kernel runs the whole B-step recurrence inside one ``pallas_call``
with every operand VMEM-resident and ZERO dynamic HBM traffic in the chain,
and — the part that actually wins — advances ALL K logical shards' chains
in lockstep inside one kernel instance:

- the per-step scalars (margins0, y, ‖x‖²·qf, α₀, X_B·Δw, live-mask) of
  every shard arrive lane-blocked as one (6K, B) tile; a single masked
  reduce yields the step-j column for all shards at once;
- the Gram row for step j arrives for all shards from ONE dynamic sublane
  slice of a precomputed (B, 2K, B) operand (gram is symmetric, so row j ==
  column j), concatenated with the equality rows (below);
- within-block duplicate draws are exact through the equality tiles
  ``eq_k[i, j] = (idx_i == idx_j)``: the live α for step j is
  ``α₀[j] + Σ_i δ_i·eq[i, j]`` — δ_i is zero for i ≥ j, so the sum ranges
  over earlier same-index steps only, exactly the sequential recurrence
  (cross-block duplicates are the caller's additive α scatter);
- the running (2K, B) coef/δ rows live in loop-carried vector registers;
- the coordinate update itself is elementwise on (K, 1) columns — one
  evaluation serves every shard.

Per step that is ~a dozen small VPU ops and one sublane slice FOR ALL K
CHAINS — hundreds of ns where the sequential kernels pay ~1.7 µs per
lockstep — while the O(B·d) tile work (row gathers, Gram matrices, Δw
apply) stays outside in XLA where it lands on the MXU
(local_sdca_block_batched).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.pallas_sdca import COMPILER_PARAMS

LANES = 128
SCAL_ROWS = 6  # [margins0 | labels | qii | alpha0 | mb | live-mask]
CHAIN_VMEM_BUDGET = 12 << 20  # leave ~4 MB of the ~16 MB VMEM for Mosaic

# ``--blockSize=auto`` candidates in MEASURED preference order — the B
# sweep in benchmarks/kernels.py (block-{128,256,512} rows, KERNELS.md).
# 128 is the measured-best tile on the epsilon flagship (v5e: 3.94 ms vs
# block-256's 4.25 — and 256 already fails fused_fits there, falling to
# the slower split path; 512 additionally fails chain_fits at K=8 and
# falls all the way to the XLA chain).  The resolver (solvers/cocoa.py
# auto_block_size) walks this ranking and takes the FIRST candidate that
# passes the same fit accounting the dispatch layer uses — a measured
# choice, not largest-that-fits.  Re-rank when benchmarks/kernels.py
# regenerates KERNELS.md with a different winner.
BLOCK_SIZE_PREFERENCE = (128, 256, 512)


def chain_vmem_estimate(k: int, b: int, itemsize: int) -> int:
    """Rough VMEM working set of one chain_block_batched instance: the
    (B, 2K, B) gq operand, the (6K, B) scal input + prologue copy, the
    (2K, B) carry + outputs."""
    return itemsize * (2 * k * b * b + 12 * k * b + 6 * k * b)


def chain_fits(k: int, b: int, itemsize: int) -> bool:
    return chain_vmem_estimate(k, b, itemsize) <= CHAIN_VMEM_BUDGET


def _chain_loop(b, unroll, step, init):
    """B dependent steps as a partially-unrolled fori_loop: ``unroll``
    consecutive steps per loop iteration as straight-line code, so Mosaic
    can hoist/pipeline each step's state-independent slices (the gq row,
    the prologue column) around its neighbours' dependent scalar ops.
    Swept on hardware — see DEFAULT_UNROLL."""
    if unroll <= 1:
        return jax.lax.fori_loop(0, b, step, init)
    assert b % unroll == 0, (b, unroll)

    def group(g, cd):
        for u in range(unroll):
            cd = step(g * unroll + u, cd)
        return cd

    return jax.lax.fori_loop(0, b // unroll, group, init)


def _chain_kernel_batched(scal_ref, gq_ref, delta_ref, coef_ref, *,
                          k, b, lam_n, coef_div, sig_eff, frozen, loss,
                          smoothing, unroll=1):
    """All K shards' B-step chains advance in lockstep: one masked reduce
    yields every shard's step scalars as a (·K, 1) column, one dynamic
    sublane slice of the (B, 2K, B) gq operand yields every shard's
    Gram AND duplicate-equality rows at once, one fused (2K, B)
    multiply-reduce forms both chain dots, and the coordinate update
    itself is elementwise on (K, 1) columns — the per-step latency is that
    of ONE chain."""
    gw = k if frozen else 2 * k   # frozen gq carries only the eq rows
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, b), 1)
    dtype = scal_ref.dtype
    scal = scal_ref[...]            # (6K, b)
    zero = jnp.zeros((2 * k, b), dtype)
    one = jnp.asarray(1.0, dtype)

    if loss == "hinge":
        # Hinge collapses algebraically: for qii > 0 the reference's
        # projected-gradient + vanishing-gradient branches are reproduced
        # exactly by the plain clip (at a boundary the clip re-pins α
        # wherever the projection would have zeroed the step), and for
        # qii == 0 the rule is the constant 1 (z = 0 ⇒ grad = −λn ≠ 0).
        # That lets every per-step constant hoist into a vectorized
        # prologue — the chained work per step is the two dots, one clip,
        # and one masked write:
        #     u_j  = a_j − (base_j + S_j·(c·G row j)),  a_j = a0_j + δ·eq row j
        #     α'_j = qii>0 ? clip(u_j, 0, 1) : 1
        m0, y, qii, a0, mb, live = (scal[i * k:(i + 1) * k]
                                    for i in range(6))
        q_safe = jnp.where(qii != 0.0, qii, one)
        base = (y * (m0 + sig_eff * mb) - 1.0) * lam_n / q_safe
        s_row = y * (sig_eff * lam_n) / q_safe
        fac = jnp.concatenate([y * (live / coef_div), live], axis=0)
        pre = jnp.concatenate(
            [base, s_row, a0, jnp.where(qii != 0.0, one, 0.0), fac], axis=0
        )  # (6K, b): [base | S | a0 | qflag | Yl | Ll]

        def step(j, cd):            # cd rows: [coefs_0..K-1 | delta_0..K-1]
            mask = lane == j
            sv = jnp.sum(jnp.where(mask, pre, 0.0), axis=1, keepdims=True)
            gq = gq_ref[pl.ds(j, 1)].reshape(gw, b)
            dots = jnp.sum(cd[2 * k - gw:] * gq, axis=1, keepdims=True)
            a = sv[2 * k:3 * k] + dots[gw - k:]
            u = a - sv[:k]
            if not frozen:
                u = u - sv[k:2 * k] * dots[:k]
            new_a = jnp.where(sv[3 * k:4 * k] > 0.0,
                              jnp.clip(u, 0.0, 1.0), one)
            dm = new_a - a
            upd = sv[4 * k:] * jnp.concatenate([dm, dm], axis=0)
            return jnp.where(mask, upd, cd)

        cd = _chain_loop(b, unroll, step, zero)
        coef_ref[...] = cd[:k]
        delta_ref[...] = cd[k:]
        return

    def step(j, cd):                # cd rows: [coefs_0..K-1 | delta_0..K-1]
        mask = lane == j
        sv = jnp.sum(jnp.where(mask, scal, 0.0), axis=1, keepdims=True)
        m0, y, qii, a0, mb, live = (sv[i * k:(i + 1) * k] for i in range(6))
        gq = gq_ref[pl.ds(j, 1)].reshape(gw, b)
        dots = jnp.sum(cd[2 * k - gw:] * gq, axis=1, keepdims=True)
        if frozen:
            margin = m0
        else:
            margin = m0 + sig_eff * (mb + dots[:k])
        a = a0 + dots[gw - k:]
        new_a = losses.alpha_step(loss, a, y * margin, qii, lam_n,
                                  smoothing=smoothing)
        d_j = (new_a - a) * live
        c_j = y * d_j / coef_div
        return jnp.where(mask, jnp.concatenate([c_j, d_j], axis=0), cd)

    cd = _chain_loop(b, unroll, step, zero)
    coef_ref[...] = cd[:k]
    delta_ref[...] = cd[k:]


DEFAULT_UNROLL = 8    # swept on v5e through the real chunked driver
                      # (epsilon fused config, B=128): 8 → 3.4-3.8
                      # ms/round, 32 → 4.3; a synthetic harness preferred
                      # 32, the production index stream prefers 8.
                      # Re-swept round 5 on the distinct path: 4 → 3.47,
                      # 8 → 3.21, 16 → 3.18, 32 → 3.52 — 8 and 16 tie
                      # within tunnel noise; 8 stays


@functools.partial(
    jax.jit,
    static_argnames=("lam_n", "coef_div", "sig_eff", "frozen", "loss",
                     "smoothing", "interpret", "unroll"),
)
def chain_block_batched(
    scal: jax.Array,   # (K, 6, B): [m0 | y | qii | alpha0 | mb | mask]
    gq: jax.Array,     # (B, 2K, B) fused Gram+equality operand:
                       # gq[j, k, i] = x_i·x_j of shard k (transposed Gram,
                       # einsum("kjd,kid->jki")), gq[j, K+k, i] =
                       # (idx_i == idx_j); frozen mode passes (B, K, B)
                       # with only the equality rows
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    frozen: bool,
    loss: str,
    smoothing: float,
    interpret: bool = False,
    unroll: int = DEFAULT_UNROLL,
):
    """Run one block's B-step recurrence for K shards in lockstep.
    Returns ``(delta, coefs)``, both (K, B): per-step α deltas (for the
    caller's additive scatter — duplicate-safe by construction) and Δw
    coefficients (for the caller's ``coefs·X_B`` apply).  B must be a
    multiple of 128 (whole lane tiles; also of ``unroll``)."""
    k, nrows, b = scal.shape
    if nrows != SCAL_ROWS:
        raise ValueError(f"scal must carry {SCAL_ROWS} metric rows, "
                         f"got {nrows}")
    if b % LANES:
        raise ValueError(f"chain_block_batched needs B % {LANES} == 0, "
                         f"got {b}")
    if gq.shape != (b, (k if frozen else 2 * k), b):
        raise ValueError(f"gq shape {gq.shape} does not match frozen={frozen}")
    # (K, 6, B) -> (6K, B) grouped by metric so the kernel's static column
    # slices are [m0_0..m0_K-1 | y_0.. | ...]
    scal_rows = scal.transpose(1, 0, 2).reshape(SCAL_ROWS * k, b)
    kernel = functools.partial(
        _chain_kernel_batched, k=k, b=b, lam_n=lam_n, coef_div=coef_div,
        sig_eff=sig_eff, frozen=frozen,
        loss=losses.validate(loss, smoothing), smoothing=smoothing,
        unroll=(unroll if b % max(unroll, 1) == 0 else 1),
    )
    delta, coefs = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((k, b), scal.dtype),
            jax.ShapeDtypeStruct((k, b), scal.dtype),
        ],
        interpret=interpret,
    )(scal_rows, gq)
    return delta, coefs


# ---------------------------------------------------------------------------
# Fused per-block kernel: Gram + margins + equality + chain + Δw update in
# ONE pallas_call.
# ---------------------------------------------------------------------------
#
# Profiling the split design (XLA einsums around a chain-only kernel) on a
# v5e showed the chain itself is CHEAP (~0.46 ms/round at epsilon scale,
# ~90 ns per lockstep step) and the round time is dominated by XLA-side
# materialization the kernel boundary forces: the (B, 2K, B) Gram+equality
# concat (3 big HBM copies), the equality-tile broadcast-compare (168 MB
# written per round), the transposing Gram einsum epilogue, and streaming
# the fused operand back in.  Hoisting that work out of the scan made it
# WORSE (7.8 vs 4.7 ms): the tiles cost more to materialize than their
# serialization ever cost.  The fix is to stop materializing: this kernel
# consumes the (K, B, d) gathered row tile directly and keeps every
# intermediate — Gram, margins, equality, the chain carry — in VMEM.
#
# VMEM is 16 MiB (measured; a 15.9 MB scratch compiles, 16 MB does not),
# and a (K, B, d) f32 tile at epsilon scale (8, 128, 2000) is 8.2 MB —
# too big to double-buffer.  So the grid is (2,) over B-HALVES: each grid
# step streams a (K, B/2, d) half-tile (4.1 MB, auto-double-buffered by
# Mosaic's pipeline), the first half parks in scratch, and the Gram
# assembles from the four half products on the MXU.  The equality tile is
# ONE broadcast compare of the f32-cast indices (no scalar reads), margins
# are one batched matvec against the caller-combined v = w + σ·Δw, and the
# Δw update leaves as a (K, d) MXU product of the coefficients against the
# two halves.  The only per-round work left outside is the row gather, the
# α gather/scatter (XLA's scatter beats in-kernel dynamic picks at ~11 ns
# per scalar-addressed op), and the (K, d) Δw add.


FUSED_VMEM_BUDGET = 14 << 20   # hard cap 16 MiB; leave ~2 MiB for Mosaic


def fused_vmem_estimate(k: int, b: int, d: int, itemsize: int) -> int:
    """Working set of one fused_block instance: the double-buffered
    (K, B/2, d) half-tile operand + the parked first half, the (K, B, B)
    Gram and equality scratch, the (K, d) v operand and Δw-update output
    (double-buffered), and the small per-draw vectors."""
    half = k * (b // 2) * d
    return itemsize * (
        3 * half            # operand double-buffer + s0 scratch
        + 2 * k * b * b     # gram + eq scratch
        + 4 * k * d         # v in + dwu out, double-buffered
        + 16 * k * b        # idxf/yb/qb/a0/live + pre + carry + delta
    )


def fused_fits(k: int, b: int, d: int, itemsize: int,
               n_shard: int = 0) -> bool:
    return (
        b % LANES == 0
        and (b // 2) % 8 == 0
        and itemsize == 4
        # the in-kernel equality compare runs on f32-cast indices — only
        # exact below 2^24 (the legacy path compares integers)
        and n_shard < (1 << 24)
        and fused_vmem_estimate(k, b, d, itemsize) <= FUSED_VMEM_BUDGET
    )


def _fused_kernel(xb_ref, idxf_ref, idxft_ref, yb_ref, qb_ref, a0_ref,
                  live_ref, v_ref, delta_ref, dwu_ref, s0_ref, gram_ref,
                  eq_ref, mb_ref, *, k, b, d, lam_n, coef_div, sig_eff,
                  frozen, loss, smoothing, unroll):
    """Grid (2,) over B-halves.  Step 0 parks its half-tile and computes
    the half-products that need no second half; step 1 completes the Gram,
    runs the chain, and emits (delta, Δw update).

    Layout rules (Mosaic): the Gram/equality scratches are j-LEADING
    (B, K, B) so the chain's per-step row read is a leading-dim dynamic
    sublane slice (``ref[pl.ds(j, 1)]``) — dynamic slicing a middle dim
    lowers to an unsupported gather.  Gram pieces are therefore computed
    per shard (static k) as plain 2D MXU matmuls and stored with a static
    middle index; the margins use a VPU lane-reduce (the matvec is 128K
    MACs — not worth an MXU lowering's layout constraints); the equality
    tile is one broadcast compare of the two index layouts the caller
    provides (f32 row-major and its transpose), so nothing transposes
    in-kernel."""
    h = pl.program_id(0)
    b2 = b // 2
    dtype = xb_ref.dtype
    # Precision: the Gram/margin products at DEFAULT measure EXACT against
    # the sequential path (da error 0.0 at epsilon scale), but the
    # vector-matrix Δw-update products lowered with ~bf16 error (2.9e-3
    # relative — enough to stall the duality gap at ~3e-4, since the
    # certificate rests on w = (1/λn)·Σyαx staying tight).  HIGHEST on
    # everything OOMs the 16 MiB VMEM by ~1 MB of matmul temps at the
    # k=8/B=128/d=2000 flagship shape, so it is applied ONLY where the
    # error was measured: the dwu dots.
    prec = jax.lax.Precision.HIGHEST
    dot2 = lambda a_, b_: jax.lax.dot_general(  # noqa: E731
        a_, b_, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dtype)

    def margins_half(lo):
        # mb[kk, lo:lo+b2] = x_kk · v_kk  (VPU lane reduce per shard)
        for kk in range(k):
            x_kk = xb_ref[kk]                         # (B/2, d)
            v_kk = v_ref[kk:kk + 1, :]                # (1, d)
            mb_ref[kk:kk + 1, lo:lo + b2] = jnp.sum(
                x_kk * v_kk, axis=1)[None, :]

    @pl.when(h == 0)
    def _first_half():
        s0_ref[...] = xb_ref[...]
        # equality tile in one vectorized compare — no scalar reads, no
        # in-kernel transpose: eq[j, kk, i] = (idx_i == idx_j) in shard kk
        eq_ref[...] = (idxft_ref[...][:, :, None]
                       == idxf_ref[...][None, :, :]).astype(dtype)
        margins_half(0)
        if not frozen:
            for kk in range(k):
                g = dot2(xb_ref[kk], xb_ref[kk])      # (B/2, B/2)
                gram_ref[0:b2, kk, 0:b2] = g

    @pl.when(h == 1)
    def _second_half():
        margins_half(b2)
        if not frozen:
            for kk in range(k):
                s0_kk = s0_ref[kk]
                x1_kk = xb_ref[kk]
                gram_ref[0:b2, kk, b2:b] = dot2(s0_kk, x1_kk)
                gram_ref[b2:b, kk, 0:b2] = dot2(x1_kk, s0_kk)
                gram_ref[b2:b, kk, b2:b] = dot2(x1_kk, x1_kk)

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, b), 1)
        one = jnp.asarray(1.0, dtype)
        zero = jnp.zeros((2 * k, b), dtype)
        m0 = mb_ref[...]
        y = yb_ref[...]
        qii = qb_ref[...]
        a0 = a0_ref[...]
        live = live_ref[...]

        if loss == "hinge":
            # same algebraic collapse as _chain_kernel_batched: constants
            # hoist into a prologue, the chain is two dots + clip + write
            q_safe = jnp.where(qii != 0.0, qii, one)
            base = (y * m0 - 1.0) * lam_n / q_safe
            s_row = y * (sig_eff * lam_n) / q_safe
            fac = jnp.concatenate([y * (live / coef_div), live], axis=0)
            pre = jnp.concatenate(
                [base, s_row, a0, jnp.where(qii != 0.0, one, 0.0), fac],
                axis=0,
            )  # (6K, B)

            def step(j, cd):
                mask = lane == j
                sv = jnp.sum(jnp.where(mask, pre, 0.0), axis=1,
                             keepdims=True)
                eqr = eq_ref[pl.ds(j, 1)].reshape(k, b)
                ddot = jnp.sum(cd[k:] * eqr, axis=1, keepdims=True)
                a = sv[2 * k:3 * k] + ddot
                u = a - sv[:k]
                if not frozen:
                    gr = gram_ref[pl.ds(j, 1)].reshape(k, b)
                    u = u - sv[k:2 * k] * jnp.sum(cd[:k] * gr, axis=1,
                                                  keepdims=True)
                new_a = jnp.where(sv[3 * k:4 * k] > 0.0,
                                  jnp.clip(u, 0.0, 1.0), one)
                dm = new_a - a
                upd = sv[4 * k:] * jnp.concatenate([dm, dm], axis=0)
                return jnp.where(mask, upd, cd)

        else:
            scal = jnp.concatenate([m0, y, qii, a0, live], axis=0)

            def step(j, cd):
                mask = lane == j
                sv = jnp.sum(jnp.where(mask, scal, 0.0), axis=1,
                             keepdims=True)
                m0j, yj, qj, a0j, livej = (sv[i * k:(i + 1) * k]
                                           for i in range(5))
                eqr = eq_ref[pl.ds(j, 1)].reshape(k, b)
                a = a0j + jnp.sum(cd[k:] * eqr, axis=1, keepdims=True)
                margin = m0j
                if not frozen:
                    gr = gram_ref[pl.ds(j, 1)].reshape(k, b)
                    margin = margin + sig_eff * jnp.sum(
                        cd[:k] * gr, axis=1, keepdims=True)
                new_a = losses.alpha_step(loss, a, yj * margin, qj, lam_n,
                                          smoothing=smoothing)
                d_j = (new_a - a) * livej
                c_j = yj * d_j / coef_div
                return jnp.where(mask, jnp.concatenate([c_j, d_j], axis=0),
                                 cd)

        cd = _chain_loop(b, unroll, step, zero)
        delta_ref[...] = cd[k:]
        coefs = cd[:k]                                # (K, B)
        for kk in range(k):
            dwu_ref[kk:kk + 1, :] = (
                jax.lax.dot_general(
                    coefs[kk:kk + 1, :b2], s0_ref[kk],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=prec,
                )
                + jax.lax.dot_general(
                    coefs[kk:kk + 1, b2:], xb_ref[kk],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=prec,
                )
            ).astype(dtype)


@functools.partial(
    jax.jit,
    static_argnames=("lam_n", "coef_div", "sig_eff", "frozen", "loss",
                     "smoothing", "interpret", "unroll"),
)
def fused_block(
    xb: jax.Array,     # (K, B, d) gathered row tile
    idxf: jax.Array,   # (K, B) f32-cast sampled indices (exact < 2^24)
    yb: jax.Array,     # (K, B) labels
    qb: jax.Array,     # (K, B) qii = ||x||^2 * qii_factor
    a0: jax.Array,     # (K, B) alpha at block start
    live: jax.Array,   # (K, B) 1.0 for real steps, 0.0 for padding
    v: jax.Array,      # (K, d) margin vector: w + sig_eff * dw_blockstart
                       # (just w broadcast for frozen mode)
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    frozen: bool,
    loss: str,
    smoothing: float,
    interpret: bool = False,
    unroll: int = DEFAULT_UNROLL,
):
    """One fused block step: margins, Gram, equality, the B-step chain, and
    the Δw update in a single kernel.  Returns (delta (K, B), dwu (K, d)):
    per-step α deltas (additive-scatter-safe) and the block's Δw increment
    Σ_j c_j·x_j."""
    k, b, d = xb.shape
    if b % LANES or (b // 2) % 8:
        raise ValueError(f"fused_block needs B % {LANES} == 0, got {b}")
    kernel = functools.partial(
        _fused_kernel, k=k, b=b, d=d, lam_n=lam_n, coef_div=coef_div,
        sig_eff=sig_eff, frozen=frozen,
        loss=losses.validate(loss, smoothing), smoothing=smoothing,
        unroll=(unroll if b % max(unroll, 1) == 0 else 1),
    )
    from jax.experimental.pallas import tpu as pltpu

    b2 = b // 2
    full = lambda s: pl.BlockSpec(s, lambda h: (0,) * len(s))  # noqa: E731
    delta, dwu = pl.pallas_call(
        kernel,
        grid=(2,),
        in_specs=[
            pl.BlockSpec((k, b2, d), lambda h: (0, h, 0)),
            full((k, b)), full((b, k)), full((k, b)), full((k, b)),
            full((k, b)), full((k, b)), full((k, d)),
        ],
        out_specs=[full((k, b)), full((k, d))],
        out_shape=[
            jax.ShapeDtypeStruct((k, b), xb.dtype),
            jax.ShapeDtypeStruct((k, d), xb.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, b2, d), xb.dtype),   # parked first half
            pltpu.VMEM((b, k, b), xb.dtype),    # gram, j-leading
            pltpu.VMEM((b, k, b), xb.dtype),    # eq, j-leading
            pltpu.VMEM((k, b), xb.dtype),       # margins
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(xb, idxf, idxf.T, yb, qb, a0, live, v)
    return delta, dwu
