"""Pallas TPU kernel for the sequential SDCA inner loop (dense layout).

The H coordinate steps of local SDCA are inherently sequential
(CoCoA.scala:148-188); under plain XLA each step pays HBM round-trips for
the row gather and the Δw update.  This kernel keeps the hot state — the Δw
accumulator and the shard's α vector — resident in VMEM scratch across all
H steps and lets Pallas's grid pipeline prefetch each sampled row HBM→VMEM
(double-buffered) while the previous step computes.

Uses the margins decomposition (ops/local_sdca.py ``mode_factors``): the
per-step margin is ``margins0[idx] + sig_eff·(x·Δw)`` with margins0 = X·w₀
precomputed outside the kernel as one MXU matvec per round.  Per step the
kernel does one row·Δw dot, scalar box-projection logic, one row axpy, and
an α write.

**Folded rows.**  A (1, d) row uses one sublane — 1/8 of the VPU.  The
caller reinterprets each dense row as an (8, d/8) tile instead (a free
reshape: the row is contiguous in HBM), so the per-step O(d) work — the
Δw dot and the axpy — runs at full VPU width, and the sampled row is its
own tile-aligned DMA unit (no sublane-alignment tricks).  Requires
d % 8 == 0; ``shard_dataset`` pads dense feature columns to a multiple of 8
(zero columns touch nothing), and the wrapper pads on the fly otherwise.

**Step groups.**  Grid is (K, ceil(H/S)): shard-major, step groups inner
(TPU grids execute sequentially with the last dimension fastest, which is
exactly the dependency order).  Each grid iteration runs S sequential
coordinate steps (unrolled in the kernel body) against S independently-
prefetched row blocks, amortizing per-grid-step fixed costs — grid
bookkeeping, DMA issue, pipeline bubbles — over S steps.  Groups past H
(when S ∤ H) clamp their row index and zero their update — inert, any H
works.

**Lane-blocked scalar access.**  TPU vectors have no cheap dynamic lane
indexing; reading a per-step scalar (y, ‖x‖², margins0[idx], α[idx]) with a
full-width iota-mask reduce costs O(n_shard) VPU work per step, which at
epsilon scale (n_shard = 100K) would dwarf the O(d) coordinate update.
Instead, the per-shard vectors are laid out as (n_shard/128, 128) — lane
blocks — so a scalar read is a *dynamic sublane slice* (legal and cheap) of
one (1, 128) row followed by a 128-wide mask pick, and the α write masks
one (1, 128) row.  Per-step cost is O(d + 128) regardless of shard size.

Block/alignment rules used:

- the sampled row arrives as a (1, 1, 8, d/8) block of the folded
  (K, n_shard, 8, d/8) X, selected by ``idxs`` via scalar prefetch;
- the per-shard vectors arrive as ``(1, n_blocks, 128)`` blocks selected by
  the grid's k index (their second-to-last dim is the full axis, which is
  always legal); they stay VMEM-resident across that shard's H steps and
  re-DMA only when k advances;
- outputs (Δw, α) are per-shard blocks too: the kernel writes them at the
  shard's last step and Pallas flushes each block to HBM when the grid
  moves to the next shard — no cross-shard masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import coef_divisor, mode_factors

LANES = 128
SUBLANES = 8  # f32 sublane count: rows fold to (8, d/8)
VMEM_BUDGET = 12 << 20  # leave ~4 MB of the ~16 MB VMEM for the compiler
UNROLL_CANDIDATES = (16, 8, 4, 2, 1)


def check_dtype(dtype) -> None:
    """2-byte dtypes are rejected: bf16 SDCA can't certify a 1e-4 duality
    gap anyway, and the folded-row layout assumes 8-sublane (4-byte) tiling
    (use the fori_loop path, which handles bf16).  f32 is the TPU path; f64
    works in interpret mode (the x64 validation tests)."""
    if jnp.dtype(dtype).itemsize < 4:
        raise ValueError(
            f"the Pallas SDCA kernel does not support 2-byte dtypes, got "
            f"{jnp.dtype(dtype).name}; use math='fast' without pallas"
        )


def vmem_estimate(n_shard: int, d: int, itemsize: int, unroll: int) -> int:
    """Rough VMEM working set of the kernel: the 4 lane-blocked per-shard
    input vectors + α output (double-buffered across the k advance) + the α
    scratch (11 n_pad-vectors total), the Δw scratch/output plus temporaries
    (~4 d-vectors), and ``unroll`` double-buffered folded row blocks."""
    n_pad = -(-n_shard // LANES) * LANES
    return itemsize * (11 * n_pad + (2 * unroll + 4) * d)


def pick_unroll(n_shard: int, d: int, itemsize: int, h: int) -> int:
    """Largest step-group size whose row blocks still fit the VMEM budget
    (0 if even S=1 does not fit — caller should stay on the fori_loop
    path)."""
    for s in UNROLL_CANDIDATES:
        if s <= max(1, h) and vmem_estimate(n_shard, d, itemsize, s) <= VMEM_BUDGET:
            return s
    return 0


def fold_rows(X: jax.Array) -> jax.Array:
    """(K, n_shard, d) -> (K, n_shard, 8, d/8): the kernel's folded-row
    operand.  The fold is a physical relayout on TPU (the 3-D and 4-D tiled
    layouts differ), so hot paths call this ONCE per dispatch — outside
    ``lax.scan``/``lax.while_loop`` — and pass the folded array through the
    loop; folding inside the round body would relayout the whole X every
    round (measured: 2×0.3 ms/round at demo scale, the entire kernel's cost
    many times over)."""
    k, n_shard, d = X.shape
    if d % SUBLANES:
        X = jnp.pad(X, ((0, 0), (0, 0), (0, SUBLANES - d % SUBLANES)))
        d = X.shape[-1]
    return X.reshape(k, n_shard, SUBLANES, d // SUBLANES)


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    *refs,           # S row blocks, 4 shard vecs, 2 outs, 2 scratch (below)
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    loss: str,
    smoothing: float,
    unroll: int,
    n_groups: int,
):
    # refs layout:
    #   x_refs[j]      (1, 1, 8, d8) VMEM: folded row of sample j
    #   margins0_ref   (1, n_blocks, LANES) VMEM: shard k's lane-blocked X·w₀
    #   labels_ref     (1, n_blocks, LANES) VMEM
    #   sqn_ref        (1, n_blocks, LANES) VMEM
    #   alpha_in_ref   (1, n_blocks, LANES) VMEM
    #   dw_ref         out (1, 8, d8) VMEM: shard k's Δw (flushed on k advance)
    #   alpha_ref      out (1, n_blocks, LANES) VMEM (flushed on k advance)
    #   dw_acc         scratch (8, d8) VMEM: this shard's Δw accumulator
    #   alpha_sc       scratch (n_blocks, LANES) VMEM: the advancing α
    x_refs = refs[:unroll]
    (margins0_ref, labels_ref, sqn_ref, alpha_in_ref,
     dw_ref, alpha_ref, dw_acc, alpha_sc) = refs[unroll:]
    k_ = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init_shard():
        dw_acc[...] = jnp.zeros_like(dw_acc)
        alpha_sc[...] = alpha_in_ref[0]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)

    # S sequential coordinate steps per grid iteration, each against its own
    # prefetched row block; step j reads the dw_acc/alpha_sc written by j-1
    for j in range(unroll):
        step = i * unroll + j
        # groups past H clamp their index (the row spec's index map does the
        # same clamp, so the DMA'd block matches) and zero their update
        idx = idxs_ref[k_, jnp.minimum(step, h - 1)]
        live = step < h
        blk = idx // LANES
        sub_lane = idx - blk * LANES
        sel = lane == sub_lane

        def pick(ref, blk=blk, sel=sel):
            """Scalar ref[idx]: dynamic sublane slice + 128-wide mask reduce."""
            return jnp.sum(jnp.where(sel, ref[0, pl.ds(blk, 1), :], 0.0))

        y = pick(labels_ref)
        sq = pick(sqn_ref)
        m0 = pick(margins0_ref)
        a = jnp.sum(jnp.where(sel, alpha_sc[pl.ds(blk, 1), :], 0.0))

        x = x_refs[j][0, 0]  # (8, d8): the folded sampled row

        if frozen:
            margin = m0
        else:
            xdw = jnp.sum(x * dw_acc[...])
            margin = m0 + sig_eff * xdw
        # the dual coordinate update is pure scalar jnp — shared with the
        # fori_loop kernels via ops/losses.py (hinge = CoCoA.scala:166-178)
        new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor, lam_n,
                                  smoothing=smoothing)

        coef = jnp.where(live, y * (new_a - a) / coef_div, 0.0)
        dw_acc[...] = dw_acc[...] + coef * x
        alpha_sc[pl.ds(blk, 1), :] = jnp.where(
            sel & live, new_a, alpha_sc[pl.ds(blk, 1), :]
        )

    @pl.when(i == n_groups - 1)
    def _flush_shard():
        dw_ref[0] = dw_acc[...]
        alpha_ref[0] = alpha_sc[...]


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing", "unroll"),
)
def pallas_sdca_round(
    w_margins0: jax.Array,   # (K, n_shard) precomputed X·w₀ per shard
    alpha: jax.Array,        # (K, n_shard)
    X: jax.Array,            # (K, n_shard, d) dense rows
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
    unroll: int = 0,
):
    """One SDCA round for K shards on this chip.  Returns (dw, alpha_inner):
    dw (K, d) unreduced per-shard updates; alpha_inner (K, n_shard) the
    locally-advanced alpha (callers apply the outer scaling law).

    ``unroll`` = coordinate steps per grid iteration (0 = auto: the largest
    of 16/8/4/2/1 whose row blocks fit the VMEM budget).  Any value yields
    the same math — it only changes DMA batching.

    Inside ``shard_map`` this must run under ``check_vma=False`` (the
    chunked driver does; pallas_call's internal slices confuse the VMA
    checker)."""
    if X.ndim == 4:
        # pre-folded (K, n_shard, 8, d/8) — the hot paths fold once per run
        # OUTSIDE the round loop: folding in here would relayout the whole X
        # every round (the 3-D and 4-D tiled layouts differ physically)
        k, n_shard, _, d8 = X.shape
        d = d_orig = SUBLANES * d8
        X_folded = X
    else:
        k, n_shard, d = X.shape
        d_orig = d
        if d % SUBLANES:
            # hot configs avoid this copy: shard_dataset pads dense d to 8
            pad = SUBLANES - d % SUBLANES
            X = jnp.pad(X, ((0, 0), (0, 0), (0, pad)))
            d += pad
        d8 = d // SUBLANES
        X_folded = X.reshape(k, n_shard, SUBLANES, d8)
    h = idxs.shape[1]
    dtype = X.dtype
    check_dtype(dtype)
    if not unroll:
        unroll = pick_unroll(n_shard, d, jnp.dtype(dtype).itemsize, h) or 1
    n_groups = -(-h // unroll)
    sig_eff, qii_factor = mode_factors(mode, sigma)

    # lane-block the per-shard vectors: (K, n_shard) -> (K, n_blocks, 128).
    # Sampled indices never exceed the shard's true row count, so zero
    # padding is inert.
    n_pad = -(-n_shard // LANES) * LANES
    pad = [(0, 0), (0, n_pad - n_shard)]
    blocked = lambda v: jnp.pad(v, pad).reshape(k, n_pad // LANES, LANES)  # noqa: E731
    n_blocks = n_pad // LANES

    kernel = functools.partial(
        _kernel,
        lam_n=float(lam * n),
        coef_div=float(coef_divisor(mode, lam * n)),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
        h=h,
        loss=losses.validate(loss, smoothing),
        smoothing=float(smoothing),
        unroll=unroll,
        n_groups=n_groups,
    )

    def row_spec(j):
        # sample j of group i: the folded row at [k, idx, :, :]; groups past
        # H clamp to the last sample (matching the kernel)
        def index_map(k_, i_, idxs_):
            step = jnp.minimum(i_ * unroll + j, h - 1)
            return (k_, idxs_[k_, step], 0, 0)

        return pl.BlockSpec((1, 1, SUBLANES, d8), index_map)

    shard_vec = pl.BlockSpec(
        (1, n_blocks, LANES), lambda k_, i_, idxs_: (k_, 0, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k, n_groups),
        in_specs=[
            *[row_spec(j) for j in range(unroll)],
            shard_vec,  # margins0
            shard_vec,  # labels
            shard_vec,  # sq_norms
            shard_vec,  # alpha_in
        ],
        out_specs=[
            pl.BlockSpec((1, SUBLANES, d8), lambda k_, i_, idxs_: (k_, 0, 0)),
            shard_vec,
        ],
        scratch_shapes=[
            pltpu.VMEM((SUBLANES, d8), dtype),
            pltpu.VMEM((n_blocks, LANES), dtype),
        ],
    )

    dw, alpha_blocked = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((k, SUBLANES, d8), dtype),
            jax.ShapeDtypeStruct((k, n_blocks, LANES), dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(idxs, *([X_folded] * unroll), blocked(w_margins0), blocked(labels),
      blocked(sq_norms), blocked(alpha))
    alpha_inner = alpha_blocked.reshape(k, n_pad)[:, :n_shard]
    return dw.reshape(k, d)[:, :d_orig], alpha_inner
