"""Pallas TPU kernel for the sequential SDCA inner loop (dense layout).

The H coordinate steps of local SDCA are inherently sequential
(CoCoA.scala:148-188); under plain XLA each step pays HBM round-trips for
the row gather and the Δw update.  This kernel keeps the hot state — the Δw
accumulator and the shard's α/labels/‖x‖²/margins vectors — resident in VMEM
across all H steps and lets Pallas's grid pipeline prefetch each sampled row
HBM→VMEM (double-buffered) while the previous step computes.

Uses the margins decomposition (ops/local_sdca.py ``mode_factors``): the
per-step margin is ``margins0[idx] + sig_eff·(x·Δw)`` with margins0 = X·w₀
precomputed outside the kernel as one MXU matvec per round.  Per grid step
the kernel does one (1, d) VPU dot, scalar box-projection logic, one (1, d)
axpy, and a masked α write.

Grid is (K, H): shard-major, steps inner.  Output blocks (Δw row, α row)
map to the shard index only, so Pallas keeps them in VMEM across the H
inner steps and flushes to HBM once per shard — the classic revisited-block
reduction pattern.

Sampled indices arrive via ``PrefetchScalarGridSpec`` so the row BlockSpec's
index_map can address X[k, idxs[k, i]] ahead of the compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops.local_sdca import mode_factors


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    x_ref,           # (1, 1, d) VMEM: the sampled row (auto-DMA'd per step)
    margins0_ref,    # (1, n) VMEM
    labels_ref,      # (1, n) VMEM
    sqn_ref,         # (1, n) VMEM
    alpha_in_ref,    # (1, n) VMEM
    dw_ref,          # out (1, d) VMEM, revisited across the H inner steps
    alpha_ref,       # out (1, n) VMEM, revisited
    *,
    lam_n: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
):
    i = pl.program_id(1)
    idx = idxs_ref[pl.program_id(0), i]

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        alpha_ref[...] = alpha_in_ref[...]

    n = alpha_ref.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    sel = lane == idx

    def pick(ref):
        return jnp.sum(jnp.where(sel, ref[...], 0.0))

    y = pick(labels_ref)
    a = pick(alpha_ref)
    sq = pick(sqn_ref)
    m0 = pick(margins0_ref)

    x = x_ref[0]                      # (1, d)
    if frozen:
        margin = m0
    else:
        xdw = jnp.sum(x * dw_ref[...])
        margin = m0 + sig_eff * xdw
    grad = (y * margin - 1.0) * lam_n

    # box projection (CoCoA.scala:166-178)
    proj_grad = jnp.where(
        a <= 0.0,
        jnp.minimum(grad, 0.0),
        jnp.where(a >= 1.0, jnp.maximum(grad, 0.0), grad),
    )
    qii = sq * qii_factor
    safe_qii = jnp.where(qii != 0.0, qii, 1.0)
    new_a = jnp.where(qii != 0.0, jnp.clip(a - grad / safe_qii, 0.0, 1.0), 1.0)
    new_a = jnp.where(proj_grad != 0.0, new_a, a)

    coef = y * (new_a - a) / lam_n
    dw_ref[...] = dw_ref[...] + coef * x
    alpha_ref[...] = jnp.where(sel, new_a, alpha_ref[...])


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret"),
)
def pallas_sdca_round(
    w_margins0: jax.Array,   # (K, n_shard) precomputed X·w₀ per shard
    alpha: jax.Array,        # (K, n_shard)
    X: jax.Array,            # (K, n_shard, d) dense rows
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
):
    """One SDCA round for K shards on this chip.  Returns (dw, alpha_inner):
    dw (K, d) unreduced per-shard updates; alpha_inner (K, n_shard) the
    locally-advanced alpha (callers apply the outer scaling law).

    Inside ``shard_map`` this must run under ``check_vma=False`` (the
    chunked driver does; pallas_call's internal slices confuse the VMA
    checker)."""
    k, n_shard, d = X.shape
    h = idxs.shape[1]
    sig_eff, qii_factor = mode_factors(mode, sigma)
    dtype = X.dtype

    kernel = functools.partial(
        _kernel,
        lam_n=float(lam * n),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k, h),
        in_specs=[
            # the sampled row: block (1,1,d) at [k, idxs[k,i], :]
            pl.BlockSpec((1, 1, d), lambda k_, i_, idxs_: (k_, idxs_[k_, i_], 0)),
            pl.BlockSpec((1, n_shard), lambda k_, i_, idxs_: (k_, 0)),
            pl.BlockSpec((1, n_shard), lambda k_, i_, idxs_: (k_, 0)),
            pl.BlockSpec((1, n_shard), lambda k_, i_, idxs_: (k_, 0)),
            pl.BlockSpec((1, n_shard), lambda k_, i_, idxs_: (k_, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, d), lambda k_, i_, idxs_: (k_, 0)),
            pl.BlockSpec((1, n_shard), lambda k_, i_, idxs_: (k_, 0)),
        ],
    )

    dw, alpha_inner = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((k, d), dtype),
            jax.ShapeDtypeStruct((k, n_shard), dtype),
        ],
        interpret=interpret,
    )(idxs, X, w_margins0, labels, sq_norms, alpha)
    return dw, alpha_inner
