"""Pallas TPU kernel for the sequential SDCA inner loop (dense layout).

The H coordinate steps of local SDCA are inherently sequential
(CoCoA.scala:148-188); under plain XLA each step pays HBM round-trips for
the row gather and the Δw update.  This kernel keeps the hot state — the Δw
accumulator and the shard's α vector — resident in VMEM scratch across all
H steps and lets Pallas's grid pipeline prefetch each sampled row HBM→VMEM
(double-buffered) while the previous step computes.

Uses the margins decomposition (ops/local_sdca.py ``mode_factors``): the
per-step margin is ``margins0[idx] + sig_eff·(x·Δw)`` with margins0 = X·w₀
precomputed outside the kernel as one MXU matvec per round.  Per grid step
the kernel does one (1, d) VPU dot, scalar box-projection logic, one (1, d)
axpy, and a masked α write.

Grid is (K, H): shard-major, steps inner (TPU grids execute sequentially
with the last dimension fastest, which is exactly the dependency order).

Mosaic alignment: block shapes must have a second-to-last dim that is a
multiple of the sublane count (8 for f32) or the full axis.  So:

- the sampled row is DMA'd as an 8-row-aligned ``(1, 8, d)`` block at row
  ``(idx//8)*8`` (index map returns block index ``idx//8``) and the kernel
  selects row ``idx % 8`` with an iota mask — shards are padded to a
  multiple of 16 rows by ``shard_dataset`` so aligned blocks never overrun;
- the per-shard vectors (margins0/labels/‖x‖²/α) and both outputs use
  full-array blocks (full axes are always legal) with constant index maps,
  so they load into VMEM once and outputs flush to HBM once at the end;
- the mutable per-shard state lives in ``(1, n)`` / ``(1, d)`` VMEM scratch,
  initialised at each shard's first step and written back to the output
  blocks (row-masked) at its last step.

Sampled indices arrive via ``PrefetchScalarGridSpec`` so the row BlockSpec's
index_map can address X[k, idxs[k, i]//8 ...] ahead of the compute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import mode_factors


def row_block_for(dtype) -> int:
    """Sublane count for the aligned row block.  2-byte dtypes are rejected:
    bf16 SDCA can't certify a 1e-4 duality gap anyway, and the kernel's
    dynamic sublane slices fail Mosaic lowering under 16-sublane tiling (use
    the fori_loop path, which handles bf16).  f32 is the TPU path; f64 works
    in interpret mode (the x64 validation tests)."""
    if jnp.dtype(dtype).itemsize < 4:
        raise ValueError(
            f"the Pallas SDCA kernel does not support 2-byte dtypes, got "
            f"{jnp.dtype(dtype).name}; use math='fast' without pallas"
        )
    return 8


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    x_ref,           # (1, row_block, d) VMEM: aligned block holding the sample
    margins0_ref,    # (K, n) VMEM (full array)
    labels_ref,      # (K, n) VMEM
    sqn_ref,         # (K, n) VMEM
    alpha_in_ref,    # (K, n) VMEM
    dw_ref,          # out (K, d) VMEM (full array, flushed once)
    alpha_ref,       # out (K, n) VMEM (full array, flushed once)
    dw_acc,          # scratch (1, d) VMEM: this shard's Δw accumulator
    alpha_sc,        # scratch (1, n) VMEM: this shard's advancing α
    vec_sc,          # scratch (3, n) VMEM: this shard's labels/‖x‖²/margins0
    *,
    lam_n: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    row_block: int,
    loss: str,
    smoothing: float,
):
    k_ = pl.program_id(0)
    i = pl.program_id(1)
    idx = idxs_ref[k_, i]

    n = alpha_sc.shape[1]
    k_total = alpha_ref.shape[0]
    krow = jax.lax.broadcasted_iota(jnp.int32, (k_total, 1), 0) == k_

    @pl.when(jnp.logical_and(k_ == 0, i == 0))
    def _init_outputs():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        alpha_ref[...] = alpha_in_ref[...]

    @pl.when(i == 0)
    def _init_shard():
        dw_acc[...] = jnp.zeros_like(dw_acc)
        # copy this shard's rows into scratch (dynamic sublane slice) so the
        # per-step scalar picks reduce over n elements, not K·n
        alpha_sc[...] = alpha_in_ref[pl.ds(k_, 1), :]
        vec_sc[0:1, :] = labels_ref[pl.ds(k_, 1), :]
        vec_sc[1:2, :] = sqn_ref[pl.ds(k_, 1), :]
        vec_sc[2:3, :] = margins0_ref[pl.ds(k_, 1), :]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    sel = lane == idx

    def pick(row):
        """Scalar vec[idx] via a lane-idx mask reduce (dynamic lane index)."""
        return jnp.sum(jnp.where(sel, row, 0.0))

    y = pick(vec_sc[0:1, :])
    sq = pick(vec_sc[1:2, :])
    m0 = pick(vec_sc[2:3, :])
    a = pick(alpha_sc[...])

    # select row idx % row_block of the aligned block (dynamic sublane slice)
    sub = idx - (idx // row_block) * row_block
    x = x_ref[0, pl.ds(sub, 1), :]

    if frozen:
        margin = m0
    else:
        xdw = jnp.sum(x * dw_acc[...])
        margin = m0 + sig_eff * xdw
    # the dual coordinate update is pure scalar jnp — shared with the
    # fori_loop kernels via ops/losses.py (hinge = CoCoA.scala:166-178)
    new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor, lam_n,
                              smoothing=smoothing)

    coef = y * (new_a - a) / lam_n
    dw_acc[...] = dw_acc[...] + coef * x
    alpha_sc[...] = jnp.where(sel, new_a, alpha_sc[...])

    @pl.when(i == h - 1)
    def _flush_shard():
        dw_ref[...] = jnp.where(krow, dw_acc[...], dw_ref[...])
        alpha_ref[...] = jnp.where(krow, alpha_sc[...], alpha_ref[...])


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing"),
)
def pallas_sdca_round(
    w_margins0: jax.Array,   # (K, n_shard) precomputed X·w₀ per shard
    alpha: jax.Array,        # (K, n_shard)
    X: jax.Array,            # (K, n_shard, d) dense rows
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
):
    """One SDCA round for K shards on this chip.  Returns (dw, alpha_inner):
    dw (K, d) unreduced per-shard updates; alpha_inner (K, n_shard) the
    locally-advanced alpha (callers apply the outer scaling law).

    Requires n_shard % 8 == 0 (shard_dataset pads to 16).  Inside
    ``shard_map`` this must run under ``check_vma=False`` (the chunked
    driver does; pallas_call's internal slices confuse the VMA checker)."""
    k, n_shard, d = X.shape
    h = idxs.shape[1]
    dtype = X.dtype
    row_block = row_block_for(dtype)
    if n_shard % row_block != 0:
        raise ValueError(
            f"n_shard must be a multiple of {row_block} for the aligned row "
            f"blocks ({dtype}), got {n_shard} (shard_dataset pads to 16)"
        )
    sig_eff, qii_factor = mode_factors(mode, sigma)

    kernel = functools.partial(
        _kernel,
        lam_n=float(lam * n),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
        h=h,
        row_block=row_block,
        loss=losses.validate(loss, smoothing),
        smoothing=float(smoothing),
    )

    full = lambda k_, i_, idxs_: (0, 0)  # noqa: E731 — full-array block
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k, h),
        in_specs=[
            # the sampled row: sublane-aligned block at [k, idx//rb*rb, :]
            pl.BlockSpec(
                (1, row_block, d),
                lambda k_, i_, idxs_: (k_, idxs_[k_, i_] // row_block, 0),
            ),
            pl.BlockSpec((k, n_shard), full),
            pl.BlockSpec((k, n_shard), full),
            pl.BlockSpec((k, n_shard), full),
            pl.BlockSpec((k, n_shard), full),
        ],
        out_specs=[
            pl.BlockSpec((k, d), full),
            pl.BlockSpec((k, n_shard), full),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, d), dtype),
            pltpu.VMEM((1, n_shard), dtype),
            pltpu.VMEM((3, n_shard), dtype),
        ],
    )

    dw, alpha_inner = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((k, d), dtype),
            jax.ShapeDtypeStruct((k, n_shard), dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(idxs, X, w_margins0, labels, sq_norms, alpha)
    return dw, alpha_inner
