"""Pallas TPU kernel for the sequential SDCA inner loop (dense layout).

The H coordinate steps of local SDCA are inherently sequential
(CoCoA.scala:148-188); under plain XLA each step pays HBM round-trips for
the row gather and the Δw update.  This kernel keeps the hot state — the Δw
accumulator and the shard's α vector — resident in VMEM scratch across all
H steps and lets Pallas's grid pipeline prefetch each sampled row HBM→VMEM
(double-buffered) while the previous step computes.

Uses the margins decomposition (ops/local_sdca.py ``mode_factors``): the
per-step margin is ``margins0[idx] + sig_eff·(x·Δw)`` with margins0 = X·w₀
precomputed outside the kernel as one MXU matvec per round.  Per grid step
the kernel does one (1, d) VPU dot, scalar box-projection logic, one (1, d)
axpy, and an α write.

Grid is (K, H): shard-major, steps inner (TPU grids execute sequentially
with the last dimension fastest, which is exactly the dependency order).

**Lane-blocked scalar access.** TPU vectors have no cheap dynamic lane
indexing; the v1 kernel read every per-step scalar (y, ‖x‖², margins0[idx],
α[idx]) with a full-width iota-mask reduce — O(n_shard) VPU work per step,
which at epsilon scale (n_shard = 100K) made each pick cost more than the
O(d) coordinate update itself.  Instead, the per-shard vectors are laid out
as (n_shard/128, 128) — lane blocks — so a scalar read is a *dynamic
sublane slice* (legal and cheap) of one (1, 128) row followed by a 128-wide
mask pick, and the α write masks one (1, 128) row.  Per-step cost is
O(d + 128) regardless of shard size.  The caller pads n_shard to a multiple
of 128 and reshapes; padded entries are never indexed.

Mosaic alignment rules used:

- the sampled row is DMA'd as an 8-row-aligned ``(1, 8, d)`` block at row
  ``(idx//8)*8`` (index map returns block index ``idx//8``) and the kernel
  selects row ``idx % 8`` with a dynamic sublane slice — shards are padded
  to a multiple of 16 rows by ``shard_dataset`` so aligned blocks never
  overrun;
- the per-shard vectors arrive as ``(1, n_blocks, 128)`` blocks selected by
  the grid's k index (their second-to-last dim is the full axis, which is
  always legal); they stay VMEM-resident across that shard's H steps and
  re-DMA only when k advances;
- outputs (Δw, α) are per-shard blocks too: the kernel writes them at the
  shard's last step and Pallas flushes each block to HBM when the grid
  moves to the next shard — no cross-shard masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import mode_factors

LANES = 128


def row_block_for(dtype) -> int:
    """Sublane count for the aligned row block.  2-byte dtypes are rejected:
    bf16 SDCA can't certify a 1e-4 duality gap anyway, and the kernel's
    dynamic sublane slices fail Mosaic lowering under 16-sublane tiling (use
    the fori_loop path, which handles bf16).  f32 is the TPU path; f64 works
    in interpret mode (the x64 validation tests)."""
    if jnp.dtype(dtype).itemsize < 4:
        raise ValueError(
            f"the Pallas SDCA kernel does not support 2-byte dtypes, got "
            f"{jnp.dtype(dtype).name}; use math='fast' without pallas"
        )
    return 8


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    x_ref,           # (1, row_block, d) VMEM: aligned block holding the sample
    margins0_ref,    # (1, n_blocks, LANES) VMEM: shard k's lane-blocked X·w₀
    labels_ref,      # (1, n_blocks, LANES) VMEM
    sqn_ref,         # (1, n_blocks, LANES) VMEM
    alpha_in_ref,    # (1, n_blocks, LANES) VMEM
    dw_ref,          # out (1, 1, d) VMEM: shard k's Δw (flushed on k advance)
    alpha_ref,       # out (1, n_blocks, LANES) VMEM (flushed on k advance)
    dw_acc,          # scratch (1, d) VMEM: this shard's Δw accumulator
    alpha_sc,        # scratch (n_blocks, LANES) VMEM: the advancing α
    *,
    lam_n: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    row_block: int,
    loss: str,
    smoothing: float,
):
    k_ = pl.program_id(0)
    i = pl.program_id(1)
    idx = idxs_ref[k_, i]
    blk = idx // LANES
    sub_lane = idx - blk * LANES

    @pl.when(i == 0)
    def _init_shard():
        dw_acc[...] = jnp.zeros_like(dw_acc)
        alpha_sc[...] = alpha_in_ref[0]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    sel = lane == sub_lane

    def pick(ref):
        """Scalar ref[idx]: dynamic sublane slice + 128-wide mask reduce."""
        return jnp.sum(jnp.where(sel, ref[0, pl.ds(blk, 1), :], 0.0))

    y = pick(labels_ref)
    sq = pick(sqn_ref)
    m0 = pick(margins0_ref)
    a = jnp.sum(jnp.where(sel, alpha_sc[pl.ds(blk, 1), :], 0.0))

    # select row idx % row_block of the aligned block (dynamic sublane slice)
    sub = idx - (idx // row_block) * row_block
    x = x_ref[0, pl.ds(sub, 1), :]

    if frozen:
        margin = m0
    else:
        xdw = jnp.sum(x * dw_acc[...])
        margin = m0 + sig_eff * xdw
    # the dual coordinate update is pure scalar jnp — shared with the
    # fori_loop kernels via ops/losses.py (hinge = CoCoA.scala:166-178)
    new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor, lam_n,
                              smoothing=smoothing)

    coef = y * (new_a - a) / lam_n
    dw_acc[...] = dw_acc[...] + coef * x
    alpha_sc[pl.ds(blk, 1), :] = jnp.where(
        sel, new_a, alpha_sc[pl.ds(blk, 1), :]
    )

    @pl.when(i == h - 1)
    def _flush_shard():
        dw_ref[0] = dw_acc[...]
        alpha_ref[0] = alpha_sc[...]


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing"),
)
def pallas_sdca_round(
    w_margins0: jax.Array,   # (K, n_shard) precomputed X·w₀ per shard
    alpha: jax.Array,        # (K, n_shard)
    X: jax.Array,            # (K, n_shard, d) dense rows
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
):
    """One SDCA round for K shards on this chip.  Returns (dw, alpha_inner):
    dw (K, d) unreduced per-shard updates; alpha_inner (K, n_shard) the
    locally-advanced alpha (callers apply the outer scaling law).

    Requires n_shard % 8 == 0 (shard_dataset pads to 16).  Inside
    ``shard_map`` this must run under ``check_vma=False`` (the chunked
    driver does; pallas_call's internal slices confuse the VMA checker)."""
    k, n_shard, d = X.shape
    h = idxs.shape[1]
    dtype = X.dtype
    row_block = row_block_for(dtype)
    if n_shard % row_block != 0:
        raise ValueError(
            f"n_shard must be a multiple of {row_block} for the aligned row "
            f"blocks ({dtype}), got {n_shard} (shard_dataset pads to 16)"
        )
    sig_eff, qii_factor = mode_factors(mode, sigma)

    # lane-block the per-shard vectors: (K, n_shard) -> (K, n_blocks, 128).
    # Sampled indices never exceed the shard's true row count, so zero
    # padding is inert.
    n_pad = -(-n_shard // LANES) * LANES
    pad = [(0, 0), (0, n_pad - n_shard)]
    blocked = lambda v: jnp.pad(v, pad).reshape(k, n_pad // LANES, LANES)  # noqa: E731
    n_blocks = n_pad // LANES

    kernel = functools.partial(
        _kernel,
        lam_n=float(lam * n),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
        h=h,
        row_block=row_block,
        loss=losses.validate(loss, smoothing),
        smoothing=float(smoothing),
    )

    shard_vec = pl.BlockSpec(
        (1, n_blocks, LANES), lambda k_, i_, idxs_: (k_, 0, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k, h),
        in_specs=[
            # the sampled row: sublane-aligned block at [k, idx//rb*rb, :]
            pl.BlockSpec(
                (1, row_block, d),
                lambda k_, i_, idxs_: (k_, idxs_[k_, i_] // row_block, 0),
            ),
            shard_vec,  # margins0
            shard_vec,  # labels
            shard_vec,  # sq_norms
            shard_vec,  # alpha_in
        ],
        out_specs=[
            # (1, 1, d): a (1, d) block is illegal (second-to-last dim must
            # divide 8 or span the axis), a singleton middle axis spans
            pl.BlockSpec((1, 1, d), lambda k_, i_, idxs_: (k_, 0, 0)),
            shard_vec,
        ],
        scratch_shapes=[
            pltpu.VMEM((1, d), dtype),
            pltpu.VMEM((n_blocks, LANES), dtype),
        ],
    )

    dw, alpha_blocked = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((k, 1, d), dtype),
            jax.ShapeDtypeStruct((k, n_blocks, LANES), dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(idxs, X, blocked(w_margins0), blocked(labels), blocked(sq_norms),
      blocked(alpha))
    alpha_inner = alpha_blocked.reshape(k, n_pad)[:, :n_shard]
    return dw.reshape(k, d), alpha_inner
