"""Pallas TPU kernel for the sequential SDCA inner loop (dense layout).

The H coordinate steps of local SDCA are inherently sequential
(CoCoA.scala:148-188); under plain XLA each step pays HBM round-trips for
the row gather and the Δw update.  This kernel keeps the hot state — the Δw
accumulator and the shard's α vector — resident in VMEM scratch across all
H steps and lets Pallas's grid pipeline prefetch each sampled row HBM→VMEM
(double-buffered) while the previous step computes.

Uses the margins decomposition (ops/local_sdca.py ``mode_factors``): the
per-step margin is ``x·w₀ + sig_eff·(x·Δw)``, with **both dots computed
in-kernel** against the VMEM-resident w₀ and Δw.  Round 3 precomputed
margins0 = X·w₀ as one MXU matvec per round instead; round 4 retired it:
the sampled row is already in VMEM for the axpy, so the w₀ dot is one more
VPU reduce on data the step touches anyway (measured: free — scalar
address generation bounds the step), while the matvec reads ALL of X every
round — at localIterFrac = 0.1 that is 10× the rows the round touches
(~90% of the demo round's HBM traffic, ~4 ms/round at epsilon scale).
The sparse kernel (ops/pallas_sparse.py) has computed margins in-kernel
since round 2 for the same reason.  Per step the kernel does the two row
dots, scalar box-projection logic, one row axpy, and an α write.

**Folded rows.**  A (1, d) row uses one sublane — 1/8 of the VPU.  The
caller reinterprets each dense row as an (8, d/8) tile instead (a free
reshape: the row is contiguous in HBM), so the per-step O(d) work — the
Δw dot and the axpy — runs at full VPU width, and the sampled row is its
own tile-aligned DMA unit (no sublane-alignment tricks).  Requires
d % 8 == 0; ``shard_dataset`` pads dense feature columns to a multiple of 8
(zero columns touch nothing), and the wrapper pads on the fly otherwise.

**Step groups.**  Grid is (K, ceil(H/S)): shard-major, step groups inner
(TPU grids execute sequentially with the last dimension fastest, which is
exactly the dependency order).  Each grid iteration runs S sequential
coordinate steps (unrolled in the kernel body) against S independently-
prefetched row blocks, amortizing per-grid-step fixed costs — grid
bookkeeping, DMA issue, pipeline bubbles — over S steps.  Groups past H
(when S ∤ H) clamp their row index and zero their update — inert, any H
works.

**Lane-blocked scalar access.**  TPU vectors have no cheap dynamic lane
indexing; reading a per-step scalar (y, ‖x‖², margins0[idx], α[idx]) with a
full-width iota-mask reduce costs O(n_shard) VPU work per step, which at
epsilon scale (n_shard = 100K) would dwarf the O(d) coordinate update.
Instead, the per-shard vectors are laid out as (n_shard/128, 128) — lane
blocks — so a scalar read is a *dynamic sublane slice* (legal and cheap) of
one (1, 128) row followed by a 128-wide mask pick, and the α write masks
one (1, 128) row.  Per-step cost is O(d + 128) regardless of shard size.

Block/alignment rules used:

- the sampled row arrives as a (1, 1, 8, d/8) block of the folded
  (K, n_shard, 8, d/8) X, selected by ``idxs`` via scalar prefetch;
- the per-shard vectors arrive as ``(1, n_blocks, 128)`` blocks selected by
  the grid's k index (their second-to-last dim is the full axis, which is
  always legal); they stay VMEM-resident across that shard's H steps and
  re-DMA only when k advances;
- outputs (Δw, α) are per-shard blocks too: the kernel writes them at the
  shard's last step and Pallas flushes each block to HBM when the grid
  moves to the next shard — no cross-shard masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import coef_divisor, mode_factors

LANES = 128
SUBLANES = 8  # f32 sublane count: rows fold to (8, d/8)
VMEM_BUDGET = 12 << 20  # leave ~4 MB of the ~16 MB VMEM for the compiler
UNROLL_CANDIDATES = (16, 8, 4, 2, 1)

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# interpret-mode (CPU CI) tests run on older jax too
COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def check_dtype(dtype) -> None:
    """2-byte dtypes are rejected: bf16 SDCA can't certify a 1e-4 duality
    gap anyway, and the folded-row layout assumes 8-sublane (4-byte) tiling
    (use the fori_loop path, which handles bf16).  f32 is the TPU path; f64
    works in interpret mode (the x64 validation tests)."""
    if jnp.dtype(dtype).itemsize < 4:
        raise ValueError(
            f"the Pallas SDCA kernel does not support 2-byte dtypes, got "
            f"{jnp.dtype(dtype).name}; use math='fast' without pallas"
        )


def vmem_estimate(n_shard: int, d: int, itemsize: int, unroll: int) -> int:
    """Rough VMEM working set of the kernel: the lane-concatenated stacked
    state (3·n_pad input, double-buffered across the k advance, + 3·n_pad
    scratch) + the α output (double-buffered) — 11 n_pad-vectors total —
    the w₀ operand, the Δw scratch/output plus temporaries (~5 d-vectors),
    and ``unroll`` double-buffered folded row blocks."""
    n_pad = -(-n_shard // LANES) * LANES
    return itemsize * (11 * n_pad + (2 * unroll + 5) * d)


def pick_unroll(n_shard: int, d: int, itemsize: int, h: int) -> int:
    """Largest step-group size whose row blocks still fit the VMEM budget
    (0 if even S=1 does not fit — caller should stay on the fori_loop
    path)."""
    for s in UNROLL_CANDIDATES:
        if s <= max(1, h) and vmem_estimate(n_shard, d, itemsize, s) <= VMEM_BUDGET:
            return s
    return 0


INTERLEAVE_BUDGET = 14 << 20  # measured headroom: flush-only outputs and the
                              # constant-block stacked input are not all
                              # double-buffered, so this can run closer to
                              # the 16 MB physical VMEM than VMEM_BUDGET


def interleave_vmem_estimate(k: int, n_shard: int, d: int, itemsize: int,
                             unroll: int) -> int:
    """Working set of the shard-interleaved kernel: ALL K shards' stacked
    state resident at once (3·n_pad input + 3·n_pad scratch each), the w₀
    operand, the Δw accumulators/outputs, and K·unroll double-buffered row
    blocks."""
    n_pad = -(-n_shard // LANES) * LANES
    return itemsize * (6 * k * n_pad + 3 * k * d + d + 2 * k * unroll * d)


def pick_interleave(k: int, n_shard: int, d: int, itemsize: int, h: int) -> int:
    """Step-group size for the interleaved kernel (0 = does not fit or
    nothing to interleave; use the shard-major kernel)."""
    if k <= 1:
        return 0
    for s in (2, 1):
        if s <= max(1, h) and interleave_vmem_estimate(
                k, n_shard, d, itemsize, s) <= INTERLEAVE_BUDGET:
            return s
    return 0


def fold_rows(X: jax.Array) -> jax.Array:
    """(K, n_shard, d) -> (K, n_shard, 8, d/8): the kernel's folded-row
    operand.  The fold is a physical relayout on TPU (the 3-D and 4-D tiled
    layouts differ), so hot paths call this ONCE per dispatch — outside
    ``lax.scan``/``lax.while_loop`` — and pass the folded array through the
    loop; folding inside the round body would relayout the whole X every
    round (measured: 2×0.3 ms/round at demo scale, the entire kernel's cost
    many times over)."""
    k, n_shard, d = X.shape
    if d % SUBLANES:
        X = jnp.pad(X, ((0, 0), (0, 0), (0, SUBLANES - d % SUBLANES)))
        d = X.shape[-1]
    return X.reshape(k, n_shard, SUBLANES, d // SUBLANES)


STACK = 3  # lane-concatenated per-shard rows: [labels, sqn, alpha]


def _step_body(srow, sub_lane, live, x, dw_k, w_k, *, frozen, sig_eff,
               qii_factor, lam_n, coef_div, loss, smoothing):
    """One coordinate step given the (1, 3·LANES) lane-concatenated state
    row (labels in lanes [0,128), ‖x‖² [128,256), α [256,384)).  Returns
    (new row, Δw contribution).

    The concatenated layout is the kernel's key scalar-unit optimization:
    all three per-step values arrive from ONE dynamic slice, and the α
    write goes back through the same row — 2 dynamically-addressed VMEM
    accesses per step instead of 5.  Address generation on the scalar core
    is the per-step bottleneck, not the O(d) vector work (measured: the
    frozen mode, which skips the Δw dot entirely, costs the same) — which
    is also why the base margin is one more VPU reduce against the
    VMEM-resident w₀ rather than a precomputed margins0 read (see the
    module docstring: the whole-shard matvec it replaces was most of the
    round's HBM traffic)."""
    lane4 = jax.lax.broadcasted_iota(jnp.int32, (1, STACK * LANES), 1)
    y = jnp.sum(jnp.where(lane4 == sub_lane, srow, 0.0))
    sq = jnp.sum(jnp.where(lane4 == sub_lane + LANES, srow, 0.0))
    a = jnp.sum(jnp.where(lane4 == sub_lane + 2 * LANES, srow, 0.0))

    margin = jnp.sum(x * w_k)
    if not frozen:
        margin = margin + sig_eff * jnp.sum(x * dw_k)
    # the dual coordinate update is pure scalar jnp — shared with the
    # fori_loop kernels via ops/losses.py (hinge = CoCoA.scala:166-178)
    new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor, lam_n,
                              smoothing=smoothing)
    coef = y * (new_a - a) / coef_div
    wmask = lane4 == sub_lane + 2 * LANES
    if live is not None:   # tail group past H (only when unroll ∤ H): inert
        coef = jnp.where(live, coef, 0.0)
        wmask = wmask & live
    return jnp.where(wmask, new_a, srow), coef * x


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    *refs,           # S row blocks, w, stacked vecs, 2 outs, 2 scratch
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    loss: str,
    smoothing: float,
    unroll: int,
    n_groups: int,
):
    # refs layout:
    #   x_refs[j]      (1, 1, 8, d8) VMEM: folded row of sample j
    #   w_ref          (8, d8) VMEM: the replicated w₀ (margin base)
    #   stacked_in     (1, n_blocks, 3·LANES) VMEM: shard k's lane-blocked
    #                  [labels | sq_norms | alpha] concatenation
    #   dw_ref         out (1, 8, d8) VMEM: shard k's Δw (flushed on k advance)
    #   alpha_ref      out (1, n_blocks, LANES) VMEM (flushed on k advance)
    #   dw_acc         scratch (8, d8) VMEM: this shard's Δw accumulator
    #   stacked_sc     scratch (n_blocks, 3·LANES): the advancing state
    x_refs = refs[:unroll]
    w_ref, stacked_in, dw_ref, alpha_ref, dw_acc, stacked_sc = refs[unroll:]
    k_ = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init_shard():
        dw_acc[...] = jnp.zeros_like(dw_acc)
        stacked_sc[...] = stacked_in[0]

    # S sequential coordinate steps per grid iteration, each against its own
    # prefetched row block; step j reads the dw_acc/stacked_sc written by j-1
    exact = h % unroll == 0
    for j in range(unroll):
        step = i * unroll + j
        # groups past H clamp their index (the row spec's index map does the
        # same clamp, so the DMA'd block matches) and zero their update;
        # when unroll | H there is no tail and the masking drops out
        idx = idxs_ref[k_, step if exact else jnp.minimum(step, h - 1)]
        live = None if exact else step < h
        blk = idx // LANES
        srow = stacked_sc[pl.ds(blk, 1)]      # (1, 3·LANES): one dyn read
        x = x_refs[j][0, 0]                   # (8, d8): the folded row
        new_row, dws = _step_body(
            srow, idx - blk * LANES, live, x, dw_acc[...], w_ref[...],
            frozen=frozen,
            sig_eff=sig_eff, qii_factor=qii_factor, lam_n=lam_n,
            coef_div=coef_div, loss=loss, smoothing=smoothing,
        )
        dw_acc[...] = dw_acc[...] + dws
        stacked_sc[pl.ds(blk, 1)] = new_row   # one dyn write

    @pl.when(i == n_groups - 1)
    def _flush_shard():
        dw_ref[0] = dw_acc[...]
        alpha_ref[0] = stacked_sc[:, 2 * LANES:]


def _kernel_interleaved(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    *refs,           # K*S row blocks, stacked_in, 2 outs, 2K scratch
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    loss: str,
    smoothing: float,
    unroll: int,
    n_groups: int,
    k: int,
):
    """Shard-interleaved variant: 1-D grid over step groups; each iteration
    advances EVERY shard's chain by S steps.  The K chains are independent
    and — crucially — keep their state in SEPARATE scratch refs, so Mosaic
    does not serialize them on ref aliasing and their per-step dependency
    chains overlap (measured ~1.6x over the shard-major kernel at epsilon
    scale, where the chain latency, not bandwidth, is the bound).  Needs
    all K shards' stacked state VMEM-resident (interleave_vmem_estimate)."""
    x_refs = refs[:k * unroll]           # x_refs[j*k + kk]
    w_ref = refs[k * unroll]
    stacked_in = refs[k * unroll + 1]
    dw_ref, alpha_ref = refs[k * unroll + 2:k * unroll + 4]
    dw_accs = refs[k * unroll + 4:k * unroll + 4 + k]
    st_scs = refs[k * unroll + 4 + k:]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for kk in range(k):
            dw_accs[kk][...] = jnp.zeros_like(dw_accs[kk])
            st_scs[kk][...] = stacked_in[kk]

    exact = h % unroll == 0
    for j in range(unroll):
        step = i * unroll + j
        live = None if exact else step < h
        step_c = step if exact else jnp.minimum(step, h - 1)
        for kk in range(k):
            idx = idxs_ref[kk, step_c]
            blk = idx // LANES
            srow = st_scs[kk][pl.ds(blk, 1)]
            x = x_refs[j * k + kk][0, 0]
            new_row, dws = _step_body(
                srow, idx - blk * LANES, live, x, dw_accs[kk][...],
                w_ref[...],
                frozen=frozen, sig_eff=sig_eff, qii_factor=qii_factor,
                lam_n=lam_n, coef_div=coef_div, loss=loss,
                smoothing=smoothing,
            )
            dw_accs[kk][...] = dw_accs[kk][...] + dws
            st_scs[kk][pl.ds(blk, 1)] = new_row

    @pl.when(i == n_groups - 1)
    def _flush():
        for kk in range(k):
            dw_ref[kk] = dw_accs[kk][...]
            alpha_ref[kk] = st_scs[kk][:, 2 * LANES:]


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing", "unroll", "interleave"),
)
def pallas_sdca_round(
    w: jax.Array,            # (d,) the replicated primal vector w₀
    alpha: jax.Array,        # (K, n_shard)
    X: jax.Array,            # (K, n_shard, d) dense rows
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
    unroll: int = 0,
    interleave=None,
):
    """One SDCA round for K shards on this chip.  Returns (dw, alpha_inner):
    dw (K, d) unreduced per-shard updates; alpha_inner (K, n_shard) the
    locally-advanced alpha (callers apply the outer scaling law).

    ``unroll`` = coordinate steps per grid iteration (0 = auto: the largest
    of 16/8/4/2/1 whose row blocks fit the VMEM budget).  Any value yields
    the same math — it only changes DMA batching.

    ``interleave`` (None = auto: K > 1 and all shards' state fits VMEM)
    advances the K independent chains in lockstep with separate scratch
    refs, overlapping their per-step dependency chains — same math, ~1.6x
    at epsilon scale.

    Inside ``shard_map`` this must run under ``check_vma=False`` (the
    chunked driver does; pallas_call's internal slices confuse the VMA
    checker)."""
    if X.ndim == 4:
        # pre-folded (K, n_shard, 8, d/8) — the hot paths fold once per run
        # OUTSIDE the round loop: folding in here would relayout the whole X
        # every round (the 3-D and 4-D tiled layouts differ physically)
        k, n_shard, _, d8 = X.shape
        d = d_orig = SUBLANES * d8
        X_folded = X
    else:
        k, n_shard, d = X.shape
        d_orig = d
        if d % SUBLANES:
            # hot configs avoid this copy: shard_dataset pads dense d to 8
            pad = SUBLANES - d % SUBLANES
            X = jnp.pad(X, ((0, 0), (0, 0), (0, pad)))
            d += pad
        d8 = d // SUBLANES
        X_folded = X.reshape(k, n_shard, SUBLANES, d8)
    h = idxs.shape[1]
    dtype = X.dtype
    check_dtype(dtype)
    itemsize = jnp.dtype(dtype).itemsize
    if interleave is None:
        # auto: the fit check must use the unroll that will actually run
        # (an explicit large unroll can blow the all-shards VMEM budget)
        fit = pick_interleave(k, n_shard, d, itemsize, h)
        interleave = fit > 0 and (
            not unroll
            or interleave_vmem_estimate(k, n_shard, d, itemsize, unroll)
            <= INTERLEAVE_BUDGET
        )
    if interleave and not unroll:
        # the interleaved budget governs the group size (pick_unroll's
        # single-shard budget would overshoot the all-shards working set)
        unroll = pick_interleave(k, n_shard, d, itemsize, h) or 1
    if not unroll:
        unroll = pick_unroll(n_shard, d, itemsize, h) or 1
    n_groups = -(-h // unroll)
    sig_eff, qii_factor = mode_factors(mode, sigma)

    # lane-block the per-shard vectors and lane-concatenate them into the
    # (K, n_blocks, 3·128) stacked state the kernel reads with ONE dynamic
    # slice per step (see _step_body).  Sampled indices never exceed the
    # shard's true row count, so zero padding is inert.
    n_pad = -(-n_shard // LANES) * LANES
    pad = [(0, 0), (0, n_pad - n_shard)]
    blocked = lambda v: jnp.pad(v, pad).reshape(k, n_pad // LANES, LANES)  # noqa: E731
    n_blocks = n_pad // LANES
    stacked = jnp.concatenate(
        [blocked(labels), blocked(sq_norms), blocked(alpha)], axis=-1,
    )  # (K, n_blocks, STACK*LANES)
    # the replicated w₀, folded like the rows (free reshape: contiguous)
    w_pad = jnp.pad(w.astype(dtype), (0, d - w.shape[0]))
    w_folded = w_pad.reshape(SUBLANES, d8)

    def row_spec(j, kk=None):
        # sample j of group i: the folded row at [shard, idx, :, :].  Groups
        # past H (only when unroll does not divide H) clamp to the last
        # sample — the kernels compute the same clamped index, so the DMA'd
        # block always matches.  ``kk`` fixes the shard (interleaved 1-D
        # grid); kk=None reads it from the grid (shard-major 2-D grid).
        exact = h % unroll == 0

        def step_of(i_):
            step = i_ * unroll + j if unroll > 1 else i_
            return step if exact else jnp.minimum(step, h - 1)

        if kk is None:
            index_map = lambda k_, i_, idxs_: (k_, idxs_[k_, step_of(i_)], 0, 0)
        else:
            index_map = lambda i_, idxs_: (kk, idxs_[kk, step_of(i_)], 0, 0)
        return pl.BlockSpec((1, 1, SUBLANES, d8), index_map)

    common = dict(
        lam_n=float(lam * n),
        coef_div=float(coef_divisor(mode, lam * n)),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
        h=h,
        loss=losses.validate(loss, smoothing),
        smoothing=float(smoothing),
        unroll=unroll,
        n_groups=n_groups,
    )

    if interleave:
        kernel = functools.partial(_kernel_interleaved, k=k, **common)


        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_groups,),
            in_specs=[
                *[row_spec(j, kk)
                  for j in range(unroll) for kk in range(k)],
                pl.BlockSpec((SUBLANES, d8), lambda i_, idxs_: (0, 0)),
                pl.BlockSpec((k, n_blocks, STACK * LANES),
                             lambda i_, idxs_: (0, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((k, SUBLANES, d8), lambda i_, idxs_: (0, 0, 0)),
                pl.BlockSpec((k, n_blocks, LANES),
                             lambda i_, idxs_: (0, 0, 0)),
            ],
            scratch_shapes=(
                [pltpu.VMEM((SUBLANES, d8), dtype)] * k
                + [pltpu.VMEM((n_blocks, STACK * LANES), dtype)] * k
            ),
        )
        n_row_ops = k * unroll
        semantics = ("arbitrary",)
    else:
        kernel = functools.partial(_kernel, **common)


        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(k, n_groups),
            in_specs=[
                *[row_spec(j) for j in range(unroll)],
                pl.BlockSpec((SUBLANES, d8), lambda k_, i_, idxs_: (0, 0)),
                pl.BlockSpec((1, n_blocks, STACK * LANES),
                             lambda k_, i_, idxs_: (k_, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, SUBLANES, d8),
                             lambda k_, i_, idxs_: (k_, 0, 0)),
                pl.BlockSpec((1, n_blocks, LANES),
                             lambda k_, i_, idxs_: (k_, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((SUBLANES, d8), dtype),
                pltpu.VMEM((n_blocks, STACK * LANES), dtype),
            ],
        )
        n_row_ops = unroll
        semantics = ("arbitrary", "arbitrary")

    dw, alpha_blocked = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((k, SUBLANES, d8), dtype),
            jax.ShapeDtypeStruct((k, n_blocks, LANES), dtype),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=semantics,
        ),
        interpret=interpret,
    )(idxs, *([X_folded] * n_row_ops), w_folded, stacked)
    alpha_inner = alpha_blocked.reshape(k, n_pad)[:, :n_shard]
    return dw.reshape(k, d)[:, :d_orig], alpha_inner
