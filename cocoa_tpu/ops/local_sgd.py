"""Local SGD / mini-batch SGD inner loop (reference: SGD.scala:87-139).

- ``local=True`` (Local SGD): H Pegasos-style steps on a private copy of w —
  per step, w *= (1 − ηλ) with η = 1/(λ(t_global + i)) (SGD.scala:106,117-121),
  then w += η·y·x when the hinge is active (:124-129); the worker's update is
  Δw = w − w_init (:132-134).
- ``local=False`` (mini-batch SGD): w stays frozen; the worker just sums raw
  hinge subgradients x·y over the H draws (:124-127); all η scaling happens
  driver-side (SGD.scala:44-50,57-59).

Like local_sdca, the loop is sequential only in the ``local=True`` case, but
both run as one fused ``lax.fori_loop`` for uniformity; the mini-batch case
could be vmapped, which matters only when H is large and the hot algorithm is
CoCoA anyway.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.rows import get_row, row_axpy, row_dot


def local_sgd(
    w_init: jax.Array,   # (d,)
    shard: dict,         # labels, X | sp_indices+sp_values
    idxs: jax.Array,     # (H,) int32
    lam: float,
    t_global,            # (t-1)*H*K, traced scalar (SGD.scala:53)
    local: bool,
    loss: str = "hinge",
    smoothing: float = 1.0,
):
    """Returns this worker's delta_w.  The hinge 0/1 "active" indicator
    (SGD.scala:115,124) generalizes to the loss's −ℓ'(z) factor."""
    losses.validate(loss, smoothing)
    labels = shard["labels"]
    dtype = w_init.dtype
    lam_c = jnp.asarray(lam, dtype)
    one = jnp.asarray(1.0, dtype)
    t0 = jnp.asarray(t_global, dtype)

    def step(i, carry):
        w, dw = carry
        # reference counts i from 1 (SGD.scala:104-106)
        eta = one / (lam_c * (t0 + i + 1))
        idx = idxs[i]
        row = get_row(shard, idx)
        y = labels[idx]
        g = losses.grad_factor(loss, y * row_dot(row, w), smoothing=smoothing)
        if local:
            # the reference also accumulates dw here but overwrites it with
            # w - w_init each step (SGD.scala:132-134); only the final value
            # matters, so the dead accumulation is skipped statically
            w = w * (one - eta * lam_c)
            w = row_axpy(row, y * eta * g, w)
        else:
            dw = row_axpy(row, y * g, dw)
        return w, dw

    dw0 = jnp.zeros_like(w_init)
    w_final, dw = lax.fori_loop(0, idxs.shape[0], step, (w_init, dw0))
    if local:
        return w_final - w_init  # SGD.scala:132-134
    return dw
