from cocoa_tpu.ops.local_sdca import local_sdca  # noqa: F401
from cocoa_tpu.ops.local_sgd import local_sgd  # noqa: F401
from cocoa_tpu.ops.subgradient import subgradient_pass  # noqa: F401
