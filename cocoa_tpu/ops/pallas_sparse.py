"""Pallas TPU kernel for the sequential SDCA inner loop — padded-CSR layout.

The XLA lowering of the sparse inner loop (ops/local_sdca.py with the
padded-CSR row accessors, ops/rows.py:46,53) serializes the per-nonzero
gather into ``Δw``/``w`` and the scatter-add back — measured ~44 µs per
coordinate step at rcv1 scale, plus a ~13 ms/round batched gather to
precompute the round's margins.  This kernel removes both:

- ``w`` and the Δw accumulator live **lane-blocked AND lane-concatenated**
  in VMEM as one (ceil(d/128), 2·128) array per shard (w in lanes [0,128),
  Δw in [128,256)), so a nonzero's margin contribution — which needs BOTH
  w[f] and Δw[f] — is ONE dynamic sublane slice + two 256-wide mask picks,
  and the scatter is a masked row update through the same slice.  Per
  nonzero: 2 dynamically-addressed VMEM accesses.  (Scalar-core address
  generation is the per-step bottleneck — same finding as the dense
  kernel, see pallas_sdca._step_body.)
- margins are computed **in-kernel** from the VMEM-resident ``w``
  (``margin = x·w + sig_eff·(x·Δw)``, the same decomposition as
  ops/local_sdca.py ``mode_factors`` with margins0 evaluated on the fly),
  so the per-round whole-shard margins gather disappears.
- the per-shard scalars (y, ‖x‖², α) are lane-concatenated the same way —
  one (n/128, 3·128) array per shard, one dynamic read + one write per
  step.

**Shard interleaving.**  The grid is 1-D over steps; each iteration
advances EVERY shard's chain by one step, with SEPARATE scratch refs per
shard (shared refs make Mosaic serialize on aliasing) — the K independent
per-nonzero dependency chains overlap.  k=1 (the shard_map per-device
case) degenerates to the plain sequential kernel.

Addressing constraint: Mosaic has no vector→scalar extraction, so every
dynamic address must come from SMEM.  The sampled rows' **feature
indices** AND **values** are gathered device-side outside the kernel into
(K, H_seg, max_nnz) tables and scalar-prefetched (SMEM holds f32 scalars
fine).  Round 3 kept the values in VMEM and picked nonzero j's value with
a max_nnz-wide lane mask; at heavy-tailed widths that one pick was the
widest op in the loop AND scaled with the PADDED width — an SMEM scalar
read costs O(1) regardless of W and removed the kernel's value
blocks/DMAs entirely.

Padded nonzero slots carry index 0 / value 0 and contribute exactly 0 to
every pick and scatter — no masking needed (same inertness trick as the
XLA path, ops/rows.py:10-11).

**Heavy-tailed rows (round 4).**  The padded width W is the MAX row nnz
across the dataset; real rcv1-like data is heavy-tailed (log-normal
document lengths), so W (~550) is ~7x the mean (~73) — and a flat unroll
over W slots per step both wastes ~85% of the per-nonzero work on padding
and blows Mosaic compile time up superlinearly in the unrolled-slot count
(measured: W=548 flat → 7 min compile; a pl.when-group-early-exit variant
kept the unroll and still compiled for minutes).  The per-nonzero loop is
therefore a **dynamic-trip ``fori_loop`` over GROUP-slot bodies**: the
trip count is ceil(row_nnz / GROUP) from a scalar-prefetched per-row
count, the body unrolls GROUP slots (values and indices are SMEM scalar
reads at dynamic group offsets), and the round-3 dead-end — ~200 ns of
scalar-branch overhead per dynamic iteration — amortizes to ~6 ns per
nonzero at GROUP=32.  Per step the cost tracks ceil(nnz/32)·32 slots
instead of W, compile size is ONE group body per pass per shard, and any
padded width works with no special tail.

Size guards: the SMEM index table is K·H_seg·max_nnz ints and must stay
under ``SMEM_IDX_BUDGET`` (512 KB — the 712 KB full-round rcv1 table
fails Mosaic compilation, so rounds split into SMEM-sized segments with
the concatenated state carried between them); ``sparse_kernel_fits``
checks the VMEM working set.  Oversized configs keep the XLA fori_loop
path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import coef_divisor, mode_factors
from cocoa_tpu.ops.pallas_sdca import LANES, check_dtype

ROW_BLOCK = 8          # aligned sublane block for the per-step value row
SMEM_IDX_BUDGET = 512 << 10
VMEM_BUDGET = 12 << 20


def sparse_vmem_estimate(n_shard: int, d: int, max_nnz: int, itemsize: int,
                         k: int = 1) -> int:
    """All K shards resident (the interleaved grid): per shard the
    (n_dblk, 2·128) w|Δw array ×3 (input, scratch, output with
    double-buffer slack) + the (n_blocks, 3·128) scalar stack ×3, plus the
    double-buffered (8, max_nnz) value blocks."""
    n_pad = -(-n_shard // LANES) * LANES
    d_pad = -(-d // LANES) * LANES
    del max_nnz  # values ride SMEM now (module docstring)
    return itemsize * k * (6 * d_pad + 9 * n_pad)


def sparse_kernel_fits(k: int, n_shard: int, d: int, max_nnz: int, h: int,
                       itemsize: int) -> bool:
    """VMEM feasibility (the SMEM index-table limit is handled by splitting
    the round into segments — see :func:`pallas_sparse_sdca_round`)."""
    del h
    return (
        segment_len(k, max_nnz) >= 1
        and sparse_vmem_estimate(n_shard, d, max_nnz, itemsize, k)
        <= VMEM_BUDGET
    )


def segment_len(k: int, max_nnz: int) -> int:
    """Steps per kernel invocation so the two (K, H_seg, max_nnz) SMEM
    tables (int32 feature indices + f32 values) stay inside the budget."""
    return SMEM_IDX_BUDGET // (8 * k * max(1, max_nnz))


GROUP = 32             # slots per dynamic-loop body (one branch per GROUP)


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H_seg) int32 sampled rows
    gidx_ref,        # scalar-prefetch: (K, H_seg, W) int32 feature indices
    svals_ref,       # scalar-prefetch: (K, H_seg, W) f32 nonzero values
    cnts_ref,        # scalar-prefetch: (K, H_seg) int32 per-row nnz counts
    *refs,           # wd_in, st_in, 2 outs, 2K+1 scratch
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    w_nnz: int,
    loss: str,
    smoothing: float,
    k: int,
):
    # refs layout (see module docstring for the concatenated layouts):
    #   wd_in         (K, n_dblk, 2·LANES): [w | Δw_carried] per shard
    #   st_in         (K, n_blocks, 3·LANES): [labels | ‖x‖² | α] per shard
    #   wd_out, st_out — same shapes (flushed at segment end; Δw and α
    #                    carry to the next segment through them)
    #   wd_scs[kk], st_scs[kk] — per-shard scratch (separate refs: chains
    #                    must not alias)
    wd_in, st_in, wd_out, st_out = refs[:4]
    wd_scs = refs[4:4 + k]
    st_scs = refs[4 + k:4 + 2 * k]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for kk in range(k):
            wd_scs[kk][...] = wd_in[kk]
            st_scs[kk][...] = st_in[kk]

    lane2 = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * LANES), 1)
    lane3 = jax.lax.broadcasted_iota(jnp.int32, (1, 3 * LANES), 1)

    group = min(GROUP, w_nnz)

    for kk in range(k):
        idx = idxs_ref[kk, i]
        cnt = cnts_ref[kk, i]
        n_trips = (cnt + (group - 1)) // group
        blk = idx // LANES
        sub_lane = idx - blk * LANES
        srow = st_scs[kk][pl.ds(blk, 1)]          # (1, 3·LANES)
        y = jnp.sum(jnp.where(lane3 == sub_lane, srow, 0.0))
        sq = jnp.sum(jnp.where(lane3 == sub_lane + LANES, srow, 0.0))
        a = jnp.sum(jnp.where(lane3 == sub_lane + 2 * LANES, srow, 0.0))
        dtype = srow.dtype

        def slot_margin(j):
            # one nonzero's margin contribution: the value/index are O(1)
            # SMEM scalar reads, and ONE dynamic slice serves both the w
            # and Δw picks (they share the concatenated row); slots past
            # the row's count carry index 0 / value 0 and contribute
            # exactly 0 (the trip count rounds up to the group size)
            f = gidx_ref[kk, i, j]
            fb = f // LANES
            fls = f - fb * LANES
            vj = svals_ref[kk, i, j]
            wrow = wd_scs[kk][pl.ds(fb, 1)]       # (1, 2·LANES)
            coord = jnp.sum(jnp.where(lane2 == fls, wrow, 0.0))
            if not frozen:
                coord = coord + sig_eff * jnp.sum(
                    jnp.where(lane2 == fls + LANES, wrow, 0.0)
                )
            return vj * coord

        # margin = x·w + sig_eff·(x·Δw), ceil(cnt/GROUP) dynamic trips of
        # a GROUP-slot unrolled body (module docstring: the dynamic-loop
        # branch overhead amortizes over the group; padding groups never
        # run)
        def margin_body(g, acc):
            base = g * group
            for u in range(group):
                acc = acc + slot_margin(base + u)
            return acc

        margin = jax.lax.fori_loop(0, n_trips, margin_body,
                                   jnp.asarray(0.0, dtype))

        new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor,
                                  lam_n, smoothing=smoothing)
        coef = y * (new_a - a) / coef_div

        def scatter_body(g, carry):
            # scatter-add coef·x into the Δw lanes: one masked row update
            # per nonzero (fresh read — nonzeros may share a lane block);
            # padded slots add exactly 0
            base = g * group
            for u in range(group):
                f = gidx_ref[kk, i, base + u]
                fb = f // LANES
                fls = f - fb * LANES
                vj = svals_ref[kk, i, base + u]
                wrow = wd_scs[kk][pl.ds(fb, 1)]
                wd_scs[kk][pl.ds(fb, 1)] = jnp.where(
                    lane2 == fls + LANES, wrow + coef * vj, wrow
                )
            return carry

        jax.lax.fori_loop(0, n_trips, scatter_body, jnp.int32(0))

        # cnt < 0 marks a padding step (the segment scan pads the round to
        # whole segments): its margin/scatter loops already ran 0 trips,
        # and the alpha write is gated off so the step is a true no-op
        @pl.when(cnt >= 0)
        def _write_alpha():
            st_scs[kk][pl.ds(blk, 1)] = jnp.where(
                lane3 == sub_lane + 2 * LANES, new_a, srow
            )

    @pl.when(i == h - 1)
    def _flush():
        for kk in range(k):
            wd_out[kk] = wd_scs[kk][...]
            st_out[kk] = st_scs[kk][...]


def row_lengths(sp_values: jax.Array) -> jax.Array:
    """(K, n_shard) int32 per-row nonzero-prefix lengths — 1 + the last
    slot holding a nonzero value (interior explicit zeros count; trailing
    padding does not).  Drives the kernel's group early exit; hot paths
    compute this ONCE per run (run_sdca_family attaches it to
    shard_arrays as ``sp_row_len``) — per round it would re-read the whole
    values array."""
    w = sp_values.shape[-1]
    iota = jnp.arange(1, w + 1, dtype=jnp.int32)
    return jnp.max(jnp.where(sp_values != 0, iota, 0), axis=-1) \
        .astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing"),
)
def pallas_sparse_sdca_round(
    w: jax.Array,            # (d,) the round's primal vector (replicated)
    alpha: jax.Array,        # (K, n_shard)
    sp_indices: jax.Array,   # (K, n_shard, W) int32 padded-CSR columns
    sp_values: jax.Array,    # (K, n_shard, W) padded-CSR values
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
    row_len: jax.Array = None,   # (K, n_shard) int32, see row_lengths
):
    """One sparse SDCA round for K shards on this chip.  Returns
    (dw, alpha_inner): dw (K, d) unreduced per-shard updates (dense — Δw is
    dense in the reference too, CoCoA.scala:145); alpha_inner (K, n_shard)
    the locally-advanced alpha.  Unlike the dense kernel no margins input is
    needed: the kernel reads x·w from the VMEM-resident w.

    When H exceeds the SMEM index-table budget the round is split into
    segments of :func:`segment_len` steps, each one ``pallas_call``; the
    concatenated (w|Δw, labels|‖x‖²|α) state carries between segments (a
    few MB of HBM traffic — the index table cannot be blocked,
    scalar-prefetch operands live whole in SMEM).  Same math regardless of
    segmentation.

    Requires n_shard % 8 == 0 (shard_dataset pads to 16).  Inside
    ``shard_map`` run with ``check_vma=False`` (as the chunked driver does).
    """
    k, n_shard, w_nnz = sp_indices.shape
    h = idxs.shape[1]
    d = w.shape[0]
    dtype = w.dtype
    check_dtype(dtype)
    if n_shard % ROW_BLOCK != 0:
        raise ValueError(
            f"n_shard must be a multiple of {ROW_BLOCK}, got {n_shard} "
            f"(shard_dataset pads to 16)"
        )
    sig_eff, qii_factor = mode_factors(mode, sigma)
    # segment sizing must use the GROUP-rounded width the SMEM tables are
    # actually padded to, or the budget overruns by up to one group
    w_round = -(-w_nnz // min(GROUP, w_nnz)) * min(GROUP, w_nnz)
    # capped at h: a small round must not pad up to a full budget-sized
    # grid of no-op steps
    h_seg = max(1, min(segment_len(k, w_round), h))

    # lane-block and lane-concatenate the state (module docstring layouts)
    n_pad = -(-n_shard // LANES) * LANES
    pad = [(0, 0), (0, n_pad - n_shard)]
    blocked = lambda v: jnp.pad(v, pad).reshape(k, n_pad // LANES, LANES)  # noqa: E731
    n_blocks = n_pad // LANES
    d_pad = -(-d // LANES) * LANES
    n_dblk = d_pad // LANES
    w_blocked = jnp.broadcast_to(
        jnp.pad(w, (0, d_pad - d)).reshape(1, n_dblk, LANES),
        (k, n_dblk, LANES),
    )
    wd = jnp.concatenate(
        [w_blocked, jnp.zeros((k, n_dblk, LANES), dtype)], axis=-1
    )
    st = jnp.concatenate(
        [blocked(labels), blocked(sq_norms), blocked(alpha)], axis=-1
    )
    idxs = idxs.astype(jnp.int32)
    if row_len is None:
        row_len = row_lengths(sp_values)

    full_wd = pl.BlockSpec(
        (k, n_dblk, 2 * LANES),
        lambda i_, idxs_, gidx_, svals_, cnts_: (0, 0, 0)
    )
    full_st = pl.BlockSpec(
        (k, n_blocks, 3 * LANES),
        lambda i_, idxs_, gidx_, svals_, cnts_: (0, 0, 0)
    )

    # The round's per-step feature indices AND values, gathered into SMEM
    # prefetch tables (addresses must be scalars; Mosaic cannot read them
    # from VMEM — and an SMEM value read is O(1) in W where a VMEM
    # lane-mask pick is O(W)), plus the rows' nnz counts for the
    # dynamic-trip loop.  The round pads to whole segments (padding steps
    # carry cnt = -1 → a kernel no-op) and runs as ONE ``lax.scan`` over
    # segments with a single pallas_call in the body: with localIterFrac=1
    # the round spans ~200 segments, and the round-3 unrolled-segment form
    # built ~200 pallas call sites into the graph — minutes of
    # trace/compile before the first step ran.
    n_seg = -(-h // h_seg)
    h_pad = n_seg * h_seg
    idxs_p = jnp.pad(idxs, ((0, 0), (0, h_pad - h)))
    gidx = jnp.take_along_axis(sp_indices, idxs_p[:, :, None], axis=1)
    svals = jnp.take_along_axis(
        sp_values, idxs_p[:, :, None], axis=1).astype(dtype)
    cnts = jnp.pad(
        jnp.take_along_axis(row_len, idxs_p, axis=1)[:, :h],
        ((0, 0), (0, h_pad - h)), constant_values=-1,
    )
    # pad the slot axis to the GROUP-rounded width (computed once above):
    # the kernel's trip count rounds the row's nnz up to whole groups, and
    # the last group may read past W otherwise (zero slots are inert)
    if w_round != w_nnz:
        gidx = jnp.pad(gidx, ((0, 0), (0, 0), (0, w_round - w_nnz)))
        svals = jnp.pad(svals, ((0, 0), (0, 0), (0, w_round - w_nnz)))
    # (n_seg, K, h_seg[, W]) scan leaves
    seg_shape = lambda a: a.reshape(k, n_seg, h_seg, *a.shape[2:]) \
        .swapaxes(0, 1)  # noqa: E731
    xs = (seg_shape(idxs_p), seg_shape(gidx), seg_shape(svals),
          seg_shape(cnts))

    kernel = functools.partial(
        _kernel,
        lam_n=float(lam * n),
        coef_div=float(coef_divisor(mode, lam * n)),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
        h=h_seg,
        w_nnz=w_nnz,
        loss=losses.validate(loss, smoothing),
        smoothing=float(smoothing),
        k=k,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(h_seg,),
        in_specs=[
            full_wd,   # [w | Δw] (Δw carried between segments)
            full_st,   # [labels | ‖x‖² | α]
        ],
        out_specs=[full_wd, full_st],
        scratch_shapes=(
            [pltpu.VMEM((n_dblk, 2 * LANES), dtype)] * k
            + [pltpu.VMEM((n_blocks, 3 * LANES), dtype)] * k
        ),
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((k, n_dblk, 2 * LANES), dtype),
            jax.ShapeDtypeStruct((k, n_blocks, 3 * LANES), dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )

    def seg_body(carry, seg_xs):
        wd_c, st_c = carry
        si, sg, sv, sc = seg_xs
        wd_c, st_c = call(si, sg, sv, sc, wd_c, st_c)
        return (wd_c, st_c), None

    if n_seg == 1:
        (wd, st), _ = seg_body((wd, st), jax.tree.map(lambda a: a[0], xs))
    else:
        (wd, st), _ = jax.lax.scan(seg_body, (wd, st), xs)

    dw = wd[:, :, LANES:].reshape(k, d_pad)[:, :d]
    alpha_inner = st[:, :, 2 * LANES:].reshape(k, n_pad)[:, :n_shard]
    return dw, alpha_inner
