"""Pallas TPU kernel for the sequential SDCA inner loop — padded-CSR layout.

The XLA lowering of the sparse inner loop (ops/local_sdca.py with the
padded-CSR row accessors, ops/rows.py:46,53) serializes the per-nonzero
gather into ``Δw``/``w`` and the scatter-add back — measured ~44 µs per
coordinate step at rcv1 scale, plus a ~13 ms/round batched gather to
precompute the round's margins.  This kernel removes both:

- ``w`` and the Δw accumulator live **lane-blocked AND lane-concatenated**
  in VMEM as one (ceil(d/128), 2·128) array per shard (w in lanes [0,128),
  Δw in [128,256)), so a nonzero's margin contribution — which needs BOTH
  w[f] and Δw[f] — is ONE dynamic sublane slice + two 256-wide mask picks,
  and the scatter is a masked row update through the same slice.  Per
  nonzero: 2 dynamically-addressed VMEM accesses.  (Scalar-core address
  generation is the per-step bottleneck — same finding as the dense
  kernel, see pallas_sdca._step_body.)
- margins are computed **in-kernel** from the VMEM-resident ``w``
  (``margin = x·w + sig_eff·(x·Δw)``, the same decomposition as
  ops/local_sdca.py ``mode_factors`` with margins0 evaluated on the fly),
  so the per-round whole-shard margins gather disappears.
- the per-shard scalars (y, ‖x‖², α) are lane-concatenated the same way —
  one (n/128, 3·128) array per shard, one dynamic read + one write per
  step.

**Shard interleaving.**  The grid is 1-D over steps; each iteration
advances EVERY shard's chain by one step, with SEPARATE scratch refs per
shard (shared refs make Mosaic serialize on aliasing) — the K independent
per-nonzero dependency chains overlap.  k=1 (the shard_map per-device
case) degenerates to the plain sequential kernel.

Addressing constraint: Mosaic has no vector→scalar extraction, so every
dynamic address must come from SMEM.  The sampled rows' **feature
indices** AND **values** are gathered device-side outside the kernel into
(K, H_seg, max_nnz) tables and scalar-prefetched (SMEM holds f32 scalars
fine).  Round 3 kept the values in VMEM and picked nonzero j's value with
a max_nnz-wide lane mask; at heavy-tailed widths that one pick was the
widest op in the loop AND scaled with the PADDED width — an SMEM scalar
read costs O(1) regardless of W and removed the kernel's value
blocks/DMAs entirely.

Padded nonzero slots carry index 0 / value 0 and contribute exactly 0 to
every pick and scatter — no masking needed (same inertness trick as the
XLA path, ops/rows.py:10-11).

**Heavy-tailed rows (round 4).**  The padded width W is the MAX row nnz
across the dataset; real rcv1-like data is heavy-tailed (log-normal
document lengths), so W (~550) is ~7x the mean (~73) — and a flat unroll
over W slots per step both wastes ~85% of the per-nonzero work on padding
and blows Mosaic compile time up superlinearly in the unrolled-slot count
(measured: W=548 flat → 7 min compile; a pl.when-group-early-exit variant
kept the unroll and still compiled for minutes).  The per-nonzero loop is
therefore a **dynamic-trip ``fori_loop`` over GROUP-slot bodies**: the
trip count is ceil(row_nnz / GROUP) from a scalar-prefetched per-row
count, the body unrolls GROUP slots (values and indices are SMEM scalar
reads at dynamic group offsets), and the round-3 dead-end — ~200 ns of
scalar-branch overhead per dynamic iteration — amortizes to ~6 ns per
nonzero at GROUP=32.  Per step the cost tracks ceil(nnz/32)·32 slots
instead of W, compile size is ONE group body per pass per shard, and any
padded width works with no special tail.

Size guards: the SMEM index table is K·H_seg·max_nnz ints and must stay
under ``SMEM_IDX_BUDGET`` (512 KB — the 712 KB full-round rcv1 table
fails Mosaic compilation, so rounds split into SMEM-sized segments with
the concatenated state carried between them); ``sparse_kernel_fits``
checks the VMEM working set.  Oversized configs keep the XLA fori_loop
path.

This module also carries the SPARSE BLOCK-CHAIN kernels (round 6):
``sparse_block_gram`` / ``sparse_block_apply`` compute the ``--blockSize``
path's (B, B) block Gram, margin base, and rank-B Δw apply from the same
SMEM-prefetched CSR layout — no (B, d) densify — feeding the lockstep
chain recurrence of ops/pallas_chain.py (see the section comment below).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import coef_divisor, mode_factors
from cocoa_tpu.ops.pallas_sdca import COMPILER_PARAMS, LANES, check_dtype

ROW_BLOCK = 8          # aligned sublane block for the per-step value row
SMEM_IDX_BUDGET = 512 << 10
VMEM_BUDGET = 12 << 20


def sparse_vmem_estimate(n_shard: int, d: int, max_nnz: int, itemsize: int,
                         k: int = 1, n_hot: int = 0) -> int:
    """All K shards resident (the interleaved grid): per shard the
    (n_dblk, 2·128) w|Δw array ×3 (input, scratch, output with
    double-buffer slack) + the (n_blocks, 3·128) scalar stack ×3, plus the
    double-buffered (8, max_nnz) value blocks.  The hybrid layout
    (``n_hot > 0``, the hot/cold split) adds per shard the (n_hot/128,
    128) hot-Δw array ×3 plus the shared w_hot operand and the per-step
    hot row's double buffer."""
    n_pad = -(-n_shard // LANES) * LANES
    d_pad = -(-d // LANES) * LANES
    del max_nnz  # values ride SMEM now (module docstring)
    return itemsize * (k * (6 * d_pad + 9 * n_pad)
                       + n_hot * (3 * k + 1) + 2 * k * n_hot)


def sparse_kernel_fits(k: int, n_shard: int, d: int, max_nnz: int, h: int,
                       itemsize: int, n_hot: int = 0) -> bool:
    """VMEM feasibility (the SMEM index-table limit is handled by splitting
    the round into segments — see :func:`pallas_sparse_sdca_round`)."""
    del h
    return (
        segment_len(k, max_nnz) >= 1
        and (n_hot == 0 or n_hot % LANES == 0)
        and sparse_vmem_estimate(n_shard, d, max_nnz, itemsize, k, n_hot)
        <= VMEM_BUDGET
    )


def segment_len(k: int, max_nnz: int) -> int:
    """Steps per kernel invocation so the two (K, H_seg, max_nnz) SMEM
    tables (int32 feature indices + f32 values) stay inside the budget."""
    return SMEM_IDX_BUDGET // (8 * k * max(1, max_nnz))


GROUP = 32             # slots per dynamic-loop body (one branch per GROUP)


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H_seg) int32 sampled rows
    gidx_ref,        # scalar-prefetch: (K, H_seg, W) int32 feature indices
    svals_ref,       # scalar-prefetch: (K, H_seg, W) f32 nonzero values
    cnts_ref,        # scalar-prefetch: (K, H_seg) int32 per-row nnz counts
    *refs,           # wd_in, st_in[, hot refs], outs, scratch
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    w_nnz: int,
    loss: str,
    smoothing: float,
    k: int,
    n_hblk: int = 0,
):
    # refs layout (see module docstring for the concatenated layouts):
    #   wd_in         (K, n_dblk, 2·LANES): [w | Δw_carried] per shard
    #   st_in         (K, n_blocks, 3·LANES): [labels | ‖x‖² | α] per shard
    #   hybrid (n_hblk > 0 — the hot/cold split, docs/DESIGN.md §3b-vi):
    #   hw_in         (n_hblk, LANES): w at the hot columns, read-only and
    #                    shared by all shards (the kernel never writes w)
    #   hd_in         (K, n_hblk, LANES): hot Δw carried between segments
    #   hrow_ref      (K, 1, n_hblk, LANES): THIS step's sampled rows' hot
    #                    panel slices (per-step BlockSpec — the pipeline
    #                    double-buffers the next step's rows automatically)
    #   wd_out, st_out[, hd_out] — flushed at segment end; Δw and α carry
    #                    to the next segment through them
    #   wd_scs[kk], st_scs[kk][, hd_scs[kk]] — per-shard scratch (separate
    #                    refs: chains must not alias)
    hot = n_hblk > 0
    if hot:
        wd_in, st_in, hw_in, hd_in, hrow_ref = refs[:5]
        wd_out, st_out, hd_out = refs[5:8]
        scs = refs[8:]
        wd_scs, st_scs = scs[:k], scs[k:2 * k]
        hd_scs = scs[2 * k:3 * k]
    else:
        wd_in, st_in, wd_out, st_out = refs[:4]
        wd_scs = refs[4:4 + k]
        st_scs = refs[4 + k:4 + 2 * k]
        hd_scs = None
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for kk in range(k):
            wd_scs[kk][...] = wd_in[kk]
            st_scs[kk][...] = st_in[kk]
            if hot:
                hd_scs[kk][...] = hd_in[kk]

    lane2 = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * LANES), 1)
    lane3 = jax.lax.broadcasted_iota(jnp.int32, (1, 3 * LANES), 1)

    group = min(GROUP, w_nnz)

    for kk in range(k):
        idx = idxs_ref[kk, i]
        cnt = cnts_ref[kk, i]
        n_trips = (cnt + (group - 1)) // group
        blk = idx // LANES
        sub_lane = idx - blk * LANES
        srow = st_scs[kk][pl.ds(blk, 1)]          # (1, 3·LANES)
        y = jnp.sum(jnp.where(lane3 == sub_lane, srow, 0.0))
        sq = jnp.sum(jnp.where(lane3 == sub_lane + LANES, srow, 0.0))
        a = jnp.sum(jnp.where(lane3 == sub_lane + 2 * LANES, srow, 0.0))
        dtype = srow.dtype

        def slot_margin(j):
            # one nonzero's margin contribution: the value/index are O(1)
            # SMEM scalar reads, and ONE dynamic slice serves both the w
            # and Δw picks (they share the concatenated row); slots past
            # the row's count carry index 0 / value 0 and contribute
            # exactly 0 (the trip count rounds up to the group size)
            f = gidx_ref[kk, i, j]
            fb = f // LANES
            fls = f - fb * LANES
            vj = svals_ref[kk, i, j]
            wrow = wd_scs[kk][pl.ds(fb, 1)]       # (1, 2·LANES)
            coord = jnp.sum(jnp.where(lane2 == fls, wrow, 0.0))
            if not frozen:
                coord = coord + sig_eff * jnp.sum(
                    jnp.where(lane2 == fls + LANES, wrow, 0.0)
                )
            return vj * coord

        # margin = x·w + sig_eff·(x·Δw), ceil(cnt/GROUP) dynamic trips of
        # a GROUP-slot unrolled body (module docstring: the dynamic-loop
        # branch overhead amortizes over the group; padding groups never
        # run)
        def margin_body(g, acc):
            base = g * group
            for u in range(group):
                acc = acc + slot_margin(base + u)
            return acc

        margin = jax.lax.fori_loop(0, n_trips, margin_body,
                                   jnp.asarray(0.0, dtype))

        if hot:
            # hot-panel margin term: two whole-array VPU multiply-reduces
            # against the lane-blocked w_hot / Δw_hot — O(n_hot/128)
            # lane-rows where the stream loop pays ~6 scalar ops PER
            # nonzero; the cold stream above covered only the residual
            hrow = hrow_ref[kk, 0]                # (n_hblk, LANES)
            mh = jnp.sum(hrow * hw_in[...])
            if not frozen:
                mh = mh + sig_eff * jnp.sum(hrow * hd_scs[kk][...])
            margin = margin + mh

        new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor,
                                  lam_n, smoothing=smoothing)
        coef = y * (new_a - a) / coef_div

        def scatter_body(g, carry):
            # scatter-add coef·x into the Δw lanes: one masked row update
            # per nonzero (fresh read — nonzeros may share a lane block);
            # padded slots add exactly 0
            base = g * group
            for u in range(group):
                f = gidx_ref[kk, i, base + u]
                fb = f // LANES
                fls = f - fb * LANES
                vj = svals_ref[kk, i, base + u]
                wrow = wd_scs[kk][pl.ds(fb, 1)]
                wd_scs[kk][pl.ds(fb, 1)] = jnp.where(
                    lane2 == fls + LANES, wrow + coef * vj, wrow
                )
            return carry

        jax.lax.fori_loop(0, n_trips, scatter_body, jnp.int32(0))

        if hot:
            # hot-panel Δw axpy: one whole-array VPU op (vs a masked row
            # update per nonzero on the stream side).  Gated off on
            # padding steps — the stream loops self-gate through their
            # zero trip counts, but this is a full-array op
            @pl.when(cnt >= 0)
            def _hot_scatter():
                hd_scs[kk][...] = hd_scs[kk][...] + coef * hrow

        # cnt < 0 marks a padding step (the segment scan pads the round to
        # whole segments): its margin/scatter loops already ran 0 trips,
        # and the alpha write is gated off so the step is a true no-op
        @pl.when(cnt >= 0)
        def _write_alpha():
            st_scs[kk][pl.ds(blk, 1)] = jnp.where(
                lane3 == sub_lane + 2 * LANES, new_a, srow
            )

    @pl.when(i == h - 1)
    def _flush():
        for kk in range(k):
            wd_out[kk] = wd_scs[kk][...]
            st_out[kk] = st_scs[kk][...]
            if hot:
                hd_out[kk] = hd_scs[kk][...]


def row_lengths(sp_values: jax.Array) -> jax.Array:
    """(K, n_shard) int32 per-row nonzero-prefix lengths — 1 + the last
    slot holding a nonzero value (interior explicit zeros count; trailing
    padding does not).  Drives the kernel's group early exit; hot paths
    compute this ONCE per run (run_sdca_family attaches it to
    shard_arrays as ``sp_row_len``) — per round it would re-read the whole
    values array."""
    w = sp_values.shape[-1]
    iota = jnp.arange(1, w + 1, dtype=jnp.int32)
    return jnp.max(jnp.where(sp_values != 0, iota, 0), axis=-1) \
        .astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing"),
)
def pallas_sparse_sdca_round(
    w: jax.Array,            # (d,) the round's primal vector (replicated)
    alpha: jax.Array,        # (K, n_shard)
    sp_indices: jax.Array,   # (K, n_shard, W) int32 padded-CSR columns
    sp_values: jax.Array,    # (K, n_shard, W) padded-CSR values
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
    row_len: jax.Array = None,   # (K, n_shard) int32, see row_lengths
    hot_cols: jax.Array = None,  # hybrid: (K, n_hot) int32 panel columns
    hot_panel: jax.Array = None,  # hybrid: (K, n_shard, n_hot) hot panel
):
    """One sparse SDCA round for K shards on this chip.  Returns
    (dw, alpha_inner): dw (K, d) unreduced per-shard updates (dense — Δw is
    dense in the reference too, CoCoA.scala:145); alpha_inner (K, n_shard)
    the locally-advanced alpha.  Unlike the dense kernel no margins input is
    needed: the kernel reads x·w from the VMEM-resident w.

    ``hot_panel``/``hot_cols`` select the HYBRID branch (the hot/cold
    column split, docs/DESIGN.md §3b-vi): ``sp_indices``/``sp_values``
    then hold only the cold residual (narrower W → shorter stream loops),
    and each step adds the sampled row's hot-panel slice — streamed
    through VMEM one step ahead by a per-step BlockSpec — against the
    lane-blocked [w_hot] operand and per-shard Δw_hot scratch as
    whole-array VPU ops.  Same math: columns partition, so hot + cold
    permutes the per-nonzero sums.

    When H exceeds the SMEM index-table budget the round is split into
    segments of :func:`segment_len` steps, each one ``pallas_call``; the
    concatenated (w|Δw, labels|‖x‖²|α) state carries between segments (a
    few MB of HBM traffic — the index table cannot be blocked,
    scalar-prefetch operands live whole in SMEM).  Same math regardless of
    segmentation.

    Requires n_shard % 8 == 0 (shard_dataset pads to 16).  Inside
    ``shard_map`` run with ``check_vma=False`` (as the chunked driver does).
    """
    k, n_shard, w_nnz = sp_indices.shape
    h = idxs.shape[1]
    d = w.shape[0]
    dtype = w.dtype
    check_dtype(dtype)
    if n_shard % ROW_BLOCK != 0:
        raise ValueError(
            f"n_shard must be a multiple of {ROW_BLOCK}, got {n_shard} "
            f"(shard_dataset pads to 16)"
        )
    sig_eff, qii_factor = mode_factors(mode, sigma)
    # segment sizing must use the GROUP-rounded width the SMEM tables are
    # actually padded to, or the budget overruns by up to one group
    w_round = -(-w_nnz // min(GROUP, w_nnz)) * min(GROUP, w_nnz)
    # capped at h: a small round must not pad up to a full budget-sized
    # grid of no-op steps
    h_seg = max(1, min(segment_len(k, w_round), h))

    # lane-block and lane-concatenate the state (module docstring layouts)
    n_pad = -(-n_shard // LANES) * LANES
    pad = [(0, 0), (0, n_pad - n_shard)]
    blocked = lambda v: jnp.pad(v, pad).reshape(k, n_pad // LANES, LANES)  # noqa: E731
    n_blocks = n_pad // LANES
    d_pad = -(-d // LANES) * LANES
    n_dblk = d_pad // LANES
    w_blocked = jnp.broadcast_to(
        jnp.pad(w, (0, d_pad - d)).reshape(1, n_dblk, LANES),
        (k, n_dblk, LANES),
    )
    wd = jnp.concatenate(
        [w_blocked, jnp.zeros((k, n_dblk, LANES), dtype)], axis=-1
    )
    st = jnp.concatenate(
        [blocked(labels), blocked(sq_norms), blocked(alpha)], axis=-1
    )
    idxs = idxs.astype(jnp.int32)
    if row_len is None:
        row_len = row_lengths(sp_values)
    hot = hot_panel is not None
    n_hot = int(hot_panel.shape[-1]) if hot else 0
    if hot and n_hot % LANES != 0:
        raise ValueError(f"hot panel width must be a multiple of {LANES}, "
                         f"got {n_hot} (data/hybrid.pad_panel owns this)")
    n_hblk = n_hot // LANES

    full_wd = pl.BlockSpec(
        (k, n_dblk, 2 * LANES),
        lambda i_, idxs_, gidx_, svals_, cnts_: (0, 0, 0)
    )
    full_st = pl.BlockSpec(
        (k, n_blocks, 3 * LANES),
        lambda i_, idxs_, gidx_, svals_, cnts_: (0, 0, 0)
    )

    # The round's per-step feature indices AND values, gathered into SMEM
    # prefetch tables (addresses must be scalars; Mosaic cannot read them
    # from VMEM — and an SMEM value read is O(1) in W where a VMEM
    # lane-mask pick is O(W)), plus the rows' nnz counts for the
    # dynamic-trip loop.  The round pads to whole segments (padding steps
    # carry cnt = -1 → a kernel no-op) and runs as ONE ``lax.scan`` over
    # segments with a single pallas_call in the body: with localIterFrac=1
    # the round spans ~200 segments, and the round-3 unrolled-segment form
    # built ~200 pallas call sites into the graph — minutes of
    # trace/compile before the first step ran.
    n_seg = -(-h // h_seg)
    h_pad = n_seg * h_seg
    idxs_p = jnp.pad(idxs, ((0, 0), (0, h_pad - h)))
    gidx = jnp.take_along_axis(sp_indices, idxs_p[:, :, None], axis=1)
    svals = jnp.take_along_axis(
        sp_values, idxs_p[:, :, None], axis=1).astype(dtype)
    cnts = jnp.pad(
        jnp.take_along_axis(row_len, idxs_p, axis=1)[:, :h],
        ((0, 0), (0, h_pad - h)), constant_values=-1,
    )
    # pad the slot axis to the GROUP-rounded width (computed once above):
    # the kernel's trip count rounds the row's nnz up to whole groups, and
    # the last group may read past W otherwise (zero slots are inert)
    if w_round != w_nnz:
        gidx = jnp.pad(gidx, ((0, 0), (0, 0), (0, w_round - w_nnz)))
        svals = jnp.pad(svals, ((0, 0), (0, 0), (0, w_round - w_nnz)))
    # (n_seg, K, h_seg[, W]) scan leaves
    seg_shape = lambda a: a.reshape(k, n_seg, h_seg, *a.shape[2:]) \
        .swapaxes(0, 1)  # noqa: E731
    xs = (seg_shape(idxs_p), seg_shape(gidx), seg_shape(svals),
          seg_shape(cnts))
    if hot:
        # the sampled rows' hot-panel slices, gathered per round like the
        # CSR streams and lane-blocked for the kernel's per-step BlockSpec
        hrows = jnp.take_along_axis(
            hot_panel, idxs_p[:, :, None], axis=1).astype(dtype) \
            .reshape(k, h_pad, n_hblk, LANES)
        xs = (*xs, seg_shape(hrows))
        hw = jnp.take(w, hot_cols[0]).reshape(n_hblk, LANES)
        hd = jnp.zeros((k, n_hblk, LANES), dtype)

    kernel = functools.partial(
        _kernel,
        lam_n=float(lam * n),
        coef_div=float(coef_divisor(mode, lam * n)),
        sig_eff=float(sig_eff),
        qii_factor=float(qii_factor),
        frozen=(mode == "frozen"),
        h=h_seg,
        w_nnz=w_nnz,
        loss=losses.validate(loss, smoothing),
        smoothing=float(smoothing),
        k=k,
        n_hblk=n_hblk,
    )
    in_specs = [
        full_wd,   # [w | Δw] (Δw carried between segments)
        full_st,   # [labels | ‖x‖² | α]
    ]
    out_specs = [full_wd, full_st]
    out_shape = [
        jax.ShapeDtypeStruct((k, n_dblk, 2 * LANES), dtype),
        jax.ShapeDtypeStruct((k, n_blocks, 3 * LANES), dtype),
    ]
    scratch = (
        [pltpu.VMEM((n_dblk, 2 * LANES), dtype)] * k
        + [pltpu.VMEM((n_blocks, 3 * LANES), dtype)] * k
    )
    if hot:
        full_hw = pl.BlockSpec((n_hblk, LANES), lambda i, *_: (0, 0))
        full_hd = pl.BlockSpec((k, n_hblk, LANES), lambda i, *_: (0, 0, 0))
        # ONE step's hot rows per grid iteration — the pipeline
        # double-buffers step i+1's block while step i runs
        step_hr = pl.BlockSpec((k, 1, n_hblk, LANES),
                               lambda i, *_: (0, i, 0, 0))
        in_specs += [full_hw, full_hd, step_hr]
        out_specs += [full_hd]
        out_shape += [jax.ShapeDtypeStruct((k, n_hblk, LANES), dtype)]
        scratch += [pltpu.VMEM((n_hblk, LANES), dtype)] * k
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(h_seg,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )

    if hot:
        def seg_body(carry, seg_xs):
            wd_c, st_c, hd_c = carry
            si, sg, sv, sc, hr = seg_xs
            wd_c, st_c, hd_c = call(si, sg, sv, sc, wd_c, st_c, hw, hd_c,
                                    hr)
            return (wd_c, st_c, hd_c), None

        carry0 = (wd, st, hd)
    else:
        def seg_body(carry, seg_xs):
            wd_c, st_c = carry
            si, sg, sv, sc = seg_xs
            wd_c, st_c = call(si, sg, sv, sc, wd_c, st_c)
            return (wd_c, st_c), None

        carry0 = (wd, st)

    if n_seg == 1:
        carry, _ = seg_body(carry0, jax.tree.map(lambda a: a[0], xs))
    else:
        carry, _ = jax.lax.scan(seg_body, carry0, xs)
    wd, st = carry[0], carry[1]

    dw = wd[:, :, LANES:].reshape(k, d_pad)[:, :d]
    if hot:
        # fold the hot Δw back into the full Δw at its column ids — hot
        # and cold columns are disjoint, and inert panel-padding lanes
        # carry value 0 at column 0, so the scatter-add is exact
        dw = dw.at[jnp.arange(k)[:, None], hot_cols].add(
            carry[2].reshape(k, n_hot))
    alpha_inner = st[:, :, 2 * LANES:].reshape(k, n_pad)[:, :n_shard]
    return dw, alpha_inner


# ---------------------------------------------------------------------------
# Sparse block-chain support: the (B, B) block Gram and the margin base
# computed IN-KERNEL from the SMEM-scalar-prefetched padded-CSR streams (no
# densify to (B, d)), plus the rank-B Δw apply as a sparse scatter.
# ---------------------------------------------------------------------------
#
# The dense block path (ops/local_sdca.local_sdca_block_batched) gathers each
# sampled block into a (K, B, d) dense tile before the Gram matmul; at rcv1
# scale (d≈47k, ~73 nnz/row) that is ~650x more HBM traffic than the rows'
# nonzeros, and benchmarks/KERNELS.md measured the densified block path 2.2x
# SLOWER than the sequential sparse kernel.  These kernels replace every
# O(B·d) dense tile with O(nnz) work over the same SMEM-scalar-prefetched
# padded-CSR layout the sequential kernel proved out:
#
# - ``sparse_block_gram``: Gram entry (i, j) = Σ_t v_j[t]·x_i[f_j[t]] is an
#   O(nnz_j) merge over SMEM index streams against a lane-blocked dense
#   expansion of row i ((d/128, 128) VMEM scratch, one masked-row scatter
#   per nonzero — O(nnz_i), amortized over the B-1 entries of Gram row i).
#   Only the strict upper triangle is computed: the chain multiplies
#   G[i, j] by the step-i coefficient, which is zero for i ≥ j.  The margin
#   base x_i·(w + σ′·Δw_blockstart) comes from the same streams against the
#   lane-concatenated [w | Δw] array (ONE dynamic slice serves both — the
#   sequential kernel's layout), so the block path needs no whole-shard
#   margins pass and no dense w.
# - ``sparse_block_apply``: Δw += Σ_j coef_j·x_j as a masked-row scatter
#   over the block's nonzeros — O(Σ_j nnz_j), not O(B·d).
#
# **SMEM segmentation.**  The scalar-prefetch tables must live whole in
# SMEM, and a (B, W) block at rcv1 scale (B=128, W≈550 GROUP-rounded) is
# ~590 KB — over the measured budget.  The Gram therefore computes in
# (S, S) row-segment tiles: a call for segment pair (s, u ≥ s) prefetches
# only the two segments' streams (2·S·W·8 bytes ≤ SMEM_IDX_BUDGET) and
# fills G[i ∈ s, j ∈ u]; scatters of segment-s rows are repeated per pair
# (O(nnz) each — noise against the merge work).  All (shard, pair) tiles
# run as ONE ``lax.scan`` over a single pallas_call site — the round-3
# many-call-sites compile blow-up does not recur.  The per-row GROUP-loop
# early exit (dynamic trip counts from prefetched per-row nnz) carries
# over unchanged, so heavy-tailed widths cost ceil(nnz/32)·32 slots, not W.


def seg_rows(b: int, w_nnz: int) -> int:
    """Rows per Gram-tile segment: the largest power-of-two divisor S of B
    (≥ 8, so output tiles stay sublane-aligned) such that a segment PAIR's
    scalar-prefetch tables — two (S, W_rounded) int32+f32 stream sets —
    fit the SMEM budget.  0 when even S=8 does not fit (the caller then
    keeps the densified path)."""
    group = min(GROUP, max(1, w_nnz))
    w_r = -(-w_nnz // group) * group
    s = b
    while s >= 8 and 16 * s * w_r > SMEM_IDX_BUDGET:
        s //= 2
    return s if s >= 8 and b % s == 0 else 0


def sparse_block_vmem(d: int, b: int, s: int, itemsize: int) -> int:
    """Working set of one Gram-tile call: the (d/128, 2·128) wd operand
    (double-buffered), the (d/128, 128) dense-row scratch, and the small
    (S, 128·⌈S/128⌉) gram / (1, ·) mb tiles."""
    d_pad = -(-d // LANES) * LANES
    lanes_out = -(-s // LANES) * LANES
    return itemsize * (5 * d_pad + 2 * s * lanes_out + 2 * lanes_out)


def sparse_chain_fits(k: int, n_shard: int, d: int, max_nnz: int, b: int,
                      itemsize: int) -> bool:
    """Feasibility of the sparse block-chain path: whole-lane-tile blocks
    (the chain kernel's contract), an SMEM-feasible segment size, the chain
    kernel's VMEM fit, and the Gram call's VMEM fit."""
    from cocoa_tpu.ops.pallas_chain import chain_fits

    s = seg_rows(b, max_nnz)
    del n_shard
    return (
        b % LANES == 0
        and s > 0
        and chain_fits(k, b, itemsize)
        and sparse_block_vmem(d, b, s, itemsize) <= VMEM_BUDGET
    )


def hybrid_fits(k: int, n_shard: int, d: int, max_nnz: int, b: int,
                n_hot: int, itemsize: int) -> bool:
    """Feasibility of the HYBRID block path (hot/cold split,
    docs/DESIGN.md §3b-vi): the cold residual runs through the exact
    CSR-stream machinery :func:`sparse_chain_fits` gates (``max_nnz`` is
    the RESIDUAL width — narrower than the unsplit streams, so the split
    only widens feasibility), and the hot panel must be lane-aligned; its
    Gram/margin/apply terms are XLA MXU einsum tiles, not VMEM-resident
    kernel state, so the panel adds no VMEM constraint here (the
    SEQUENTIAL kernel's panel accounting lives in
    :func:`sparse_kernel_fits` via ``n_hot``)."""
    return (
        n_hot > 0
        and n_hot % LANES == 0
        and sparse_chain_fits(k, n_shard, d, max_nnz, b, itemsize)
    )


def wd_stack(w: jax.Array, k: int) -> jax.Array:
    """(d,) replicated w -> the (K, d/128, 2·128) lane-blocked AND
    lane-concatenated [w | Δw=0] array the sparse kernels address (module
    docstring layout; Δw rides lanes [128, 256))."""
    d = w.shape[0]
    d_pad = -(-d // LANES) * LANES
    n_dblk = d_pad // LANES
    w_blocked = jnp.broadcast_to(
        jnp.pad(w, (0, d_pad - d)).reshape(1, n_dblk, LANES),
        (k, n_dblk, LANES),
    )
    return jnp.concatenate(
        [w_blocked, jnp.zeros((k, n_dblk, LANES), w.dtype)], axis=-1
    )


def wd_delta(wd: jax.Array, d: int) -> jax.Array:
    """Extract the accumulated (K, d) Δw from the concatenated layout."""
    k, n_dblk, _ = wd.shape
    return wd[:, :, LANES:].reshape(k, n_dblk * LANES)[:, :d]


def _gram_kernel(
    sidx_ref,    # scalar-prefetch: (S, W) int32 scatter-segment indices
    svals_ref,   # scalar-prefetch: (S, W) f32 scatter-segment values
    scnt_ref,    # scalar-prefetch: (S,) int32 scatter-row nnz (-1 = pad step)
    pidx_ref,    # scalar-prefetch: (S, W) int32 pick-segment indices
    pvals_ref,   # scalar-prefetch: (S, W) f32 pick-segment values
    pcnt_ref,    # scalar-prefetch: (S,) int32 pick-row nnz
    diag_ref,    # scalar-prefetch: (1,) int32, 1 when pick seg == scatter seg
    wd_ref,      # (n_dblk, 2·LANES) [w | Δw at block start], read-only
    gram_ref,    # out (S, lanes_out): gram_ref[j, i] = G[i, j], i < j only
    mb_ref,      # out (1, lanes_out): margin base (diagonal tiles only)
    xrow_ref,    # scratch (n_dblk, LANES): dense expansion of scatter row i
    *,
    s: int,
    w_nnz: int,
    sig_eff: float,
    frozen: bool,
    lanes_out: int,
):
    """Grid (S,) over scatter rows i.  Step i scatters row i densely into
    ``xrow`` (O(nnz_i) masked row updates), then merges every pick row j
    (j > i on diagonal tiles, all j off-diagonal) against it — each Gram
    entry an O(nnz_j) accumulate of SMEM scalar reads and (1, 128) dynamic
    slices, with the GROUP-loop trip counts skipping padding.  Diagonal
    tiles also emit the margin base from the [w | Δw] operand (one slice
    serves both coordinates — the concatenation trick)."""
    i = pl.program_id(0)
    group = min(GROUP, max(1, w_nnz))
    dtype = wd_ref.dtype
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, lanes_out), 1)
    lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    lane2 = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * LANES), 1)

    @pl.when(i == 0)
    def _init():
        gram_ref[...] = jnp.zeros((s, lanes_out), dtype)
        mb_ref[...] = jnp.zeros((1, lanes_out), dtype)

    diag = diag_ref[0] == 1
    cnt_i = scnt_ref[i]
    trips_i = (jnp.maximum(cnt_i, 0) + (group - 1)) // group

    # dense lane-blocked expansion of scatter row i; padded slots add
    # exactly 0 at feature 0 (same inertness trick as the whole module)
    xrow_ref[...] = jnp.zeros(xrow_ref.shape, dtype)

    def scatter_body(g, c):
        base = g * group
        for u in range(group):
            f = sidx_ref[i, base + u]
            fb = f // LANES
            fls = f - fb * LANES
            v = svals_ref[i, base + u]
            row = xrow_ref[pl.ds(fb, 1)]
            xrow_ref[pl.ds(fb, 1)] = jnp.where(lane1 == fls, row + v, row)
        return c

    jax.lax.fori_loop(0, trips_i, scatter_body, jnp.int32(0))

    # margin base x_i·(w + σ′·Δw_blockstart), diagonal tiles only (each row
    # is a scatter row of exactly one diagonal tile)
    @pl.when(diag)
    def _margin():
        def m_body(g, acc):
            base = g * group
            for u in range(group):
                f = sidx_ref[i, base + u]
                fb = f // LANES
                fls = f - fb * LANES
                v = svals_ref[i, base + u]
                wrow = wd_ref[pl.ds(fb, 1)]
                coord = jnp.sum(jnp.where(lane2 == fls, wrow, 0.0))
                if not frozen:
                    coord = coord + sig_eff * jnp.sum(
                        jnp.where(lane2 == fls + LANES, wrow, 0.0)
                    )
                acc = acc + v * coord
            return acc

        m = jax.lax.fori_loop(0, trips_i, m_body, jnp.asarray(0.0, dtype))
        mb_ref[...] = jnp.where(lane == i, m, mb_ref[...])

    if frozen:
        return  # frozen margins never see Δw: no Gram coupling needed

    # Gram row i against every later pick row: G[i, j] = Σ_t v_j·xrow[f_j],
    # written at [j, i] so the chain's per-step read is ONE leading-dim
    # dynamic sublane slice (gram is assembled j-leading)
    j_start = jnp.where(diag, i + 1, 0)

    def j_body(j, c):
        cnt_j = pcnt_ref[j]
        trips_j = (jnp.maximum(cnt_j, 0) + (group - 1)) // group

        def p_body(g, acc):
            base = g * group
            for u in range(group):
                f = pidx_ref[j, base + u]
                fb = f // LANES
                fls = f - fb * LANES
                v = pvals_ref[j, base + u]
                xr = xrow_ref[pl.ds(fb, 1)]
                acc = acc + v * jnp.sum(jnp.where(lane1 == fls, xr, 0.0))
            return acc

        g_ij = jax.lax.fori_loop(0, trips_j, p_body, jnp.asarray(0.0, dtype))
        grow = gram_ref[pl.ds(j, 1)]
        gram_ref[pl.ds(j, 1)] = jnp.where(lane == i, g_ij, grow)
        return c

    jax.lax.fori_loop(j_start, s, j_body, jnp.int32(0))


def sparse_block_gram(
    wd: jax.Array,       # (K, n_dblk, 2·LANES) [w | Δw at block start]
    gidx: jax.Array,     # (K, B, W_r) int32 block CSR indices (GROUP-rounded)
    svals: jax.Array,    # (K, B, W_r) block CSR values
    cnts: jax.Array,     # (K, B) int32 per-row nnz; -1 marks padded steps
    sig_eff: float,
    frozen: bool,
    interpret: bool = False,
):
    """The block's Gram and margin base, in-kernel from the CSR streams.

    Returns ``(gram, mb)``: gram (B, K, B) j-leading with the strict upper
    triangle filled (``gram[j, k, i] = x_i·x_j`` of shard k for i < j,
    zeros elsewhere — exactly the entries the chain's coefficient dots can
    see; None in frozen mode), and mb (K, B) = x_j·(w + σ′·Δw_blockstart)
    (x_j·w for frozen).  All (shard, segment-pair) tiles run as one
    ``lax.scan`` over a single pallas_call site."""
    k, b, w_r = gidx.shape
    dtype = wd.dtype
    n_dblk = wd.shape[1]
    s = seg_rows(b, w_r)
    if s <= 0:
        raise ValueError(
            f"no SMEM-feasible Gram segment for B={b}, W={w_r} "
            f"(sparse_chain_fits should have rejected this config)"
        )
    ns = b // s
    lanes_out = -(-s // LANES) * LANES
    # (shard, scatter-seg, pick-seg) tiles; only u >= s segments (upper
    # triangle — earlier pick rows multiply zero coefficients)
    pairs = [(si, ui) for si in range(ns) for ui in range(si, ns)]
    np_ = len(pairs)
    si_t = jnp.tile(jnp.asarray([p[0] for p in pairs], jnp.int32), k)
    ui_t = jnp.tile(jnp.asarray([p[1] for p in pairs], jnp.int32), k)
    kk_t = jnp.repeat(jnp.arange(k, dtype=jnp.int32), np_)
    seg = lambda a: a.reshape(k, ns, s, *a.shape[2:])  # noqa: E731
    gi, sv, cn = seg(gidx), seg(svals), seg(cnts)
    xs = (
        gi[kk_t, si_t], sv[kk_t, si_t], cn[kk_t, si_t],
        gi[kk_t, ui_t], sv[kk_t, ui_t], cn[kk_t, ui_t],
        (si_t == ui_t).astype(jnp.int32)[:, None], kk_t,
    )

    kernel = functools.partial(
        _gram_kernel, s=s, w_nnz=w_r, sig_eff=float(sig_eff),
        frozen=frozen, lanes_out=lanes_out,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((n_dblk, 2 * LANES), lambda i, *_: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((s, lanes_out), lambda i, *_: (0, 0)),
            pl.BlockSpec((1, lanes_out), lambda i, *_: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((n_dblk, LANES), dtype)],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((s, lanes_out), dtype),
            jax.ShapeDtypeStruct((1, lanes_out), dtype),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )

    def body(carry, xs_p):
        si, sv_, sc, pi_, pv, pc, dg, kp = xs_p
        wd_k = jax.lax.dynamic_index_in_dim(wd, kp, axis=0, keepdims=False)
        g_tile, mb_tile = call(si, sv_, sc, pi_, pv, pc, dg, wd_k)
        return carry, (g_tile, mb_tile)

    _, (gtiles, mbtiles) = jax.lax.scan(body, jnp.int32(0), xs)
    gtiles = gtiles[..., :s].reshape(k, np_, s, s)
    mbtiles = mbtiles[:, 0, :s].reshape(k, np_, s)

    mb = jnp.zeros((k, b), dtype)
    gram = None if frozen else jnp.zeros((b, k, b), dtype)
    for p, (si, ui) in enumerate(pairs):
        if si == ui:
            mb = mb.at[:, si * s:(si + 1) * s].set(mbtiles[:, p])
        if not frozen:
            gram = gram.at[ui * s:(ui + 1) * s, :, si * s:(si + 1) * s].set(
                gtiles[:, p].transpose(1, 0, 2)
            )
    return gram, mb


def _apply_kernel(
    gidx_ref,    # scalar-prefetch: (S, W) int32 segment indices
    svals_ref,   # scalar-prefetch: (S, W) f32 segment values
    cnts_ref,    # scalar-prefetch: (S,) int32 per-row nnz (-1 = pad step)
    coefs_ref,   # scalar-prefetch: (S,) f32 chain Δw coefficients
    wd_in,       # (n_dblk, 2·LANES)
    wd_out,      # (n_dblk, 2·LANES)
    *,
    s: int,
    w_nnz: int,
):
    """Grid (S,) over the segment's rows: Δw lanes += coef_j·x_j as masked
    row updates over row j's nonzeros — the rank-B apply without the dense
    (B, d) tile.  Padded steps carry coef 0 AND cnt -1 (zero trips)."""
    j = pl.program_id(0)
    group = min(GROUP, max(1, w_nnz))
    lane2 = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * LANES), 1)

    @pl.when(j == 0)
    def _init():
        wd_out[...] = wd_in[...]

    cnt = cnts_ref[j]
    coef = coefs_ref[j]
    trips = (jnp.maximum(cnt, 0) + (group - 1)) // group

    def body(g, c):
        base = g * group
        for u in range(group):
            f = gidx_ref[j, base + u]
            fb = f // LANES
            fls = f - fb * LANES
            v = svals_ref[j, base + u]
            row = wd_out[pl.ds(fb, 1)]
            wd_out[pl.ds(fb, 1)] = jnp.where(
                lane2 == fls + LANES, row + coef * v, row
            )
        return c

    jax.lax.fori_loop(0, trips, body, jnp.int32(0))


def sparse_block_apply(
    wd: jax.Array,       # (K, n_dblk, 2·LANES)
    gidx: jax.Array,     # (K, B, W_r) int32
    svals: jax.Array,    # (K, B, W_r)
    cnts: jax.Array,     # (K, B) int32; -1 marks padded steps
    coefs: jax.Array,    # (K, B) chain Δw coefficients
    interpret: bool = False,
):
    """Apply the block's rank-B Δw update into the concatenated [w | Δw]
    array as a sparse scatter — one (shard, row-segment) pallas call per
    scan step, same SMEM segmentation as the Gram."""
    k, b, w_r = gidx.shape
    dtype = wd.dtype
    n_dblk = wd.shape[1]
    s = seg_rows(b, w_r)
    if s <= 0:
        raise ValueError(f"no SMEM-feasible apply segment for B={b}, W={w_r}")
    ns = b // s
    kk_t = jnp.repeat(jnp.arange(k, dtype=jnp.int32), ns)
    ss_t = jnp.tile(jnp.arange(ns, dtype=jnp.int32), k)
    seg = lambda a: a.reshape(k, ns, s, *a.shape[2:])  # noqa: E731
    xs = (
        seg(gidx)[kk_t, ss_t], seg(svals)[kk_t, ss_t],
        seg(cnts)[kk_t, ss_t], seg(coefs.astype(svals.dtype))[kk_t, ss_t],
        kk_t,
    )
    kernel = functools.partial(_apply_kernel, s=s, w_nnz=w_r)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s,),
        in_specs=[pl.BlockSpec((n_dblk, 2 * LANES), lambda i, *_: (0, 0))],
        out_specs=[pl.BlockSpec((n_dblk, 2 * LANES), lambda i, *_: (0, 0))],
    )
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((n_dblk, 2 * LANES), dtype)],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )

    def body(wd_c, xs_p):
        gi, sv, cn, cf, kp = xs_p
        wd_k = jax.lax.dynamic_index_in_dim(wd_c, kp, axis=0, keepdims=False)
        (wd_k2,) = call(gi, sv, cn, cf, wd_k)
        return jax.lax.dynamic_update_index_in_dim(wd_c, wd_k2, kp, 0), None

    wd, _ = jax.lax.scan(body, wd, xs)
    return wd
