"""Pallas TPU kernel for the sequential SDCA inner loop — padded-CSR layout.

The XLA lowering of the sparse inner loop (ops/local_sdca.py with the
padded-CSR row accessors, ops/rows.py:46,53) serializes the per-nonzero
gather into ``Δw``/``w`` and the scatter-add back — measured ~44 µs per
coordinate step at rcv1 scale, plus a ~13 ms/round batched gather to
precompute the round's margins.  This kernel removes both:

- ``w`` and the Δw accumulator live **lane-blocked** in VMEM as
  (ceil(d/128), 128) tiles (d=47K ⇒ ~185 KB each), so a nonzero's
  coordinate read is a dynamic *sublane* slice (legal and cheap) of one
  (1, 128) row + a 128-wide mask pick, and the scatter is a masked (1, 128)
  row update — per nonzero O(128) VPU work regardless of d.
- margins are computed **in-kernel** from the VMEM-resident ``w``
  (``margin = x·w + sig_eff·(x·Δw)``, the same decomposition as
  ops/local_sdca.py ``mode_factors`` with margins0 evaluated on the fly),
  so the per-round whole-shard margins gather disappears.

Addressing constraint: Mosaic has no vector→scalar extraction, so every
dynamic address must come from SMEM.  The sampled rows' **feature indices**
are therefore gathered host^W device-side outside the kernel into a
(K, H, max_nnz) int32 table and scalar-prefetched (SMEM); the row
**values** stay in VMEM — the value of nonzero j is picked vectorially
with a static lane-j mask (j is a Python unroll index), never needed as a
scalar address.

Grid is (K, H): shard-major, steps inner (sequential, the dependency
order).  Padded nonzero slots carry index 0 / value 0 and contribute
exactly 0 to every pick and scatter — no masking needed (same inertness
trick as the XLA path, ops/rows.py:10-11).

Size guards: the SMEM index table is K·H_seg·max_nnz ints and must stay
under ``SMEM_IDX_BUDGET`` (512 KB — the 712 KB full-round rcv1 table
fails Mosaic compilation, so rounds split into SMEM-sized segments with
the lane-blocked Δw/α carried between them); ``sparse_kernel_fits``
checks the VMEM working set (lane-blocked d-vectors + per-shard
vectors).  Oversized configs keep the XLA fori_loop path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.local_sdca import coef_divisor, mode_factors
from cocoa_tpu.ops.pallas_sdca import LANES, check_dtype

ROW_BLOCK = 8          # aligned sublane block for the per-step value row
SMEM_IDX_BUDGET = 512 << 10
VMEM_BUDGET = 12 << 20


def sparse_vmem_estimate(n_shard: int, d: int, max_nnz: int, itemsize: int) -> int:
    """Lane-blocked d-vectors — w (x1), Δw carried input (double-buffered,
    x2), Δw output (double-buffered, x2), Δw scratch (x1), plus slack for
    temporaries (x1) — the per-shard vectors (4 inputs + α output
    double-buffered + α scratch), and the double-buffered (8, max_nnz)
    value block."""
    n_pad = -(-n_shard // LANES) * LANES
    d_pad = -(-d // LANES) * LANES
    return itemsize * (11 * n_pad + 7 * d_pad + 2 * ROW_BLOCK * max_nnz)


def sparse_kernel_fits(k: int, n_shard: int, d: int, max_nnz: int, h: int,
                       itemsize: int) -> bool:
    """VMEM feasibility (the SMEM index-table limit is handled by splitting
    the round into segments — see :func:`pallas_sparse_sdca_round`)."""
    del h
    return (
        segment_len(k, max_nnz) >= 1
        and sparse_vmem_estimate(n_shard, d, max_nnz, itemsize) <= VMEM_BUDGET
    )


def segment_len(k: int, max_nnz: int) -> int:
    """Steps per kernel invocation so the (K, H_seg, max_nnz) int32 SMEM
    feature-index table stays inside the budget."""
    return SMEM_IDX_BUDGET // (4 * k * max(1, max_nnz))


def _kernel(
    idxs_ref,        # scalar-prefetch: (K, H) int32 sampled rows
    gidx_ref,        # scalar-prefetch: (K, H, W) int32 feature indices
    val_ref,         # (1, ROW_BLOCK, W) VMEM: aligned block holding the row
    w_ref,           # (1, n_dblk, LANES) VMEM: lane-blocked w (replicated)
    labels_ref,      # (1, n_blocks, LANES) VMEM
    sqn_ref,         # (1, n_blocks, LANES) VMEM
    alpha_in_ref,    # (1, n_blocks, LANES) VMEM
    dw_in_ref,       # (1, n_dblk, LANES) VMEM: Δw carried from prior segment
    dw_ref,          # out (1, n_dblk, LANES): shard k's lane-blocked Δw
    alpha_ref,       # out (1, n_blocks, LANES)
    dw_acc,          # scratch (n_dblk, LANES)
    alpha_sc,        # scratch (n_blocks, LANES)
    *,
    lam_n: float,
    coef_div: float,
    sig_eff: float,
    qii_factor: float,
    frozen: bool,
    h: int,
    w_nnz: int,
    loss: str,
    smoothing: float,
):
    k_ = pl.program_id(0)
    i = pl.program_id(1)
    idx = idxs_ref[k_, i]

    @pl.when(i == 0)
    def _init_shard():
        dw_acc[...] = dw_in_ref[0]
        alpha_sc[...] = alpha_in_ref[0]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    blk = idx // LANES
    sub_lane = idx - blk * LANES
    sel = lane == sub_lane

    def pick(ref):
        return jnp.sum(jnp.where(sel, ref[0, pl.ds(blk, 1), :], 0.0))

    y = pick(labels_ref)
    sq = pick(sqn_ref)
    a = jnp.sum(jnp.where(sel, alpha_sc[pl.ds(blk, 1), :], 0.0))

    # the sampled row's values: sublane idx % 8 of the aligned value block
    sub = idx - (idx // ROW_BLOCK) * ROW_BLOCK
    val_row = val_ref[0, pl.ds(sub, 1), :]          # (1, W)
    vlane = jax.lax.broadcasted_iota(jnp.int32, val_row.shape, 1)

    # margin = x·w + sig_eff·(x·Δw), one pass over the row's nonzeros; the
    # feature addresses come from SMEM, the values from lane-j masks (j is
    # a Python index).  Padded slots (idx 0, val 0) contribute exactly 0.
    margin = jnp.asarray(0.0, val_row.dtype)
    fblk = []
    fsel = []
    vals = []
    for j in range(w_nnz):
        f = gidx_ref[k_, i, j]
        fb = f // LANES
        fs = lane == (f - fb * LANES)
        vj = jnp.sum(jnp.where(vlane == j, val_row, 0.0))
        fblk.append(fb)
        fsel.append(fs)
        vals.append(vj)
        coord = jnp.sum(jnp.where(fs, w_ref[0, pl.ds(fb, 1), :], 0.0))
        if not frozen:
            coord = coord + sig_eff * jnp.sum(
                jnp.where(fs, dw_acc[pl.ds(fb, 1), :], 0.0)
            )
        margin = margin + vj * coord

    new_a = losses.alpha_step(loss, a, y * margin, sq * qii_factor, lam_n,
                              smoothing=smoothing)
    coef = y * (new_a - a) / coef_div

    # scatter-add coef·x into Δw: one masked (1, 128) row update per nonzero
    for j in range(w_nnz):
        dw_acc[pl.ds(fblk[j], 1), :] = jnp.where(
            fsel[j],
            dw_acc[pl.ds(fblk[j], 1), :] + coef * vals[j],
            dw_acc[pl.ds(fblk[j], 1), :],
        )
    alpha_sc[pl.ds(blk, 1), :] = jnp.where(
        sel, new_a, alpha_sc[pl.ds(blk, 1), :]
    )

    @pl.when(i == h - 1)
    def _flush_shard():
        dw_ref[0] = dw_acc[...]
        alpha_ref[0] = alpha_sc[...]


@functools.partial(
    jax.jit,
    static_argnames=("lam", "n", "mode", "sigma", "interpret", "loss",
                     "smoothing"),
)
def pallas_sparse_sdca_round(
    w: jax.Array,            # (d,) the round's primal vector (replicated)
    alpha: jax.Array,        # (K, n_shard)
    sp_indices: jax.Array,   # (K, n_shard, W) int32 padded-CSR columns
    sp_values: jax.Array,    # (K, n_shard, W) padded-CSR values
    labels: jax.Array,       # (K, n_shard)
    sq_norms: jax.Array,     # (K, n_shard)
    idxs: jax.Array,         # (K, H) int32
    lam: float,
    n: int,
    mode: str = "plus",
    sigma: float = 1.0,
    interpret: bool = False,
    loss: str = "hinge",
    smoothing: float = 1.0,
):
    """One sparse SDCA round for K shards on this chip.  Returns
    (dw, alpha_inner): dw (K, d) unreduced per-shard updates (dense — Δw is
    dense in the reference too, CoCoA.scala:145); alpha_inner (K, n_shard)
    the locally-advanced alpha.  Unlike the dense kernel no margins input is
    needed: the kernel reads x·w from the VMEM-resident w.

    When H exceeds the SMEM index-table budget the round is split into
    segments of :func:`segment_len` steps, each one ``pallas_call``; the
    lane-blocked (Δw, α) carry between segments (a few MB of HBM traffic —
    the table cannot be blocked, scalar-prefetch operands live whole in
    SMEM).  Same math regardless of segmentation.

    Requires n_shard % 8 == 0 (shard_dataset pads to 16).  Inside
    ``shard_map`` run with ``check_vma=False`` (as the chunked driver does).
    """
    k, n_shard, w_nnz = sp_indices.shape
    h = idxs.shape[1]
    d = w.shape[0]
    dtype = w.dtype
    check_dtype(dtype)
    if n_shard % ROW_BLOCK != 0:
        raise ValueError(
            f"n_shard must be a multiple of {ROW_BLOCK}, got {n_shard} "
            f"(shard_dataset pads to 16)"
        )
    sig_eff, qii_factor = mode_factors(mode, sigma)
    h_seg = max(1, segment_len(k, w_nnz))

    # lane-block the per-shard vectors and the d-vectors
    n_pad = -(-n_shard // LANES) * LANES
    pad = [(0, 0), (0, n_pad - n_shard)]
    blocked = lambda v: jnp.pad(v, pad).reshape(k, n_pad // LANES, LANES)  # noqa: E731
    n_blocks = n_pad // LANES
    d_pad = -(-d // LANES) * LANES
    n_dblk = d_pad // LANES
    w_blocked = jnp.pad(w, (0, d_pad - d)).reshape(1, n_dblk, LANES)

    labels_b = blocked(labels)
    sqn_b = blocked(sq_norms)
    alpha_b = blocked(alpha)
    dw_b = jnp.zeros((k, n_dblk, LANES), dtype)
    idxs = idxs.astype(jnp.int32)

    shard_vec = pl.BlockSpec(
        (1, n_blocks, LANES), lambda k_, i_, idxs_, gidx_: (k_, 0, 0)
    )
    dvec_in = pl.BlockSpec(
        (1, n_dblk, LANES), lambda k_, i_, idxs_, gidx_: (0, 0, 0)
    )
    dvec_k = pl.BlockSpec(
        (1, n_dblk, LANES), lambda k_, i_, idxs_, gidx_: (k_, 0, 0)
    )

    for lo in range(0, h, h_seg):
        seg = idxs[:, lo:lo + h_seg]
        h_this = seg.shape[1]
        # the segment's feature indices, gathered into the SMEM prefetch
        # table (addresses must be scalars; Mosaic cannot read them from
        # VMEM)
        gidx = jnp.take_along_axis(
            sp_indices, seg[:, :, None], axis=1
        )  # (K, h_this, W)

        kernel = functools.partial(
            _kernel,
            lam_n=float(lam * n),
            coef_div=float(coef_divisor(mode, lam * n)),
            sig_eff=float(sig_eff),
            qii_factor=float(qii_factor),
            frozen=(mode == "frozen"),
            h=h_this,
            w_nnz=w_nnz,
            loss=losses.validate(loss, smoothing),
            smoothing=float(smoothing),
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(k, h_this),
            in_specs=[
                # the sampled row's values: 8-row aligned block at idx//8*8
                pl.BlockSpec(
                    (1, ROW_BLOCK, w_nnz),
                    lambda k_, i_, idxs_, gidx_: (
                        k_, idxs_[k_, i_] // ROW_BLOCK, 0
                    ),
                ),
                dvec_in,    # w (replicated across shards)
                shard_vec,  # labels
                shard_vec,  # sq_norms
                shard_vec,  # alpha_in
                dvec_k,     # dw_in (carried between segments)
            ],
            out_specs=[dvec_k, shard_vec],
            scratch_shapes=[
                pltpu.VMEM((n_dblk, LANES), dtype),
                pltpu.VMEM((n_blocks, LANES), dtype),
            ],
        )
        dw_b, alpha_b = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((k, n_dblk, LANES), dtype),
                jax.ShapeDtypeStruct((k, n_blocks, LANES), dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary"),
            ),
            interpret=interpret,
        )(seg, gidx, sp_values, w_blocked, labels_b, sqn_b, alpha_b, dw_b)

    alpha_inner = alpha_b.reshape(k, n_pad)[:, :n_shard]
    return dw_b.reshape(k, d_pad)[:, :d], alpha_inner
