"""Full subgradient pass over a shard — DistGD's inner step
(reference: DistGD.scala:67-102).

Unlike SDCA/SGD this has **no sequential dependency**: every example's
subgradient is evaluated against the same frozen w.  That makes it the one
inner solver that vectorizes perfectly — on TPU it is a single masked
matvec pair (margins = X·w, Δw = Xᵀ·coef), which XLA tiles onto the MXU.
The reference's off-by-one (`0 to nLocal` inclusive, DistGD.scala:82, reads
one past the shard) is fixed here — deviation documented in SURVEY.md §2.4.

Per-worker regularizer term −λ·w_init (DistGD.scala:98) is included, so the
K-worker sum subtracts K·λ·w, matching the reference's aggregate exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cocoa_tpu.ops import losses
from cocoa_tpu.ops.rows import shard_margins


def subgradient_pass(w_init: jax.Array, shard: dict, lam: float,
                     loss: str = "hinge", smoothing: float = 1.0) -> jax.Array:
    """Returns this worker's delta_w (DistGD.scala:82-98 semantics,
    generalized to any ops/losses.py loss via its −ℓ'(z) factor)."""
    losses.validate(loss, smoothing)
    labels = shard["labels"]

    margins = shard_margins(w_init, shard)                 # (n_shard,)

    # padded rows have label 0 ⇒ coef 0 ⇒ contribute nothing
    coef = labels * losses.grad_factor(loss, labels * margins,
                                       smoothing=smoothing)

    if "X" in shard:
        dw = coef @ shard["X"]                             # Xᵀ·coef on the MXU
    else:
        flat_idx = shard["sp_indices"].reshape(-1)
        flat_val = (shard["sp_values"] * coef[:, None]).reshape(-1)
        dw = jnp.zeros_like(w_init).at[flat_idx].add(flat_val)
        if "X_hot" in shard:
            # hybrid layout: the hot-panel majority as one MXU matvec,
            # scattered at the (disjoint) hot column ids
            dw = dw.at[shard["hot_cols"]].add(coef @ shard["X_hot"])

    return dw - lam * w_init
